"""The static thread-topology model (PERF.md §26).

One AST pass per file discovers, per class:

* **Thread entry points** — methods handed to ``threading.Thread(
  target=self.m)``, submitted to an executor (``self._ex.submit(
  self.m, ...)``), or escaped as bound-method callbacks (``self.m``
  passed as any call argument: the fleet's ``on_event=self._on_event``
  reader-plane registrations).  Everything else is reachable from the
  implicit ``(caller)`` entry — Python has no privacy, and the
  embedder-mode APIs call underscore methods by contract.
* **The per-class shared-state map** — ``self.<attr>`` writes
  (assignment, augmented assignment, subscript stores, and mutating
  method calls on non-thread-safe containers), attributed to every
  entry point whose intra-class call closure reaches the writing
  method.  An attribute written from ≥ 2 entries is SHARED.
* **Guards** — a write is guarded when it happens lexically under
  ``with self.<lock>:`` (or between explicit ``acquire``/``release``
  on the same block), where ``<lock>`` is an attribute initialized
  from ``threading.Lock``/``RLock``/``Condition``; a method whose
  every intra-class call site holds a lock inherits that lock as its
  *ambient* guard (the one-level interprocedural case: ``_drop_health``
  under ``_health_lock``).  ``queue.Queue``/``threading.Event``/
  ``deque`` attributes are thread-safe channels: calling into them is
  never a shared write (the bounded-queue handoff discipline);
  REBINDING one still is.
* **The lock-acquisition graph** — lock → lock edges from lexical
  nesting plus call edges one level deep (acquire-while-holding
  through ``self.m()``); cycles are findings (GT002).
* **Wait-for self-cycles** — a thread entry that blocks on an
  unbounded ``queue.get()`` whose only in-class producers run on that
  same entry can never be satisfied (GT003): the fleet
  requeue-worker deadlock's distilled shape.

Annotations (the guard grammar, checked not trusted)::

    self._x = 0   # graftrace: guard=_lock   (held by protocol; the
                  #   name must resolve to a real lock attribute)
    self._y = 1   # graftrace: owner=serve   (single-writer claim;
                  #   free-form thread label)

An annotation on an ``__init__`` assignment covers every write of
that attribute; on any other line it covers that line only.  Benign
findings that predate the pass live in ``allowlist.py`` (shrink-only,
one justification per entry — the GL013 grandfather discipline).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from .findings import Finding

#: ``# graftrace: guard=<lock>`` / ``# graftrace: owner=<label>``.
_ANNOTATION_RE = re.compile(
    r"#\s*graftrace:\s*(guard|owner)=([A-Za-z_][A-Za-z0-9_.-]*)"
)

#: Constructor dotted names → attribute kind.
_TYPE_TABLE: Dict[str, str] = {
    "threading.Lock": "lock", "Lock": "lock",
    "threading.RLock": "rlock", "RLock": "rlock",
    "threading.Condition": "lock", "Condition": "lock",
    "threading.Semaphore": "lock", "threading.BoundedSemaphore": "lock",
    "queue.Queue": "queue", "Queue": "queue",
    "queue.SimpleQueue": "queue", "SimpleQueue": "queue",
    "queue.LifoQueue": "queue", "queue.PriorityQueue": "queue",
    "threading.Event": "event", "Event": "event",
    "collections.deque": "deque", "deque": "deque",
    "threading.Thread": "thread", "Thread": "thread",
    "ThreadPoolExecutor": "executor",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "futures.ThreadPoolExecutor": "executor",
}

#: Kinds whose METHOD CALLS are thread-safe channels (never a shared
#: write); rebinding the attribute itself is still a write.
_SAFE_KINDS = frozenset(
    {"lock", "rlock", "queue", "event", "deque", "thread", "executor"}
)

_LOCK_KINDS = frozenset({"lock", "rlock"})

#: Container method calls that mutate the receiver.
_MUTATORS = frozenset({
    "append", "appendleft", "add", "remove", "discard", "pop",
    "popleft", "popitem", "clear", "update", "extend", "insert",
    "setdefault", "sort", "reverse",
})

#: The implicit entry for code reachable from ordinary method calls.
CALLER = "(caller)"


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``attr`` when ``node`` is exactly ``self.attr``."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclass(frozen=True)
class WriteSite:
    """One mutation of ``self.<attr>``."""

    attr: str
    line: int
    col: int
    method: str
    held: FrozenSet[str]
    kind: str  # assign | augassign | mutate | delete


@dataclass(frozen=True)
class QueueOp:
    attr: str
    line: int
    col: int
    method: str
    op: str  # get | put
    blocking: bool  # an unbounded blocking get


@dataclass(frozen=True)
class LockEdge:
    src: str
    dst: str
    line: int
    method: str
    via: str  # "" for lexical nesting, callee name for call edges


@dataclass
class MethodScan:
    name: str
    lineno: int
    writes: List[WriteSite] = field(default_factory=list)
    #: every ``self.m(...)`` call: (callee, line, locks held there)
    call_sites: List[Tuple[str, int, FrozenSet[str]]] = field(
        default_factory=list
    )
    #: every lock this method acquires (lexically, anywhere)
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    #: lexical lock nesting edges recorded during the scan
    nest_edges: List[LockEdge] = field(default_factory=list)
    queue_ops: List[QueueOp] = field(default_factory=list)

    @property
    def calls(self) -> Set[str]:
        return {callee for callee, _line, _held in self.call_sites}


@dataclass
class ClassModel:
    """Everything graftrace knows about one class."""

    name: str
    path: str
    lineno: int
    #: attr -> kind from _TYPE_TABLE (any method's ``self.x = Ctor()``)
    attr_kinds: Dict[str, str] = field(default_factory=dict)
    #: attr -> __init__ assignment line (attr-level annotations)
    decl_lines: Dict[str, int] = field(default_factory=dict)
    methods: Dict[str, MethodScan] = field(default_factory=dict)
    #: entry method name -> kind (thread | worker | callback)
    entries: Dict[str, str] = field(default_factory=dict)

    # -- derived (filled by finalize) ----------------------------------
    #: entry name (incl. CALLER) -> reachable method set
    reach: Dict[str, Set[str]] = field(default_factory=dict)
    #: attr -> entry names that write it (outside __init__)
    writers: Dict[str, Set[str]] = field(default_factory=dict)
    shared: Set[str] = field(default_factory=set)
    lock_edges: List[LockEdge] = field(default_factory=list)
    #: shared attr -> "guard=x"/"owner=y" labels covering its writes
    #: (filled by build_class_models; the topology report renders these
    #: so a declared single-writer never looks like an unguarded one)
    declared: Dict[str, str] = field(default_factory=dict)

    @property
    def lock_attrs(self) -> Set[str]:
        return {
            a for a, k in self.attr_kinds.items() if k in _LOCK_KINDS
        }

    def finalize(self) -> None:
        """Compute reachability, writer attribution, shared set, and
        the lock graph (lexical nesting + one-level call edges)."""
        graph = {m: s.calls for m, s in self.methods.items()}

        def closure(roots: Set[str]) -> Set[str]:
            seen: Set[str] = set()
            todo = [r for r in roots if r in self.methods]
            while todo:
                m = todo.pop()
                if m in seen:
                    continue
                seen.add(m)
                todo.extend(
                    c for c in graph.get(m, ()) if c in self.methods
                )
            return seen

        for entry in self.entries:
            self.reach[entry] = closure({entry})
        caller_roots = {
            m for m in self.methods
            if m not in self.entries and m != "__init__"
        }
        self.reach[CALLER] = closure(caller_roots)

        for entry, methods in self.reach.items():
            for m in methods:
                if m == "__init__":
                    continue
                for w in self.methods[m].writes:
                    self.writers.setdefault(w.attr, set()).add(entry)
        self.shared = {
            a for a, ents in self.writers.items() if len(ents) >= 2
        }

        locks = self.lock_attrs
        edges: Dict[Tuple[str, str], LockEdge] = {}
        for scan in self.methods.values():
            for e in scan.nest_edges:
                if e.src in locks and e.dst in locks:
                    edges.setdefault((e.src, e.dst), e)
            # Call edges, one level deep: a lock held across
            # ``self.m()`` reaches every lock m acquires lexically.
            for callee, line, held in scan.call_sites:
                target = self.methods.get(callee)
                if target is None or not held:
                    continue
                for lock in held:
                    if lock not in locks:
                        continue
                    for dst, _dline in target.acquires:
                        if dst in locks:
                            edge = LockEdge(
                                lock, dst, line, scan.name, callee
                            )
                            edges.setdefault((lock, dst), edge)
        self.lock_edges = list(edges.values())

    # -- queries -------------------------------------------------------

    def entries_reaching(self, method: str) -> Set[str]:
        return {
            e for e, methods in self.reach.items() if method in methods
        }

    def all_writes(self, attr: str) -> List[WriteSite]:
        out = [
            w
            for m, scan in self.methods.items()
            if m != "__init__"
            for w in scan.writes
            if w.attr == attr
        ]
        out.sort(key=lambda w: (w.line, w.col))
        return out


def _collect_annotations(source: str) -> Dict[int, Tuple[str, str]]:
    """line -> (kind, value) for ``# graftrace: guard=x / owner=y``.

    A trailing comment annotates its own line; an annotation in a
    comment-only line (or block) annotates the next code line below —
    the readable form for multi-line statements."""
    out: Dict[int, Tuple[str, str]] = {}
    lines = source.splitlines()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _ANNOTATION_RE.search(tok.string)
            if m is None:
                continue
            line = tok.start[0]
            ann = (m.group(1), m.group(2))
            if lines[line - 1].lstrip().startswith("#"):
                # Comment-only line: attach to the code line below.
                j = line
                while j < len(lines) and (
                    not lines[j].strip()
                    or lines[j].lstrip().startswith("#")
                ):
                    j += 1
                out.setdefault(j + 1, ann)
            else:
                out[line] = ann
    except tokenize.TokenError:
        pass
    return out


class _MethodScanner:
    """Scan one method body, tracking held locks block-linearly."""

    def __init__(self, model: ClassModel, scan: MethodScan) -> None:
        self._model = model
        self._scan = scan

    # -- entry ---------------------------------------------------------

    def run(self, fn: ast.FunctionDef) -> None:
        self._block(fn.body, [])

    # -- blocks --------------------------------------------------------

    def _block(self, stmts: Sequence[ast.stmt], held: List[str]) -> None:
        held = list(held)
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                self._with(stmt, held)
            elif isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # Deferred execution: a nested def may run on another
                # thread later, so it inherits NO held locks.
                self._block(stmt.body, [])
            elif isinstance(stmt, ast.ClassDef):
                pass
            elif self._acquire_release(stmt, held):
                pass
            else:
                for expr_node in self._stmt_exprs(stmt):
                    self._expr(expr_node, held)
                self._writes(stmt, held)
                for sub in self._sub_blocks(stmt):
                    self._block(sub, held)

    def _with(self, stmt: ast.With, held: List[str]) -> None:
        acquired: List[str] = []
        for item in stmt.items:
            self._expr(item.context_expr, held + acquired)
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                for outer in held + acquired:
                    self._scan.nest_edges.append(LockEdge(
                        outer, lock, stmt.lineno, self._scan.name, ""
                    ))
                self._scan.acquires.append((lock, stmt.lineno))
                acquired.append(lock)
        self._block(stmt.body, held + acquired)

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self._model.lock_attrs:
            return attr
        # ``with self._x.acquire_timeout():``-style wrappers are out of
        # scope; ``with self._cond:`` is covered by the attr form.
        return None

    def _acquire_release(
        self, stmt: ast.stmt, held: List[str]
    ) -> bool:
        """Handle bare ``self.X.acquire()`` / ``self.X.release()``
        statements (block-linear held tracking)."""
        if not (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
        ):
            return False
        call = stmt.value
        func = call.func
        assert isinstance(func, ast.Attribute)
        attr = _self_attr(func.value)
        if attr is None or attr not in self._model.lock_attrs:
            return False
        if func.attr == "acquire":
            for outer in held:
                self._scan.nest_edges.append(LockEdge(
                    outer, attr, stmt.lineno, self._scan.name, ""
                ))
            self._scan.acquires.append((attr, stmt.lineno))
            held.append(attr)
            return True
        if func.attr == "release":
            if attr in held:
                held.remove(attr)
            return True
        return False

    @staticmethod
    def _sub_blocks(stmt: ast.stmt) -> Iterator[Sequence[ast.stmt]]:
        for name in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, name, None)
            if isinstance(sub, list) and sub and isinstance(
                sub[0], ast.stmt
            ):
                yield sub
        for handler in getattr(stmt, "handlers", ()):
            yield handler.body

    @staticmethod
    def _stmt_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
        """The statement's own expression children (its sub-blocks are
        recursed separately with held-lock tracking)."""
        for name, value in ast.iter_fields(stmt):
            if name in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.AST):
                yield value
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.AST):
                        yield v

    # -- expressions ---------------------------------------------------

    def _expr(self, node: ast.AST, held: List[str]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._call(sub, held)
            elif isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                pass  # nested defs handled at block level; lambdas rare

    def _call(self, call: ast.Call, held: List[str]) -> None:
        func = call.func
        name = dotted_name(func)
        # -- thread / worker entry registration ------------------------
        if name in ("threading.Thread", "Thread"):
            for kw in call.keywords:
                if kw.arg == "target":
                    target = _self_attr(kw.value)
                    if target is not None:
                        self._model.entries.setdefault(target, "thread")
        if isinstance(func, ast.Attribute) and func.attr == "submit" \
                and call.args:
            target = _self_attr(call.args[0])
            if target is not None:
                self._model.entries.setdefault(target, "worker")
        # -- bound-method escapes (callback entries) -------------------
        for arg in list(call.args) + [
            kw.value for kw in call.keywords
        ]:
            target = _self_attr(arg)
            if target is not None and target in self._model.methods:
                # Only methods escape; data attributes are just reads.
                self._model.entries.setdefault(target, "callback")
        # -- queue ops / container mutators on self attrs --------------
        if isinstance(func, ast.Attribute):
            attr = _self_attr(func.value)
            if attr is not None:
                kind = self._model.attr_kinds.get(attr)
                if kind == "queue" and func.attr in (
                    "get", "put", "get_nowait", "put_nowait"
                ):
                    op = "get" if func.attr.startswith("get") else "put"
                    # Blocks forever only when block is (statically)
                    # True AND no timeout is given: get(False) /
                    # get(block=False) / any timeout never deadlock,
                    # and a non-literal block value gets the benefit
                    # of the doubt (false GT003s are lint failures).
                    block_arg: Optional[ast.expr] = (
                        call.args[0] if call.args else None
                    )
                    timeout_arg: Optional[ast.expr] = (
                        call.args[1] if len(call.args) > 1 else None
                    )
                    for kw in call.keywords:
                        if kw.arg == "block":
                            block_arg = kw.value
                        elif kw.arg == "timeout":
                            timeout_arg = kw.value
                    blocks_forever = (
                        block_arg is None
                        or (
                            isinstance(block_arg, ast.Constant)
                            and block_arg.value is True
                        )
                    ) and (
                        timeout_arg is None
                        or (
                            isinstance(timeout_arg, ast.Constant)
                            and timeout_arg.value is None
                        )
                    )
                    blocking = func.attr == "get" and blocks_forever
                    self._scan.queue_ops.append(QueueOp(
                        attr, call.lineno, call.col_offset,
                        self._scan.name, op, blocking,
                    ))
                elif (
                    func.attr in _MUTATORS
                    and kind not in _SAFE_KINDS
                ):
                    self._record_write(
                        attr, call.lineno, call.col_offset, held,
                        "mutate",
                    )
            # -- intra-class call edges --------------------------------
            target = _self_attr(func)
            if target is not None:
                self._scan.call_sites.append(
                    (target, call.lineno, frozenset(held))
                )

    # -- writes --------------------------------------------------------

    def _writes(self, stmt: ast.stmt, held: List[str]) -> None:
        targets: List[ast.AST] = []
        kind = "assign"
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]
        elif isinstance(stmt, ast.AugAssign):
            targets = [stmt.target]
            kind = "augassign"
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
            kind = "delete"
        for target in targets:
            self._target(target, stmt, held, kind)

    def _target(
        self, target: ast.AST, stmt: ast.stmt, held: List[str],
        kind: str,
    ) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._target(elt, stmt, held, kind)
            return
        attr = _self_attr(target)
        if attr is not None:
            self._record_write(
                attr, stmt.lineno, stmt.col_offset, held, kind
            )
            return
        if isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
            if attr is not None and self._model.attr_kinds.get(
                attr
            ) not in _SAFE_KINDS:
                self._record_write(
                    attr, stmt.lineno, stmt.col_offset, held, "mutate"
                )

    def _record_write(
        self, attr: str, line: int, col: int, held: List[str],
        kind: str,
    ) -> None:
        self._scan.writes.append(WriteSite(
            attr, line, col, self._scan.name, frozenset(held), kind
        ))


def _scan_attr_kinds(cls: ast.ClassDef, model: ClassModel) -> None:
    """attr -> kind from ``self.x = Ctor()`` anywhere in the class
    (first binding wins), plus __init__ declaration lines."""
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            value: Optional[ast.expr] = None
            target: Optional[ast.expr] = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if target is None or value is None:
                continue
            attr = _self_attr(target)
            if attr is None:
                continue
            if fn.name == "__init__":
                model.decl_lines.setdefault(attr, node.lineno)
            if isinstance(value, ast.Call):
                ctor = dotted_name(value.func)
                kind = _TYPE_TABLE.get(ctor or "")
                if kind is not None:
                    model.attr_kinds.setdefault(attr, kind)


def build_class_models(
    source: str, path: str
) -> Tuple[List[ClassModel], Dict[int, Tuple[str, str]], ast.Module]:
    """Parse ``source`` (analyzed as ``path``) into per-class models
    plus the file's annotation map and parsed tree (returned so
    callers feeding tree-level checks never parse twice).  Raises
    ``SyntaxError`` on an unparseable file — the CLI reports those as
    hard errors."""
    tree = ast.parse(source, filename=path)
    annotations = _collect_annotations(source)
    models: List[ClassModel] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        model = ClassModel(node.name, path, node.lineno)
        for fn in node.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                model.methods[fn.name] = MethodScan(fn.name, fn.lineno)
        _scan_attr_kinds(node, model)
        for fn in node.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _MethodScanner(model, model.methods[fn.name]).run(fn)
        model.finalize()
        for attr in model.shared:
            decl = annotations.get(model.decl_lines.get(attr, -1))
            labels = [decl] if decl is not None else [
                a for w in model.all_writes(attr)
                if (a := annotations.get(w.line)) is not None
            ]
            if labels:
                model.declared[attr] = ", ".join(
                    sorted({f"{k}={v}" for k, v in labels})
                )
        models.append(model)
    return models, annotations, tree


# ---------------------------------------------------------------------------
# Checks over the model
# ---------------------------------------------------------------------------


def check_shared_writes(
    model: ClassModel, annotations: Dict[int, Tuple[str, str]]
) -> Iterator[Finding]:
    """GT001: every write to a shared attribute needs a guard — a held
    lock, a thread-safe-channel type, or an explicit annotation."""
    for attr in sorted(model.shared):
        decl_ann = annotations.get(model.decl_lines.get(attr, -1))
        if decl_ann is not None:
            ann_kind, ann_value = decl_ann
            if ann_kind == "guard" and ann_value not in model.lock_attrs:
                yield Finding(
                    model.path, model.decl_lines[attr], 0, "GT001",
                    f"{model.name}.{attr}: guard={ann_value!r} names no "
                    f"lock attribute of {model.name} (known: "
                    f"{', '.join(sorted(model.lock_attrs)) or 'none'})",
                    key=f"{model.name}.{attr}",
                )
            continue  # attribute-level annotation covers all writes
        writers = ", ".join(sorted(model.writers.get(attr, ())))
        guarded_sets: List[FrozenSet[str]] = []
        for w in model.all_writes(attr):
            ann = annotations.get(w.line)
            if ann is not None:
                ann_kind, ann_value = ann
                if ann_kind == "guard" and ann_value not in \
                        model.lock_attrs:
                    yield Finding(
                        model.path, w.line, w.col, "GT001",
                        f"{model.name}.{attr}: guard={ann_value!r} "
                        f"names no lock attribute of {model.name}",
                        key=f"{model.name}.{attr}",
                    )
                continue
            held = w.held | _ambient_locks(model, w.method)
            if not held:
                yield Finding(
                    model.path, w.line, w.col, "GT001",
                    f"unguarded write to shared {model.name}.{attr} "
                    f"(written from: {writers}) in {w.method}; hold a "
                    "declared lock, hand off through a queue, or "
                    "annotate '# graftrace: guard=<lock>|owner=<label>'",
                    key=f"{model.name}.{attr}",
                )
            else:
                guarded_sets.append(frozenset(held))
        if len(guarded_sets) >= 2 and not frozenset.intersection(
            *guarded_sets
        ):
            first = model.all_writes(attr)[0]
            locks = sorted({lk for s in guarded_sets for lk in s})
            yield Finding(
                model.path, first.line, first.col, "GT001",
                f"inconsistent guards on shared {model.name}.{attr}: "
                f"writes hold {', '.join(locks)} with no common lock",
                key=f"{model.name}.{attr}",
            )


def _ambient_locks(model: ClassModel, method: str) -> Set[str]:
    """Locks held at EVERY intra-class call site of ``method`` (one
    level deep): a helper only ever called under a lock inherits it.
    A single bare call site (or being a thread entry) clears it."""
    if method in model.entries:
        return set()
    sites: List[FrozenSet[str]] = [
        held
        for scan in model.methods.values()
        for callee, _line, held in scan.call_sites
        if callee == method
    ]
    if not sites:
        return set()
    return set(frozenset.intersection(*sites))


def check_lock_cycles(model: ClassModel) -> Iterator[Finding]:
    """GT002: cycles in the lock-acquisition graph (lexical nesting +
    one-level call edges).  A non-reentrant self-edge is a cycle of
    length one."""
    graph: Dict[str, List[LockEdge]] = {}
    for e in model.lock_edges:
        if e.src == e.dst and model.attr_kinds.get(e.src) == "rlock":
            continue  # reentrant self-acquire is legal
        graph.setdefault(e.src, []).append(e)

    seen_cycles: Set[Tuple[str, ...]] = set()
    path: List[str] = []
    on_path: Set[str] = set()

    def dfs(node: str) -> Iterator[Tuple[List[str], LockEdge]]:
        for edge in graph.get(node, ()):
            if edge.dst in on_path:
                i = path.index(edge.dst)
                yield path[i:] + [edge.dst], edge
                continue
            path.append(edge.dst)
            on_path.add(edge.dst)
            yield from dfs(edge.dst)
            on_path.discard(edge.dst)
            path.pop()

    for start in sorted(graph):
        path[:] = [start]
        on_path = {start}
        for cycle, edge in dfs(start):
            canon = tuple(sorted(set(cycle)))
            if canon in seen_cycles:
                continue
            seen_cycles.add(canon)
            shape = " -> ".join(cycle)
            via = f" (via self.{edge.via}())" if edge.via else ""
            yield Finding(
                model.path, edge.line, 0, "GT002",
                f"lock-order cycle in {model.name}: {shape}{via} — "
                "two threads taking these in opposite orders deadlock",
                key=f"{model.name}:{'|'.join(canon)}",
            )


def check_queue_self_wait(model: ClassModel) -> Iterator[Finding]:
    """GT003: a thread entry blocking on an unbounded ``get()`` of a
    queue whose only in-class producers run on that same entry — the
    wait can never be satisfied (the fleet requeue-worker deadlock
    shape: re-dispatch work must hand off to a DIFFERENT thread than
    the reader that must deliver the ack)."""
    puts: Dict[str, Set[str]] = {}
    gets: List[QueueOp] = []
    for scan in model.methods.values():
        for op in scan.queue_ops:
            if op.op == "put":
                puts.setdefault(op.attr, set()).update(
                    model.entries_reaching(op.method)
                )
            elif op.blocking:
                gets.append(op)
    for op in gets:
        producers = puts.get(op.attr, set())
        if not producers:
            continue  # cross-class producer: unknowable, stay quiet
        for entry in sorted(model.entries_reaching(op.method)):
            if entry == CALLER or entry not in model.entries:
                continue
            if producers <= {entry}:
                yield Finding(
                    model.path, op.line, op.col, "GT003",
                    f"wait-for cycle in {model.name}: entry "
                    f"'{entry}' blocks on {model.name}.{op.attr}."
                    f"get() (in {op.method}) but the only producer "
                    f"of that queue is '{entry}' itself — hand the "
                    "work to a dedicated worker thread instead",
                    key=f"{model.name}.{op.attr}",
                )

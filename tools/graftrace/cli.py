"""graftrace command line: ``python -m tools.graftrace [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/parse error — the contract
``scripts/lint.sh`` and CI key on (same as graftlint/graftaudit).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence

from . import ALL_CHECKS, analyze_paths
from .report import metrics, to_markdown

#: What ``python -m tools.graftrace`` scans with no arguments: the
#: threaded runtime, the chunk-compile ring, and tools/ itself (the
#: interleave harness spawns threads too — the tier eats its own
#: dogfood).
DEFAULT_PATHS = (
    "hashcat_a5_table_generator_tpu/runtime",
    "hashcat_a5_table_generator_tpu/ops/packing.py",
    "tools",
)


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="graftrace",
        description=(
            "Thread-topology & lock-discipline static analysis for the "
            "threaded runtime (shared-write guards, lock-order cycles, "
            "queue wait-for cycles, router passthrough)."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=list(DEFAULT_PATHS),
        help="files or directories to analyze "
             f"(default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument(
        "--select",
        metavar="CODES",
        help="comma-separated check codes to run (default: all)",
    )
    parser.add_argument(
        "--list-checks",
        action="store_true",
        help="print the check table and exit",
    )
    parser.add_argument(
        "--report",
        metavar="PATH",
        help="write the thread-topology markdown report to PATH "
             "('-' for stdout)",
    )
    parser.add_argument(
        "--summary",
        metavar="PATH",
        help="append the topology report + finding counts to PATH "
             "(CI: pass \"$GITHUB_STEP_SUMMARY\")",
    )
    parser.add_argument(
        "--metrics-json",
        metavar="PATH",
        help="write run metrics (classes/entries/shared-attr/finding "
             "counts) as JSON to PATH; CI uploads it as a job artifact",
    )
    parser.add_argument(
        "--no-allowlist",
        action="store_true",
        help="surface grandfathered findings (the shrink-only list in "
             "tools/graftrace/allowlist.py)",
    )
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_checks:
        for code, summary in ALL_CHECKS.items():
            print(f"{code}  {summary}")
        return 0
    select: Optional[List[str]] = None
    if args.select:
        select = [c.strip() for c in args.select.split(",") if c.strip()]
    t0 = time.monotonic()
    try:
        findings, models = analyze_paths(
            args.paths,
            select=select,
            use_allowlist=not args.no_allowlist,
        )
    except ValueError as exc:
        print(f"graftrace: error: {exc}", file=sys.stderr)
        return 2
    except SyntaxError as exc:
        print(f"graftrace: parse error: {exc}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - t0

    report_md = to_markdown(models)
    if args.report == "-":
        print(report_md, end="")
    elif args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            fh.write(report_md)
    if args.summary:
        with open(args.summary, "a", encoding="utf-8") as fh:
            fh.write(report_md)
            fh.write(
                f"\n**graftrace**: {len(findings)} finding(s) over "
                f"{len(models)} classes in {elapsed:.2f}s\n"
            )
            for f in findings:
                fh.write(f"- `{f.render()}`\n")
    if args.metrics_json:
        counts: Dict[str, float] = {
            "findings": len(findings), "elapsed_s": elapsed,
        }
        for code in ALL_CHECKS:
            counts[f"findings_{code.lower()}"] = sum(
                1 for f in findings if f.code == code
            )
        payload = metrics(models, counts)
        with open(args.metrics_json, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")

    try:
        for finding in findings:
            print(finding.render())
    except BrokenPipeError:  # piped into head; keep the exit contract
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
    if findings:
        print(f"graftrace: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""graftrace — thread-topology & lock-discipline static analysis.

The concurrency tier of the repo's static stack (PERF.md §26):
graftlint checks single-file AST hazards, graftaudit checks what XLA
compiles, and graftrace checks what the THREADS do — entry points,
shared attribute writes and their guards, lock-acquisition ordering,
queue-handoff wait cycles, and the serve-protocol/router op diff.

Checks:

* **GT001** — unguarded write to a shared attribute (written from ≥ 2
  thread entry points without a held lock, a thread-safe channel, or a
  ``# graftrace: guard=<lock>|owner=<label>`` annotation)
* **GT002** — cycle in the lock-acquisition graph (lexical nesting +
  one-level call edges)
* **GT003** — wait-for self-cycle: a thread entry blocking on an
  unbounded ``queue.get()`` it is itself the only producer for (the
  fleet requeue-worker deadlock shape)
* **GT004** — serve op without a router decision
  (CONTRIBUTING: router-passthrough-safe)

Typed public API::

    from tools.graftrace import analyze_paths, analyze_sources

    findings, models = analyze_paths(["hashcat_a5_table_generator_tpu/runtime"])

Run as ``python -m tools.graftrace`` (see ``scripts/lint.sh`` layer 5).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tools.graftlint import iter_python_files

from . import allowlist
from .findings import Finding
from .model import ClassModel, build_class_models, check_lock_cycles, \
    check_queue_self_wait, check_shared_writes
from .passthrough import check_passthrough

__all__ = [
    "ALL_CHECKS",
    "Finding",
    "ClassModel",
    "analyze_sources",
    "analyze_paths",
]

#: code -> one-line summary (the ``--list-checks`` table).
ALL_CHECKS: Dict[str, str] = {
    "GT001": "unguarded write to an attribute shared across thread "
             "entry points",
    "GT002": "cycle in the lock-acquisition graph (lexical + one-level "
             "call edges)",
    "GT003": "thread entry blocking on a queue only it produces "
             "(wait-for self-cycle)",
    "GT004": "serve op without a router decision "
             "(router-passthrough-safe)",
}


def _selected(select: Optional[Iterable[str]]) -> List[str]:
    if select is None:
        return list(ALL_CHECKS)
    codes = [c for c in select]
    unknown = [c for c in codes if c not in ALL_CHECKS]
    if unknown:
        raise ValueError(
            f"unknown check code(s): {', '.join(unknown)}"
        )
    return codes


def analyze_sources(
    items: Sequence[Tuple[str, str]],
    *,
    select: Optional[Iterable[str]] = None,
    use_allowlist: bool = True,
) -> Tuple[List[Finding], List[ClassModel]]:
    """Analyze ``(source, path)`` pairs as one program.

    Returns ``(findings, class_models)``; the models feed the topology
    report.  ``use_allowlist=False`` surfaces grandfathered findings
    (the shrink-only test's hook).  Raises ``SyntaxError`` on an
    unparseable file and ``ValueError`` on an unknown check code."""
    codes = _selected(select)
    models: List[ClassModel] = []
    annotations_by_path: Dict[str, Dict[int, Tuple[str, str]]] = {}
    trees: Dict[str, ast.Module] = {}
    for source, path in items:
        file_models, annotations, tree = build_class_models(source, path)
        models.extend(file_models)
        annotations_by_path[path] = annotations
        trees[path] = tree
    findings: List[Finding] = []
    for model in models:
        ann = annotations_by_path.get(model.path, {})
        if "GT001" in codes:
            findings.extend(check_shared_writes(model, ann))
        if "GT002" in codes:
            findings.extend(check_lock_cycles(model))
        if "GT003" in codes:
            findings.extend(check_queue_self_wait(model))
    if "GT004" in codes:
        findings.extend(check_passthrough(trees))
    if use_allowlist:
        findings, _grandfathered = allowlist.split(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings, models


def analyze_paths(
    paths: Sequence[str],
    *,
    select: Optional[Iterable[str]] = None,
    use_allowlist: bool = True,
) -> Tuple[List[Finding], List[ClassModel]]:
    """Analyze every ``.py`` file under ``paths`` as one program."""
    items: List[Tuple[str, str]] = []
    for file_path in iter_python_files(paths):
        with open(file_path, "r", encoding="utf-8") as fh:
            items.append((fh.read(), file_path))
    return analyze_sources(
        items, select=select, use_allowlist=use_allowlist
    )

"""The thread-topology report: threads × shared attrs × guards.

Rendered as markdown for the CI job summary (and ``--report`` locally)
so every PR shows at a glance which classes own threads, what state
they share, and what guards each shared attribute — the review surface
CONTRIBUTING's "declare your shared state" rule points at.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Tuple

from .allowlist import ALLOWLIST
from .model import CALLER, ClassModel, _ambient_locks


def _allowlisted(model: ClassModel, attr: str) -> bool:
    key = f"{model.name}.{attr}"
    path = model.path.replace("\\", "/")
    return any(
        path.endswith(suffix) and k == key
        for (suffix, k) in ALLOWLIST
    )


def _guards_of(model: ClassModel, attr: str) -> str:
    kind = model.attr_kinds.get(attr)
    if kind in ("queue", "event", "deque"):
        return f"channel ({kind})"
    held = [
        sorted(w.held | _ambient_locks(model, w.method))
        for w in model.all_writes(attr)
    ]
    common: List[str] = []
    if held and all(held):
        common = sorted(set(held[0]).intersection(*map(set, held[1:])))
    if common:
        return ", ".join(common)
    # A declared or grandfathered attribute must never render like an
    # unguarded hazard — the job-summary table is the review surface.
    declared = model.declared.get(attr)
    if declared:
        return f"declared {declared}"
    if _allowlisted(model, attr):
        return "allowlisted (allowlist.py)"
    if any(held):
        return "mixed"
    return "—"


def class_rows(model: ClassModel) -> List[Tuple[str, str, str]]:
    """(attr, writers, guard) rows for the class's shared attrs."""
    rows = []
    for attr in sorted(model.shared):
        writers = ", ".join(sorted(model.writers.get(attr, ())))
        rows.append((attr, writers, _guards_of(model, attr)))
    return rows


def to_markdown(models: List[ClassModel]) -> str:
    """The full topology report over every analyzed class that owns a
    thread entry (classes without one are single-threaded from this
    model's point of view and stay out of the table)."""
    lines = ["## graftrace thread topology", ""]
    threaded = [m for m in models if m.entries]
    if not threaded:
        lines.append("_no thread entry points discovered_")
        return "\n".join(lines) + "\n"
    for model in threaded:
        entries = ", ".join(
            f"`{name}` ({kind})"
            for name, kind in sorted(model.entries.items())
        )
        lines.append(f"### `{model.name}` — {model.path}")
        lines.append(f"entries: {entries}, `{CALLER}`")
        lines.append("")
        rows = class_rows(model)
        if rows:
            lines.append("| shared attr | written from | guard |")
            lines.append("| --- | --- | --- |")
            for attr, writers, guard in rows:
                lines.append(f"| `{attr}` | {writers} | {guard} |")
        else:
            lines.append("_no attribute written from ≥ 2 entries_")
        if model.lock_edges:
            edges = ", ".join(
                f"`{e.src}` → `{e.dst}`"
                + (f" (via `{e.via}`)" if e.via else "")
                for e in sorted(
                    model.lock_edges, key=lambda e: (e.src, e.dst)
                )
            )
            lines.append("")
            lines.append(f"lock order: {edges}")
        lines.append("")
    return "\n".join(lines) + "\n"


def metrics(models: List[ClassModel],
            counts: Mapping[str, float]) -> Dict[str, object]:
    """The ``--metrics-json`` payload (the graftaudit artifact shape:
    plain gauges a dashboard can diff across runs)."""
    threaded = [m for m in models if m.entries]
    return {
        "graftrace": {
            "classes_analyzed": len(models),
            "classes_threaded": len(threaded),
            "thread_entries": sum(len(m.entries) for m in threaded),
            "shared_attrs": sum(len(m.shared) for m in threaded),
            "lock_edges": sum(len(m.lock_edges) for m in models),
            **counts,
        }
    }

"""GT004: the CONTRIBUTING "router-passthrough-safe" rule, mechanized.

The fleet router fronts engines with the engine's own JSONL protocol,
so every op the engine session handles needs a ROUTER DECISION: either
the router handles/forwards it explicitly (an ``op == "x"`` branch in
``_RouterSession._handle``) or it is declared in the router module's
``ROUTER_PASSTHROUGH_OPS`` frozenset (ops that are id-carrying and
router-state-free by construction, forwarded by the unknown-op
fallback).  A new serve op added to ``_JsonlSession._handle`` without
either is a lint failure — the prose rule becomes a diff gate.

Both op tables are extracted from the AST: every string constant
compared against the ``op`` variable (``op == "x"``, ``op in ("a",
"b")``) inside each class's ``_handle`` method.  The check runs only
when the analyzed file set contains BOTH classes (scanning ``tools/``
alone skips it); fixtures feed miniature twin classes under virtual
paths through the same extraction.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .findings import Finding

#: The serve-session class whose ``_handle`` defines the op table.
ENGINE_SESSION = "_JsonlSession"
#: The router-session class whose ``_handle`` must decide each op.
ROUTER_SESSION = "_RouterSession"
#: Module-level declaration of deliberately-passed-through ops.
PASSTHROUGH_DECL = "ROUTER_PASSTHROUGH_OPS"


def _handle_ops(cls: ast.ClassDef) -> Tuple[Set[str], Optional[int]]:
    """String constants compared against ``op`` in ``_handle``."""
    ops: Set[str] = set()
    line: Optional[int] = None
    for fn in cls.body:
        if not isinstance(fn, ast.FunctionDef) or fn.name != "_handle":
            continue
        line = fn.lineno
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            sides: List[ast.expr] = [node.left, *node.comparators]
            if not any(
                isinstance(s, ast.Name) and s.id == "op" for s in sides
            ):
                continue
            for s in sides:
                if isinstance(s, ast.Constant) and isinstance(
                    s.value, str
                ):
                    ops.add(s.value)
                elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                    ops.update(
                        e.value for e in s.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    )
    return ops, line


def _declared_passthrough(tree: ast.Module) -> Set[str]:
    for node in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None or not any(
            isinstance(t, ast.Name) and t.id == PASSTHROUGH_DECL
            for t in targets
        ):
            continue
        try:
            out = ast.literal_eval(
                value.args[0] if isinstance(value, ast.Call)
                and value.args else value
            )
        except (ValueError, TypeError):
            return set()
        return {op for op in out if isinstance(op, str)}
    return set()


def check_passthrough(
    trees: Dict[str, ast.Module]
) -> Iterator[Finding]:
    """Diff the engine session's op table against the router's
    handled + declared-passthrough set (GT004)."""
    engine: Optional[Tuple[str, ast.ClassDef]] = None
    router: Optional[Tuple[str, ast.ClassDef]] = None
    for path, tree in sorted(trees.items()):
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                if node.name == ENGINE_SESSION and engine is None:
                    engine = (path, node)
                elif node.name == ROUTER_SESSION and router is None:
                    router = (path, node)
    if engine is None or router is None:
        return  # partial file set: the diff needs both sides
    engine_ops, engine_line = _handle_ops(engine[1])
    router_ops, _router_line = _handle_ops(router[1])
    router_ops |= _declared_passthrough(trees[router[0]])
    for op in sorted(engine_ops - router_ops):
        yield Finding(
            engine[0], engine_line or engine[1].lineno, 0, "GT004",
            f"serve op {op!r} ({ENGINE_SESSION}._handle) has no router "
            f"decision: handle it in {ROUTER_SESSION}._handle or "
            f"declare it in {PASSTHROUGH_DECL} "
            "(CONTRIBUTING: router-passthrough-safe)",
            key=f"op:{op}",
        )

"""Deterministic-interleaving scheduler for the threaded runtime's
race-window tests (PERF.md §26, part B of the graftrace tier).

The static model proves guard DISCIPLINE; this harness makes the known
race WINDOWS replayable.  It rides the existing fault seam: every
instrumented yield point in the runtime already calls

    if faults.ACTIVE is not None:
        faults.ACTIVE.fire("<point>")

so installing an :class:`Interleaver`'s plan turns those same named
points into schedule gates — no new production hooks, and an unarmed
run keeps the one-``None``-check hot path.

Two modes:

* **Breakpoint mode** (fully deterministic — the race-window tests):
  ``hold(point)`` parks every thread that arrives at the point;
  ``await_arrival`` observes the parked thread; the test then runs the
  racing operation and ``release``/``release_all`` resumes.  The
  interleaving is an explicit program, not a sleep race.
* **Seeded-governor mode** (the schedule sweeps): ``auto(seed)``
  releases parked threads one at a time in an order drawn from a
  seeded RNG over the deterministically-sorted parked set.  The seed
  replays the governor's CHOICES; tests assert invariants (byte
  parity, settled states), never exact schedules.

Parks are bounded (``park_timeout_s``): an orphaned gate times out and
the thread proceeds, recording the timeout in :attr:`timeouts` so a
test that forgot to release fails loudly instead of hanging tier-1.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Set, Tuple

from hashcat_a5_table_generator_tpu.runtime import faults


class _Plan(faults.FaultPlan):
    """A rule-less FaultPlan whose only effect is gating arrivals."""

    def __init__(self, interleaver: "Interleaver") -> None:
        super().__init__([], seed=0)
        self._interleaver = interleaver

    def fire(self, point: str) -> None:  # pragma: no cover - trivial
        self._interleaver._arrive(point)


class Interleaver:
    """Schedule gates over the fault-injection points.

    Use as a context manager: entering installs the plan process-wide
    (restoring whatever was armed before on exit) and exiting stops
    the governor and releases every parked thread — a failing test
    never strands runtime threads."""

    def __init__(self, *, park_timeout_s: float = 30.0) -> None:
        self._park_timeout_s = float(park_timeout_s)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._held: Set[str] = set()
        #: parked threads: (point, ticket) -> release event
        self._parked: Dict[Tuple[str, int], threading.Event] = {}
        self._tickets = 0
        self._closing = False
        self._governor: Optional[threading.Thread] = None
        self._governor_stop = threading.Event()
        #: every arrival, in order — the replay log tests assert on.
        self.arrivals: List[Tuple[str, int]] = []
        #: parks that timed out (a test bug: assert this stays empty).
        self.timeouts: List[Tuple[str, int]] = []
        self._armed: Optional[faults.armed] = None

    # -- context management --------------------------------------------

    def __enter__(self) -> "Interleaver":
        if self._closing:
            # One-shot by design: after stop() the _closing latch makes
            # _arrive a pass-through, so a reused instance would run
            # UNSCHEDULED and pass vacuously — fail loudly instead.
            raise RuntimeError(
                "Interleaver is one-shot; create a new instance per "
                "'with' block"
            )
        self._armed = faults.armed(_Plan(self))
        self._armed.__enter__()
        return self

    def __exit__(self, *exc: object) -> None:
        self.stop()
        assert self._armed is not None
        self._armed.__exit__(*exc)

    def stop(self) -> None:
        """Stop the governor and release everything parked."""
        self._governor_stop.set()
        with self._cond:
            self._closing = True
            self._held.clear()
            for ev in self._parked.values():
                ev.set()
            self._cond.notify_all()
        if self._governor is not None:
            self._governor.join(timeout=5.0)
            self._governor = None

    # -- breakpoint mode -----------------------------------------------

    def hold(self, point: str) -> None:
        """Park every subsequent arrival at ``point``."""
        if point not in faults.POINTS:
            raise ValueError(
                f"unknown interleave point {point!r} "
                f"(want one of {', '.join(sorted(faults.POINTS))})"
            )
        with self._cond:
            self._held.add(point)

    def release(self, point: str, n: int = 1) -> int:
        """Resume up to ``n`` threads parked at ``point`` (oldest
        first); returns how many were resumed.  The point stays held
        for FUTURE arrivals — drop the gate with :meth:`unhold`."""
        with self._cond:
            # A released thread stays parked until it wakes and pops
            # itself; skip already-set events so back-to-back releases
            # resume DISTINCT threads instead of double-counting one.
            keys = sorted(
                (k for k in self._parked
                 if k[0] == point and not self._parked[k].is_set()),
                key=lambda k: k[1],
            )[: max(0, int(n))]
            for key in keys:
                self._parked[key].set()
            return len(keys)

    def release_all(self, point: Optional[str] = None) -> int:
        """Resume every thread parked at ``point`` (or anywhere)."""
        with self._cond:
            keys = [
                k for k in self._parked
                if (point is None or k[0] == point)
                and not self._parked[k].is_set()
            ]
            for key in keys:
                self._parked[key].set()
            return len(keys)

    def unhold(self, point: str) -> None:
        """Drop the gate: future arrivals pass through (threads
        already parked stay parked until released)."""
        with self._cond:
            self._held.discard(point)

    def parked(self, point: Optional[str] = None) -> int:
        with self._cond:
            return sum(
                1 for k in self._parked
                if point is None or k[0] == point
            )

    def await_arrival(self, point: str, *, count: int = 1,
                      timeout: float = 20.0) -> int:
        """Block until ``count`` threads are parked at ``point``;
        returns the parked count (raises on timeout — a schedule test
        must never silently degrade into the sleep-and-hope it
        replaces)."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: sum(
                    1 for k in self._parked if k[0] == point
                ) >= count,
                timeout=timeout,
            )
            got = sum(1 for k in self._parked if k[0] == point)
        if not ok:
            raise TimeoutError(
                f"no arrival at {point!r} within {timeout:g}s "
                f"(parked: {got})"
            )
        return got

    # -- seeded-governor mode ------------------------------------------

    def auto(self, seed: int, *, quantum_s: float = 0.02) -> None:
        """Start the seeded governor: whenever threads are parked, one
        (chosen by the seeded RNG over the sorted parked set) is
        released per ``quantum_s`` tick.  The seed replays the
        governor's choices."""
        if self._governor is not None:
            raise RuntimeError("governor already running")
        rng = random.Random(int(seed))
        self._governor_stop.clear()

        def govern() -> None:
            while not self._governor_stop.wait(quantum_s):
                with self._cond:
                    keys = sorted(
                        k for k in self._parked
                        if not self._parked[k].is_set()
                    )
                    if not keys:
                        continue
                    key = keys[rng.randrange(len(keys))]
                    self._parked[key].set()

        self._governor = threading.Thread(
            target=govern, name="graftrace-governor", daemon=True
        )
        self._governor.start()

    # -- the gate (called from runtime threads via the plan) -----------

    def _arrive(self, point: str) -> None:
        with self._cond:
            if self._closing or point not in self._held:
                return
            ticket = self._tickets
            self._tickets += 1
            self.arrivals.append((point, ticket))
            ev = threading.Event()
            self._parked[(point, ticket)] = ev
            self._cond.notify_all()
        try:
            if not ev.wait(self._park_timeout_s):
                with self._cond:
                    self.timeouts.append((point, ticket))
        finally:
            with self._cond:
                self._parked.pop((point, ticket), None)
                self._cond.notify_all()

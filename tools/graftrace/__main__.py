"""``python -m tools.graftrace`` entry point."""

import sys

from .cli import main

sys.exit(main())

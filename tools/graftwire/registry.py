"""The declared wire registry, extracted — never imported.

graftwire reads ``runtime/protocol.py`` the same way graftaudit reads
kernels and graftrace reads thread entry points: via AST.  The registry
literals (``PROTOCOL_VERSION``, ``WIRE_OPS``, ``WIRE_EVENTS``,
``CHECKPOINT_WIRE``) are pure by contract, so ``ast.literal_eval``
recovers exactly what the runtime declares without executing (or even
being able to import) the package — the CI job runs on a bare checkout
with no JAX.

The same module owns the PROTOCOL.json pin discipline (the
KERNEL_BUDGETS pattern): :func:`diff_pin` classifies every change as an
addition or a removal/rename, and :func:`check_bump` enforces the
version rule — additions need a minor ``PROTOCOL_VERSION`` bump,
removals/renames a major one.
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

#: Module-level names that make a scanned file a registry source.
REGISTRY_NAMES = (
    "PROTOCOL_VERSION", "WIRE_OPS", "WIRE_EVENTS", "CHECKPOINT_WIRE",
)

#: Where the shipped registry and its pin live, relative to the repo
#: root (``tools/graftwire/registry.py`` -> two parents up).
REPO_ROOT = Path(__file__).resolve().parents[2]
REGISTRY_REL = "hashcat_a5_table_generator_tpu/runtime/protocol.py"
PIN_REL = "PROTOCOL.json"


@dataclass
class Registry:
    """The extracted wire contract (pure data, JSON-serializable)."""

    version: str
    ops: Dict[str, Dict[str, Any]]
    events: Dict[str, Dict[str, Any]]
    checkpoint: Dict[str, Any] = field(default_factory=dict)
    path: str = ""

    def fields_of(self, kind: str, name: str) -> Optional[Tuple[str, ...]]:
        """required+optional of one op/event; None when undeclared."""
        spec = (self.ops if kind == "op" else self.events).get(name)
        if spec is None:
            return None
        return tuple(spec.get("required", ())) + tuple(
            spec.get("optional", ())
        )


def is_registry_source(tree: ast.Module) -> bool:
    """Whether a module declares the registry (defines ``WIRE_OPS``)."""
    for node in tree.body:
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        if any(
            isinstance(t, ast.Name) and t.id == "WIRE_OPS"
            for t in targets
        ):
            return True
    return False


def extract_registry(tree: ast.Module, path: str) -> Optional[Registry]:
    """Literal-eval the registry assignments out of one module.

    Returns None when the module declares no complete registry; raises
    :class:`ValueError` when it declares one that is not a pure
    literal (the module contract graftwire exists to keep honest)."""
    found: Dict[str, Any] = {}
    for node in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id in REGISTRY_NAMES:
                try:
                    found[t.id] = ast.literal_eval(value)
                except (ValueError, TypeError) as exc:
                    raise ValueError(
                        f"{path}: registry literal {t.id} is not pure "
                        f"(ast.literal_eval failed: {exc})"
                    ) from None
    if "WIRE_OPS" not in found or "WIRE_EVENTS" not in found:
        return None
    return Registry(
        version=str(found.get("PROTOCOL_VERSION", "0.0")),
        ops=found["WIRE_OPS"],
        events=found["WIRE_EVENTS"],
        checkpoint=found.get("CHECKPOINT_WIRE", {}),
        path=path,
    )


def load_repo_registry() -> Registry:
    """Parse the shipped ``runtime/protocol.py`` (AST only)."""
    path = REPO_ROOT / REGISTRY_REL
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    reg = extract_registry(tree, str(path))
    if reg is None:
        raise ValueError(f"{path}: no wire registry declared")
    return reg


# ---------------------------------------------------------------------------
# The PROTOCOL.json pin
# ---------------------------------------------------------------------------


def registry_to_pin(reg: Registry) -> Dict[str, Any]:
    """The JSON document ``--update-protocol`` writes and GW006 diffs."""
    return {
        "protocol_version": reg.version,
        "ops": reg.ops,
        "events": reg.events,
        "checkpoint": reg.checkpoint,
    }


def load_pin(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        pin = json.load(fh)
    if not isinstance(pin, dict):
        raise ValueError(f"{path}: pin must be a JSON object")
    return pin


@dataclass(frozen=True)
class PinChange:
    """One classified difference between the pin and the live registry.

    ``severity`` drives the bump rule: ``addition`` (new op/event/
    field) needs a minor bump, ``removal`` (dropped or renamed — a
    rename IS a removal plus an addition) a major one, ``metadata``
    (note/route/handlers wording) any re-pin."""

    severity: str  # "addition" | "removal" | "metadata"
    kind: str      # "op" | "event" | "checkpoint" | "version"
    name: str
    detail: str


def _diff_family(
    kind: str,
    pinned: Dict[str, Any],
    live: Dict[str, Any],
) -> List[PinChange]:
    changes: List[PinChange] = []
    for name in sorted(set(pinned) - set(live)):
        changes.append(PinChange("removal", kind, name,
                                 f"{kind} {name!r} removed"))
    for name in sorted(set(live) - set(pinned)):
        changes.append(PinChange("addition", kind, name,
                                 f"{kind} {name!r} added"))
    for name in sorted(set(pinned) & set(live)):
        old, new = pinned[name], live[name]
        for fset in ("required", "optional"):
            o = list(old.get(fset, ()))
            n = list(new.get(fset, ()))
            for f in [x for x in o if x not in n]:
                changes.append(PinChange(
                    "removal", kind, name,
                    f"{kind} {name!r} {fset} field {f!r} removed"))
            for f in [x for x in n if x not in o]:
                changes.append(PinChange(
                    "addition", kind, name,
                    f"{kind} {name!r} {fset} field {f!r} added"))
        meta_keys = (set(old) | set(new)) - {"required", "optional"}
        for mk in sorted(meta_keys):
            if old.get(mk) != new.get(mk):
                changes.append(PinChange(
                    "metadata", kind, name,
                    f"{kind} {name!r} {mk} changed: "
                    f"{old.get(mk)!r} -> {new.get(mk)!r}"))
    return changes


def diff_pin(pin: Dict[str, Any], reg: Registry) -> List[PinChange]:
    """Every difference between the committed pin and the live
    registry, classified for the bump rule.  Empty means in sync."""
    changes: List[PinChange] = []
    live = registry_to_pin(reg)
    changes.extend(_diff_family("op", pin.get("ops", {}), live["ops"]))
    changes.extend(
        _diff_family("event", pin.get("events", {}), live["events"]))
    old_ck, new_ck = pin.get("checkpoint", {}), live["checkpoint"]
    if old_ck != new_ck:
        o = list(old_ck.get("required", ()))
        n = list(new_ck.get("required", ()))
        removed = [f for f in o if f not in n]
        added = [f for f in n if f not in o]
        for f in removed:
            changes.append(PinChange(
                "removal", "checkpoint", f,
                f"checkpoint required field {f!r} removed"))
        for f in added:
            changes.append(PinChange(
                "addition", "checkpoint", f,
                f"checkpoint required field {f!r} added"))
        if old_ck.get("version") != new_ck.get("version"):
            changes.append(PinChange(
                "removal" if removed else "metadata", "checkpoint",
                "version",
                f"checkpoint wire version changed: "
                f"{old_ck.get('version')!r} -> "
                f"{new_ck.get('version')!r}"))
        elif not removed and not added and old_ck != new_ck:
            changes.append(PinChange(
                "metadata", "checkpoint", "note",
                "checkpoint metadata changed"))
    old_v = str(pin.get("protocol_version", "0.0"))
    if old_v != reg.version:
        changes.append(PinChange(
            "metadata", "version", "protocol_version",
            f"PROTOCOL_VERSION {old_v!r} -> {reg.version!r}"))
    return changes


def _parse_version(v: str) -> Tuple[int, int]:
    parts = v.split(".")
    try:
        return int(parts[0]), int(parts[1]) if len(parts) > 1 else 0
    except (ValueError, IndexError):
        raise ValueError(
            f"unparseable PROTOCOL_VERSION {v!r} (want MAJOR.MINOR)"
        ) from None


def check_bump(
    old_version: str,
    new_version: str,
    changes: List[PinChange],
) -> Optional[str]:
    """The ``--update-protocol`` version rule; None when satisfied.

    * any ``removal`` change -> the major must increase;
    * else any ``addition``  -> the minor (or major) must increase;
    * metadata-only          -> any version >= the pinned one."""
    old = _parse_version(old_version)
    new = _parse_version(new_version)
    severities = {c.severity for c in changes
                  if c.kind != "version"}
    if "removal" in severities:
        if new[0] <= old[0]:
            return (
                f"removals/renames need a MAJOR PROTOCOL_VERSION bump "
                f"(pinned {old_version}, live {new_version})"
            )
        return None
    if "addition" in severities:
        if new > old:
            return None
        return (
            f"additions need a MINOR PROTOCOL_VERSION bump "
            f"(pinned {old_version}, live {new_version})"
        )
    if new < old:
        return (
            f"PROTOCOL_VERSION cannot move backwards "
            f"(pinned {old_version}, live {new_version})"
        )
    return None


def write_pin(path: str, reg: Registry) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(registry_to_pin(reg), fh, indent=2, sort_keys=True)
        fh.write("\n")

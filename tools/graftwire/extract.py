"""AST extraction of the wire surfaces graftwire audits.

Four surfaces, extracted per file with no imports (bare-checkout CI):

* **Wire docs** — every dict literal carrying an ``"op"``/``"event"``
  key (the pre-migration emission shape, and any future straggler) and
  every ``protocol.op_*``/``protocol.ev_*`` constructor call (the
  migrated shape).  GW001/GW003 audit these against the registry.
* **Dispatch sites** — string constants compared against a dispatch
  variable (one assigned from ``protocol.doc_op``/``doc_event`` or a
  raw ``.get("op"/"event")``), or compared directly against such a
  call.  GW001 checks the names; GW002 diffs the per-class tables
  against the registry's handler matrix.
* **Handler reads** — fields a declared handler method reads off its
  doc parameter (``doc.get("x")``, ``doc["x"]``, ``"x" in doc``).
  GW004 checks each against the fields some sender can set.
* **Key literals** — raw ``"op"``/``"event"`` STRING KEYS outside the
  registry module: dict keys, ``.get`` first arguments, subscripts,
  containment tests.  GW005 bans these (the GL012 sprawl discipline).
  Op/event VALUE strings (``op == "submit"``) stay legal: graftrace
  GT004 extracts exactly those, and a dispatch table has to spell the
  names somewhere.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

#: The two envelope keys (mirrors ``protocol.K_OP``/``K_EVENT``; kept
#: literal here so graftwire never imports the runtime).
ENVELOPE_KEYS = ("op", "event")

#: Dispatch-read helpers in the registry module: calling one makes the
#: assigned variable a dispatch variable of the given family.
DOC_READERS = {"doc_op": "op", "doc_event": "event"}

#: Constructor-name pattern and the constructors whose suffix is not
#: the doc name verbatim.
_CONSTRUCTOR_RE = re.compile(r"^(op|ev)_([a-z0-9_]+)$")
CONSTRUCTOR_ALIASES = {("event", "error_overloaded"): "error"}

#: Handler methods whose doc-parameter reads GW004 audits, mapped to
#: (field context, which argument is the doc).  ``last`` skips
#: ``self``/``link``-style leading params; ``first`` is for
#: module-level parsers like ``_job_from_doc(doc, ...)``.
HANDLER_METHODS: Dict[str, Tuple[str, str]] = {
    "_handle": ("op", "last"),
    "_on_job_event": ("event", "last"),
    "_job_from_doc": ("submit", "first"),
}


@dataclass(frozen=True)
class WireDoc:
    """One extracted emission (dict literal or constructor call)."""

    path: str
    line: int
    col: int
    kind: str                       # "op" | "event"
    name: Optional[str]             # None when the value is dynamic
    fields: Tuple[str, ...]         # constant string keys present
    open: bool                      # **-spread or non-constant key
    via: str                        # "literal" | "constructor"


@dataclass(frozen=True)
class DispatchSite:
    """One name compared at a dispatch surface."""

    path: str
    line: int
    col: int
    kind: str                       # "op" | "event"
    name: str
    owner: str                      # enclosing Class.method (or func)
    func: str                       # bare function name


@dataclass(frozen=True)
class FieldRead:
    """One field a handler reads off its doc parameter."""

    path: str
    line: int
    col: int
    context: str                    # "op" | "event" | "submit"
    owner: str
    field: str


@dataclass(frozen=True)
class KeyLiteral:
    """One raw envelope-key literal (GW005 material)."""

    path: str
    line: int
    col: int
    key: str                        # "op" | "event"
    detail: str                     # where it appeared


@dataclass
class FileSurfaces:
    """Everything extracted from one file."""

    path: str
    docs: List[WireDoc] = field(default_factory=list)
    dispatches: List[DispatchSite] = field(default_factory=list)
    reads: List[FieldRead] = field(default_factory=list)
    key_literals: List[KeyLiteral] = field(default_factory=list)
    passthrough_ops: Set[str] = field(default_factory=set)
    classes: Dict[str, int] = field(default_factory=dict)
    handler_funcs: Set[str] = field(default_factory=set)


def _const_str(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_name(node: ast.expr) -> Optional[str]:
    """The trailing name of ``f(...)`` / ``mod.f(...)``, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _envelope_get(node: ast.expr) -> Optional[str]:
    """Family of a raw ``X.get("op"/"event", ...)`` call, else None."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "get"
        and node.args
    ):
        key = _const_str(node.args[0])
        if key in ENVELOPE_KEYS:
            return key
    return None


def _dispatch_family(node: ast.expr) -> Optional[str]:
    """Family when ``node`` reads the envelope: a ``doc_op``/
    ``doc_event`` call or a raw ``.get("op"/"event")``."""
    name = _call_name(node)
    if name in DOC_READERS:
        return DOC_READERS[name]
    return _envelope_get(node)


def _compared_strings(node: ast.Compare) -> List[Tuple[str, ast.expr]]:
    """Every string constant on either side of a comparison."""
    out: List[Tuple[str, ast.expr]] = []
    for side in (node.left, *node.comparators):
        s = _const_str(side)
        if s is not None:
            out.append((s, side))
        elif isinstance(side, (ast.Tuple, ast.List, ast.Set)):
            for elt in side.elts:
                s = _const_str(elt)
                if s is not None:
                    out.append((s, elt))
    return out


def _doc_param(fn: ast.FunctionDef, which: str) -> Optional[str]:
    args = [a.arg for a in fn.args.args]
    if args and args[0] in ("self", "cls"):
        args = args[1:]
    if not args:
        return None
    return args[0] if which == "first" else args[-1]


class _Extractor(ast.NodeVisitor):
    def __init__(self, path: str, *, registry_source: bool) -> None:
        self.out = FileSurfaces(path)
        self._path = path
        self._registry_source = registry_source
        self._class_stack: List[str] = []
        self._func_stack: List[str] = []
        #: dispatch vars of the INNERMOST function: name -> family
        self._dispatch_vars: List[Dict[str, str]] = []
        #: doc params of enclosing handler functions: name -> context
        self._doc_params: List[Dict[str, str]] = []

    # -- scope tracking --------------------------------------------------

    def _owner(self) -> str:
        cls = self._class_stack[-1] if self._class_stack else ""
        fn = self._func_stack[-1] if self._func_stack else ""
        return f"{cls}.{fn}" if cls else fn

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.out.classes.setdefault(node.name, node.lineno)
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_function(self, node: ast.FunctionDef) -> None:
        self._func_stack.append(node.name)
        self._dispatch_vars.append({})
        params: Dict[str, str] = {}
        spec = HANDLER_METHODS.get(node.name)
        if spec is not None:
            context, which = spec
            param = _doc_param(node, which)
            if param is not None:
                params = {param: context}
                self.out.handler_funcs.add(node.name)
        self._doc_params.append(params)
        self.generic_visit(node)
        self._doc_params.pop()
        self._dispatch_vars.pop()
        self._func_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function  # type: ignore[assignment]

    def _doc_context(self, node: ast.expr) -> Optional[str]:
        """Handler context when ``node`` is a doc parameter Name."""
        if not isinstance(node, ast.Name):
            return None
        for params in reversed(self._doc_params):
            if node.id in params:
                return params[node.id]
        return None

    # -- wire docs -------------------------------------------------------

    def visit_Dict(self, node: ast.Dict) -> None:
        fields: List[str] = []
        kind: Optional[str] = None
        name: Optional[str] = None
        is_open = False
        for key, value in zip(node.keys, node.values):
            if key is None:          # **spread
                is_open = True
                continue
            k = _const_str(key)
            if k is None:
                is_open = True       # computed key: unknowable field
                continue
            fields.append(k)
            if k in ENVELOPE_KEYS and kind is None:
                kind = k
                name = _const_str(value)
        if kind is not None:
            self.out.docs.append(WireDoc(
                self._path, node.lineno, node.col_offset,
                kind, name, tuple(fields), is_open, "literal",
            ))
            if not self._registry_source:
                self.out.key_literals.append(KeyLiteral(
                    self._path, node.lineno, node.col_offset, kind,
                    "dict key in an inline wire doc",
                ))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn_name = _call_name(node)
        m = _CONSTRUCTOR_RE.match(fn_name or "")
        if m is not None:
            prefix, suffix = m.group(1), m.group(2)
            kind = "op" if prefix == "op" else "event"
            doc_name = CONSTRUCTOR_ALIASES.get((kind, suffix), suffix)
            self.out.docs.append(WireDoc(
                self._path, node.lineno, node.col_offset,
                kind, doc_name, (), False, "constructor",
            ))
        key = _envelope_get(node)
        if key is not None and not self._registry_source:
            self.out.key_literals.append(KeyLiteral(
                self._path, node.lineno, node.col_offset, key,
                f".get({key!r}) read",
            ))
        # handler read: `doc.get("field", ...)` on a doc parameter
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and node.args
        ):
            context = self._doc_context(node.func.value)
            f = _const_str(node.args[0])
            if context is not None and f is not None:
                self.out.reads.append(FieldRead(
                    self._path, node.lineno, node.col_offset,
                    context, self._owner(), f,
                ))
        self.generic_visit(node)

    # -- dispatch --------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        # module-level passthrough declaration (the GT004 anchor)
        if not self._func_stack and not self._class_stack:
            for t in node.targets:
                if (
                    isinstance(t, ast.Name)
                    and t.id == "ROUTER_PASSTHROUGH_OPS"
                ):
                    value = node.value
                    try:
                        ops = ast.literal_eval(
                            value.args[0]
                            if isinstance(value, ast.Call) and value.args
                            else value
                        )
                        self.out.passthrough_ops |= {
                            o for o in ops if isinstance(o, str)
                        }
                    except (ValueError, TypeError):
                        pass
        family = _dispatch_family(node.value)
        if family is not None and self._dispatch_vars:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._dispatch_vars[-1][t.id] = family
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        family: Optional[str] = None
        for side in (node.left, *node.comparators):
            if isinstance(side, ast.Name) and self._dispatch_vars:
                for scope in reversed(self._dispatch_vars):
                    if side.id in scope:
                        family = scope[side.id]
                        break
            if family is None:
                family = _dispatch_family(side)
            if family is not None:
                break
        if family is not None:
            fn = self._func_stack[-1] if self._func_stack else ""
            for name, site in _compared_strings(node):
                self.out.dispatches.append(DispatchSite(
                    self._path, site.lineno, site.col_offset,
                    family, name, self._owner(), fn,
                ))
        # containment test on a raw envelope key: `"op" in doc`
        if (
            not self._registry_source
            and any(isinstance(o, (ast.In, ast.NotIn)) for o in node.ops)
        ):
            key = _const_str(node.left)
            if key in ENVELOPE_KEYS:
                self.out.key_literals.append(KeyLiteral(
                    self._path, node.lineno, node.col_offset, key,
                    f"{key!r} in <doc> containment test",
                ))
        # handler read via containment: `"x" in doc`
        if len(node.ops) == 1 and isinstance(node.ops[0], ast.In):
            context = self._doc_context(node.comparators[0])
            f = _const_str(node.left)
            if context is not None and f is not None:
                self.out.reads.append(FieldRead(
                    self._path, node.lineno, node.col_offset,
                    context, self._owner(), f,
                ))
        self.generic_visit(node)

    # -- handler reads & subscripts --------------------------------------

    def visit_Subscript(self, node: ast.Subscript) -> None:
        key = _const_str(node.slice)
        if key is not None:
            if key in ENVELOPE_KEYS and not self._registry_source:
                self.out.key_literals.append(KeyLiteral(
                    self._path, node.lineno, node.col_offset, key,
                    f"[{key!r}] subscript",
                ))
            context = self._doc_context(node.value)
            if context is not None:
                self.out.reads.append(FieldRead(
                    self._path, node.lineno, node.col_offset,
                    context, self._owner(), key,
                ))
        self.generic_visit(node)

def extract_surfaces(
    tree: ast.Module, path: str, *, registry_source: bool
) -> FileSurfaces:
    """Extract every audited surface from one parsed module."""
    ex = _Extractor(path, registry_source=registry_source)
    ex.visit(tree)
    return ex.out

"""GW001–GW006: the wire-contract checks.

Each check consumes the extracted surfaces (:mod:`.extract`) and the
declared registry (:mod:`.registry`) and yields typed findings — no
printing, no imports of the analyzed package.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Set

from .extract import FileSurfaces
from .findings import Finding
from .registry import PinChange, Registry, diff_pin

#: Role -> the session class whose ``_handle`` must decide each op
#: (the graftrace GT004 constants, generalized into a matrix).
ROLE_CLASSES = {"engine": "_JsonlSession", "router": "_RouterSession"}

#: The router method whose chain must decide every ``dispatch`` event.
EVENT_HANDLER = "_on_job_event"

#: Envelope keys never listed in per-doc field specs.
_ENVELOPE = {"op", "event"}


def _all_fields(reg: Registry, kind: str, names: Set[str]) -> Set[str]:
    """Union of declared fields over ``names`` (+ the envelope)."""
    out: Set[str] = set(_ENVELOPE)
    family = reg.ops if kind != "event" else reg.events
    for name in names:
        spec = family.get(name)
        if spec is None:
            continue
        out.update(spec.get("required", ()))
        out.update(spec.get("optional", ()))
    return out


def check_undeclared(
    surfaces: Sequence[FileSurfaces], reg: Registry
) -> Iterator[Finding]:
    """GW001: an emitted or dispatched op/event the registry never
    declared — the doc would fail ``protocol.validate_doc`` at
    runtime, and no replicated router could route it."""
    for fs in surfaces:
        for doc in fs.docs:
            if doc.name is None:
                continue
            family = reg.ops if doc.kind == "op" else reg.events
            if doc.name not in family:
                via = ("constructor call" if doc.via == "constructor"
                       else "inline doc")
                yield Finding(
                    fs.path, doc.line, doc.col, "GW001",
                    f"emitted {doc.kind} {doc.name!r} ({via}) is not "
                    "declared in the wire registry "
                    "(runtime/protocol.py WIRE_OPS/WIRE_EVENTS)",
                    key=f"{doc.kind}:{doc.name}",
                )
        for site in fs.dispatches:
            family = reg.ops if site.kind == "op" else reg.events
            if site.name not in family:
                yield Finding(
                    fs.path, site.line, site.col, "GW001",
                    f"dispatched {site.kind} {site.name!r} "
                    f"({site.owner}) is not declared in the wire "
                    "registry (runtime/protocol.py)",
                    key=f"{site.kind}:{site.name}",
                )


def check_handler_matrix(
    surfaces: Sequence[FileSurfaces], reg: Registry
) -> Iterator[Finding]:
    """GW002: a declared op with no handler at its receiver role, or a
    declared ``dispatch`` event the router's event chain never decides
    (the router<->engine compatibility matrix generalizing GT004).
    Role checks run only when the role's session class is in the
    analyzed file set (partial scans skip, like GT004)."""
    op_tables: Dict[str, Set[str]] = {}
    class_sites: Dict[str, Any] = {}
    passthrough: Set[str] = set()
    event_chain: Set[str] = set()
    have_event_handler = False
    for fs in surfaces:
        passthrough |= fs.passthrough_ops
        if EVENT_HANDLER in fs.handler_funcs:
            have_event_handler = True
        for site in fs.dispatches:
            cls = site.owner.split(".")[0] if "." in site.owner else ""
            if site.kind == "op" and site.func == "_handle" and cls:
                op_tables.setdefault(cls, set()).add(site.name)
            if site.kind == "event" and site.func == EVENT_HANDLER:
                event_chain.add(site.name)
        for cls, line in fs.classes.items():
            class_sites.setdefault(cls, (fs.path, line))
    for role, cls in sorted(ROLE_CLASSES.items()):
        if cls not in class_sites:
            continue  # partial file set: this role is not on screen
        handled = op_tables.get(cls, set())
        if role == "router":
            handled = handled | passthrough
        path, line = class_sites[cls]
        for name in sorted(reg.ops):
            spec = reg.ops[name]
            if role not in spec.get("handlers", ()):
                continue
            if name not in handled:
                yield Finding(
                    path, line, 0, "GW002",
                    f"declared op {name!r} names {role!r} as a handler "
                    f"but {cls}._handle never decides it "
                    "(fix the handler or the registry's handlers list)",
                    key=f"op:{name}:{role}",
                )
    if have_event_handler:
        path, line = ("", 1)
        for fs in surfaces:
            if EVENT_HANDLER in fs.handler_funcs:
                path, line = fs.path, 1
                break
        for name in sorted(reg.events):
            spec = reg.events[name]
            if spec.get("route") != "dispatch":
                continue
            if name not in event_chain:
                yield Finding(
                    path, line, 0, "GW002",
                    f"declared event {name!r} routes as 'dispatch' but "
                    f"{EVENT_HANDLER} never decides it (handle it, or "
                    "declare its route as passthrough/control/"
                    "synthesized in the registry)",
                    key=f"event:{name}",
                )


def check_required_fields(
    surfaces: Sequence[FileSurfaces], reg: Registry
) -> Iterator[Finding]:
    """GW003: an inline wire doc missing a field its op/event declares
    required (a ``failed`` without ``error``, a ``hit`` without
    ``id``).  Constructor calls are exempt by construction — their
    signatures make required fields unskippable — and ``open`` docs
    (``**``-spread or computed keys) carry fields the AST cannot
    enumerate."""
    for fs in surfaces:
        for doc in fs.docs:
            if doc.via != "literal" or doc.name is None or doc.open:
                continue
            family = reg.ops if doc.kind == "op" else reg.events
            spec = family.get(doc.name)
            if spec is None or spec.get("open"):
                continue
            missing = [
                f for f in spec.get("required", ())
                if f not in doc.fields
            ]
            if missing:
                yield Finding(
                    fs.path, doc.line, doc.col, "GW003",
                    f"{doc.kind} {doc.name!r} doc is missing required "
                    f"field(s): {', '.join(missing)}",
                    key=f"{doc.kind}:{doc.name}",
                )


def check_unset_reads(
    surfaces: Sequence[FileSurfaces], reg: Registry
) -> Iterator[Finding]:
    """GW004: a handler reads a field no sender can set — the field is
    not declared (required or optional) for any op/event the handler
    dispatches.  The read would see its default forever; either the
    registry is missing a field or the handler is reading a ghost."""
    op_tables: Dict[str, Set[str]] = {}
    event_tables: Dict[str, Set[str]] = {}
    for fs in surfaces:
        for site in fs.dispatches:
            table = (op_tables if site.kind == "op" else event_tables)
            table.setdefault(site.owner, set()).add(site.name)
    for fs in surfaces:
        for read in fs.reads:
            if read.context == "submit":
                legal = _all_fields(reg, "op", {"submit"})
            elif read.context == "op":
                names = op_tables.get(read.owner) or set(reg.ops)
                legal = _all_fields(reg, "op", names)
            else:
                names = (event_tables.get(read.owner)
                         or set(reg.events))
                legal = _all_fields(reg, "event", names)
            if read.field not in legal:
                yield Finding(
                    fs.path, read.line, read.col, "GW004",
                    f"handler {read.owner or read.context} reads "
                    f"field {read.field!r} that no declared "
                    f"{'op' if read.context != 'event' else 'event'} "
                    "it dispatches can carry (declare the field in "
                    "runtime/protocol.py or drop the read)",
                    key=f"{read.context}:{read.field}",
                )


def check_key_sprawl(
    surfaces: Sequence[FileSurfaces],
) -> Iterator[Finding]:
    """GW005: a raw ``"op"``/``"event"`` envelope-key literal outside
    the registry module (the GL012 sprawl discipline).  Emissions go
    through the ``protocol`` constructors, dispatch reads through
    ``doc_op``/``doc_event`` — op/event VALUE strings stay legal (the
    dispatch tables graftrace GT004 extracts spell them)."""
    for fs in surfaces:
        for kl in fs.key_literals:
            yield Finding(
                fs.path, kl.line, kl.col, "GW005",
                f"raw envelope key {kl.key!r} ({kl.detail}) outside "
                "runtime/protocol.py — emit via a protocol "
                "constructor, read via protocol.doc_op/doc_event",
                key=f"key:{kl.key}",
            )


def check_pin_drift(
    reg: Registry,
    pin: Optional[Dict[str, Any]],
    pin_path: str,
) -> Iterator[Finding]:
    """GW006: drift between the live registry and the committed
    PROTOCOL.json pin — either direction fails (the KERNEL_BUDGETS
    discipline).  Deliberate changes re-pin via ``python -m
    tools.graftwire --update-protocol``, which also enforces the
    version bump rule."""
    where = reg.path or pin_path
    if pin is None:
        yield Finding(
            where, 1, 0, "GW006",
            f"no protocol pin at {pin_path} — bootstrap it with "
            "python -m tools.graftwire --update-protocol",
            key="pin:missing",
        )
        return
    changes: List[PinChange] = diff_pin(pin, reg)
    for ch in changes:
        yield Finding(
            where, 1, 0, "GW006",
            f"registry drifted from {pin_path}: {ch.detail} "
            "(deliberate? re-pin via --update-protocol, which "
            "enforces the PROTOCOL_VERSION bump rule)",
            key=f"pin:{ch.kind}:{ch.name}",
        )

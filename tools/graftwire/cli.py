"""graftwire command line: ``python -m tools.graftwire [paths...]``.

Exit codes: 0 clean, 1 findings (or a stale README section), 2
usage/parse error — the contract ``scripts/lint.sh`` and CI key on
(same as graftlint/graftaudit/graftrace).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence

from . import ALL_CHECKS, DEFAULT_PIN_PATH, analyze_paths
from .registry import check_bump, diff_pin, write_pin
from .report import drift_table, extract_readme_section, metrics, \
    render_section, replace_readme_section, to_markdown

#: What ``python -m tools.graftwire`` scans with no arguments: the
#: serve/fleet tier that speaks the protocol.  tools/ and tests/ stay
#: out — graftwire's own extraction strings and the suites' hand-rolled
#: docs are not wire emissions.
DEFAULT_PATHS = (
    "hashcat_a5_table_generator_tpu/runtime",
)


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="graftwire",
        description=(
            "Wire-protocol contract audit for the serve/fleet tier "
            "(emitted docs and dispatch sites vs the declared "
            "runtime/protocol.py registry and the PROTOCOL.json pin)."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=list(DEFAULT_PATHS),
        help="files or directories to analyze "
             f"(default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument(
        "--select",
        metavar="CODES",
        help="comma-separated check codes to run (default: all)",
    )
    parser.add_argument(
        "--list-checks",
        action="store_true",
        help="print the check table and exit",
    )
    parser.add_argument(
        "--protocol-json",
        metavar="PATH",
        default=DEFAULT_PIN_PATH,
        help="the committed protocol pin GW006 diffs against "
             "(default: PROTOCOL.json at the repo root)",
    )
    parser.add_argument(
        "--update-protocol",
        action="store_true",
        help="re-pin PROTOCOL.json from the live registry (enforces "
             "the PROTOCOL_VERSION bump rule: additions need a minor "
             "bump, removals/renames a major), then analyze",
    )
    parser.add_argument(
        "--report",
        metavar="PATH",
        help="write the wire-protocol markdown report to PATH "
             "('-' for stdout)",
    )
    parser.add_argument(
        "--check-readme",
        metavar="PATH",
        help="fail (exit 1) when PATH's marker-delimited wire-protocol "
             "section is stale vs the live registry",
    )
    parser.add_argument(
        "--update-readme",
        metavar="PATH",
        help="rewrite PATH's marker-delimited wire-protocol section "
             "from the live registry",
    )
    parser.add_argument(
        "--summary",
        metavar="PATH",
        help="append the protocol report + drift table + finding "
             "counts to PATH (CI: pass \"$GITHUB_STEP_SUMMARY\")",
    )
    parser.add_argument(
        "--metrics-json",
        metavar="PATH",
        help="write run metrics (ops/events/emission/dispatch/finding "
             "counts) as JSON to PATH; CI uploads it as a job artifact",
    )
    parser.add_argument(
        "--no-allowlist",
        action="store_true",
        help="surface grandfathered findings (the shrink-only list in "
             "tools/graftwire/allowlist.py)",
    )
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_checks:
        for code, summary in ALL_CHECKS.items():
            print(f"{code}  {summary}")
        return 0
    select: Optional[List[str]] = None
    if args.select:
        select = [c.strip() for c in args.select.split(",") if c.strip()]
    t0 = time.monotonic()
    try:
        findings, model = analyze_paths(
            args.paths,
            select=select,
            use_allowlist=not args.no_allowlist,
            pin_path=args.protocol_json,
        )
    except ValueError as exc:
        print(f"graftwire: error: {exc}", file=sys.stderr)
        return 2
    except SyntaxError as exc:
        print(f"graftwire: parse error: {exc}", file=sys.stderr)
        return 2

    if args.update_protocol:
        reg = model.registry
        if reg is None:
            print("graftwire: error: no registry to pin",
                  file=sys.stderr)
            return 2
        if model.pin is not None:
            changes = diff_pin(model.pin, reg)
            err = check_bump(
                str(model.pin.get("protocol_version", "0.0")),
                reg.version, changes,
            )
            if err is not None:
                print(f"graftwire: --update-protocol refused: {err}",
                      file=sys.stderr)
                return 2
        write_pin(args.protocol_json, reg)
        print(f"graftwire: pinned protocol {reg.version} -> "
              f"{args.protocol_json}")
        # the fresh pin supersedes the pre-update drift findings
        try:
            findings, model = analyze_paths(
                args.paths,
                select=select,
                use_allowlist=not args.no_allowlist,
                pin_path=args.protocol_json,
            )
        except (ValueError, SyntaxError) as exc:
            print(f"graftwire: error: {exc}", file=sys.stderr)
            return 2
    elapsed = time.monotonic() - t0

    readme_stale = False
    if args.update_readme or args.check_readme:
        reg = model.registry
        if reg is None:
            print("graftwire: error: no registry for the README "
                  "section", file=sys.stderr)
            return 2
        section = render_section(reg)
        readme_path = args.update_readme or args.check_readme
        with open(readme_path, "r", encoding="utf-8") as fh:
            text = fh.read()
        if args.update_readme:
            try:
                updated = replace_readme_section(text, section)
            except ValueError as exc:
                print(f"graftwire: error: {exc}", file=sys.stderr)
                return 2
            with open(readme_path, "w", encoding="utf-8") as fh:
                fh.write(updated)
            print(f"graftwire: wrote wire-protocol section -> "
                  f"{readme_path}")
        else:
            current = extract_readme_section(text)
            if current is None or current.strip() != section.strip():
                readme_stale = True
                print(
                    f"graftwire: {readme_path} wire-protocol section "
                    "is stale — refresh with python -m tools.graftwire "
                    f"--update-readme {readme_path}",
                    file=sys.stderr,
                )

    report_md = to_markdown(model.registry, model.changes)
    if args.report == "-":
        print(report_md, end="")
    elif args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            fh.write(report_md)
    if args.summary:
        with open(args.summary, "a", encoding="utf-8") as fh:
            fh.write(report_md)
            fh.write(drift_table(model.changes))
            fh.write(
                f"\n**graftwire**: {len(findings)} finding(s) over "
                f"{model.n_docs} emissions / {model.n_dispatches} "
                f"dispatch sites in {elapsed:.2f}s\n"
            )
            for f in findings:
                fh.write(f"- `{f.render()}`\n")
    if args.metrics_json:
        counts: Dict[str, float] = {
            "findings": len(findings), "elapsed_s": elapsed,
            "emissions": model.n_docs,
            "dispatch_sites": model.n_dispatches,
            "handler_reads": model.n_reads,
            "pin_changes": len(model.changes),
        }
        for code in ALL_CHECKS:
            counts[f"findings_{code.lower()}"] = sum(
                1 for f in findings if f.code == code
            )
        payload = metrics(model.registry, counts)
        with open(args.metrics_json, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")

    try:
        for finding in findings:
            print(finding.render())
    except BrokenPipeError:  # piped into head; keep the exit contract
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
    if findings or readme_stale:
        n = len(findings) + (1 if readme_stale else 0)
        print(f"graftwire: {n} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""graftwire — wire-protocol contract static analysis.

The protocol tier of the repo's static stack (PERF.md §25–§27):
graftlint checks single-file AST hazards, graftaudit checks what XLA
compiles, graftrace checks what the threads do, and graftwire checks
what goes OVER THE WIRE — every emitted JSONL doc and every dispatch
site in the serve/fleet tier, audited against the single declared
registry in ``runtime/protocol.py`` and the committed ``PROTOCOL.json``
pin.

Checks:

* **GW001** — emitted or dispatched op/event not in the declared
  registry
* **GW002** — declared op with no handler at its receiver role, or a
  ``dispatch`` event the router's event chain never decides (the
  router↔engine compatibility matrix generalizing GT004)
* **GW003** — inline wire doc missing a declared-required field
* **GW004** — handler reads a field no declared sender can set
* **GW005** — raw ``"op"``/``"event"`` envelope-key literal outside
  ``runtime/protocol.py`` (shrink-only grandfather list)
* **GW006** — drift between the live registry and the committed
  ``PROTOCOL.json`` pin (re-pin via ``--update-protocol``, which
  enforces the PROTOCOL_VERSION bump rule)

Typed public API::

    from tools.graftwire import analyze_paths

    findings, model = analyze_paths(
        ["hashcat_a5_table_generator_tpu/runtime"])

Run as ``python -m tools.graftwire`` (see ``scripts/lint.sh`` layer 6).
Stdlib-only: the registry is extracted via AST, never imported.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tools.graftlint import iter_python_files

from . import allowlist
from .checks import check_handler_matrix, check_key_sprawl, \
    check_pin_drift, check_required_fields, check_undeclared, \
    check_unset_reads
from .extract import FileSurfaces, extract_surfaces
from .findings import Finding
from .registry import PIN_REL, PinChange, Registry, REPO_ROOT, \
    diff_pin, extract_registry, is_registry_source, load_pin, \
    load_repo_registry

__all__ = [
    "ALL_CHECKS",
    "Finding",
    "Registry",
    "WireModel",
    "analyze_sources",
    "analyze_paths",
]

#: code -> one-line summary (the ``--list-checks`` table).
ALL_CHECKS: Dict[str, str] = {
    "GW001": "emitted/dispatched op or event not in the declared "
             "registry",
    "GW002": "declared op/event with no handler at its receiver role "
             "(router-engine matrix)",
    "GW003": "inline wire doc missing a declared-required field",
    "GW004": "handler reads a field no declared sender can set",
    "GW005": "raw \"op\"/\"event\" envelope-key literal outside "
             "runtime/protocol.py",
    "GW006": "live registry drifted from the committed PROTOCOL.json "
             "pin",
}

#: The committed pin the repo-default analysis diffs against.
DEFAULT_PIN_PATH = str(REPO_ROOT / PIN_REL)


@dataclass
class WireModel:
    """Everything one analysis extracted (feeds the report)."""

    registry: Optional[Registry]
    surfaces: List[FileSurfaces] = field(default_factory=list)
    pin: Optional[Dict[str, object]] = None
    pin_path: str = ""
    changes: List[PinChange] = field(default_factory=list)

    @property
    def n_docs(self) -> int:
        return sum(len(fs.docs) for fs in self.surfaces)

    @property
    def n_dispatches(self) -> int:
        return sum(len(fs.dispatches) for fs in self.surfaces)

    @property
    def n_reads(self) -> int:
        return sum(len(fs.reads) for fs in self.surfaces)


def _selected(select: Optional[Iterable[str]]) -> List[str]:
    if select is None:
        return list(ALL_CHECKS)
    codes = [c for c in select]
    unknown = [c for c in codes if c not in ALL_CHECKS]
    if unknown:
        raise ValueError(
            f"unknown check code(s): {', '.join(unknown)}"
        )
    return codes


def analyze_sources(
    items: Sequence[Tuple[str, str]],
    *,
    select: Optional[Iterable[str]] = None,
    use_allowlist: bool = True,
    registry: Optional[Registry] = None,
    pin: Optional[Dict[str, object]] = None,
    pin_path: Optional[str] = None,
) -> Tuple[List[Finding], WireModel]:
    """Analyze ``(source, path)`` pairs as one program.

    The registry comes from (first match wins) the ``registry``
    argument, a scanned file that declares ``WIRE_OPS`` (basename
    ``protocol.py`` preferred — fixtures embed miniature registries),
    or the shipped ``runtime/protocol.py``.  ``pin``/``pin_path``
    feed GW006; with neither, the repo's committed ``PROTOCOL.json``
    is used when present.  Returns ``(findings, model)``; raises
    ``SyntaxError`` on an unparseable file and ``ValueError`` on an
    unknown check code or an impure registry literal."""
    codes = _selected(select)
    surfaces: List[FileSurfaces] = []
    scanned_registries: List[Registry] = []
    for source, path in items:
        tree = ast.parse(source, filename=path)
        source_file = is_registry_source(tree)
        if source_file:
            reg = extract_registry(tree, path)
            if reg is not None:
                scanned_registries.append(reg)
        surfaces.append(
            extract_surfaces(tree, path, registry_source=source_file)
        )
    if registry is None and scanned_registries:
        preferred = [r for r in scanned_registries
                     if os.path.basename(r.path) == "protocol.py"]
        registry = (preferred or scanned_registries)[0]
    if registry is None:
        registry = load_repo_registry()

    if pin_path is None:
        pin_path = DEFAULT_PIN_PATH
    if pin is None and os.path.exists(pin_path):
        pin = load_pin(pin_path)
    rel_pin = os.path.basename(pin_path)

    findings: List[Finding] = []
    if "GW001" in codes:
        findings.extend(check_undeclared(surfaces, registry))
    if "GW002" in codes:
        findings.extend(check_handler_matrix(surfaces, registry))
    if "GW003" in codes:
        findings.extend(check_required_fields(surfaces, registry))
    if "GW004" in codes:
        findings.extend(check_unset_reads(surfaces, registry))
    if "GW005" in codes:
        findings.extend(check_key_sprawl(surfaces))
    if "GW006" in codes:
        findings.extend(check_pin_drift(registry, pin, rel_pin))
    if use_allowlist:
        findings, _grandfathered = allowlist.split(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    model = WireModel(
        registry=registry, surfaces=surfaces,
        pin=pin, pin_path=pin_path,
        changes=diff_pin(pin, registry) if pin is not None else [],
    )
    return findings, model


def analyze_paths(
    paths: Sequence[str],
    *,
    select: Optional[Iterable[str]] = None,
    use_allowlist: bool = True,
    registry: Optional[Registry] = None,
    pin: Optional[Dict[str, object]] = None,
    pin_path: Optional[str] = None,
) -> Tuple[List[Finding], WireModel]:
    """Analyze every ``.py`` file under ``paths`` as one program."""
    items: List[Tuple[str, str]] = []
    for file_path in iter_python_files(paths):
        with open(file_path, "r", encoding="utf-8") as fh:
            items.append((fh.read(), file_path))
    return analyze_sources(
        items, select=select, use_allowlist=use_allowlist,
        registry=registry, pin=pin, pin_path=pin_path,
    )

"""The wire-protocol report: registry tables, pin drift, metrics.

``--report`` renders the protocol section (also embedded in README
between the markers below and kept fresh by ``--check-readme`` in CI);
``--summary`` appends it plus the drift table to the CI job summary;
``--metrics-json`` emits the counters CI uploads as an artifact.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from .registry import PinChange, Registry

#: README markers delimiting the rendered section (the region
#: ``--update-readme`` rewrites and ``--check-readme`` verifies).
BEGIN_MARK = "<!-- graftwire:wire-protocol:begin -->"
END_MARK = "<!-- graftwire:wire-protocol:end -->"


def _csv(values: Any) -> str:
    vals = [str(v) for v in (values or ())]
    return ", ".join(f"`{v}`" for v in vals) if vals else "—"


def protocol_tables(reg: Registry) -> str:
    """The op/event/checkpoint tables for one registry."""
    lines: List[str] = []
    lines.append(f"Protocol version **{reg.version}** — declared in "
                 "`runtime/protocol.py`, pinned in `PROTOCOL.json` "
                 "(changes re-pin via `python -m tools.graftwire "
                 "--update-protocol`: additions bump the minor, "
                 "removals/renames the major).")
    lines.append("")
    lines.append("| op | required | optional | handlers |")
    lines.append("|----|----------|----------|----------|")
    for name in sorted(reg.ops):
        spec = reg.ops[name]
        op_cell = f"`{name}`"
        if spec.get("default"):
            op_cell += " (default)"
        lines.append(
            f"| {op_cell} | {_csv(spec.get('required'))} "
            f"| {_csv(spec.get('optional'))} "
            f"| {_csv(spec.get('handlers'))} |"
        )
    lines.append("")
    lines.append("| event | required | optional | emitters | route |")
    lines.append("|-------|----------|----------|----------|-------|")
    for name in sorted(reg.events):
        spec = reg.events[name]
        ev_cell = f"`{name}`"
        if spec.get("open"):
            ev_cell += " (open)"
        lines.append(
            f"| {ev_cell} | {_csv(spec.get('required'))} "
            f"| {_csv(spec.get('optional'))} "
            f"| {_csv(spec.get('emitters'))} "
            f"| {spec.get('route', '—')} |"
        )
    ck = reg.checkpoint
    if ck:
        lines.append("")
        lines.append(
            f"Checkpoint wire doc v{ck.get('version', '?')}: required "
            f"{_csv(ck.get('required'))}; minor-newer docs round-trip "
            "unknown extra fields verbatim."
        )
    lines.append("")
    return "\n".join(lines)


def render_section(reg: Registry) -> str:
    """The marker-delimited README region (heading included)."""
    return (
        f"{BEGIN_MARK}\n"
        "### Wire protocol\n\n"
        f"{protocol_tables(reg)}"
        f"{END_MARK}\n"
    )


def drift_table(changes: Sequence[PinChange]) -> str:
    """The pin-drift table CI publishes to the job summary."""
    if not changes:
        return ("\n**PROTOCOL.json**: in sync with the live "
                "registry.\n")
    lines = ["", "**PROTOCOL.json drift** (GW006):", "",
             "| severity | change |", "|----------|--------|"]
    for ch in changes:
        lines.append(f"| {ch.severity} | {ch.detail} |")
    lines.append("")
    return "\n".join(lines)


def to_markdown(
    reg: Optional[Registry],
    changes: Sequence[PinChange] = (),
) -> str:
    """The full ``--report`` document."""
    if reg is None:
        return "# graftwire\n\nNo wire registry in the analyzed set.\n"
    return (
        "# graftwire — wire-protocol contract\n\n"
        + protocol_tables(reg)
        + drift_table(changes)
    )


def extract_readme_section(text: str) -> Optional[str]:
    """The marker-delimited region of a README, markers included."""
    start = text.find(BEGIN_MARK)
    end = text.find(END_MARK)
    if start < 0 or end < 0 or end < start:
        return None
    return text[start:end + len(END_MARK)] + "\n"


def replace_readme_section(text: str, section: str) -> str:
    """README text with the marker region replaced by ``section``."""
    start = text.find(BEGIN_MARK)
    end = text.find(END_MARK)
    if start < 0 or end < 0 or end < start:
        raise ValueError(
            f"README has no {BEGIN_MARK} .. {END_MARK} region"
        )
    return text[:start] + section.rstrip("\n") + text[end + len(END_MARK):]


def metrics(
    reg: Optional[Registry],
    counts: Dict[str, float],
) -> Dict[str, Any]:
    """The ``graftwire-metrics.json`` payload."""
    payload: Dict[str, Any] = dict(counts)
    if reg is not None:
        payload["protocol_version"] = reg.version
        payload["ops"] = len(reg.ops)
        payload["events"] = len(reg.events)
    return {"graftwire": payload}

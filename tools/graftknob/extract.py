"""AST extraction of the knob surfaces graftknob audits.

Five layer surfaces plus four key sites, extracted per file with no
imports (bare-checkout CI):

* **Env reads** — every string constant that spells an env knob name
  (``A5GEN_*`` / the grandfathered ``A5_NATIVE``): accessor first
  arguments, ``_STEP_ENV_KNOBS``-style tuples, ``os.environ``
  subscripts.  GK001 audits these against the registry's env layer.
* **Config fields** — ``SweepConfig``'s annotated fields with
  const-folded defaults (``1 << 17`` folds to ``131072``).  GK001 +
  GK005.
* **CLI flags** — every ``add_argument`` call inside the four parser
  builder functions, with argparse's effective default normalized
  (``store_true`` without ``default=`` -> ``False``; absent ->
  ``None``).  GK001 + GK005.
* **Serve-doc fields** — the keys of ``_JOB_CONFIG_FIELDS`` (the
  submit-doc ``config`` sub-object; doc-level spec fields are
  graftwire's domain).  GK001.
* **Tune-profile knobs** — the ``PROFILE_KNOBS`` tuple.  GK001.

Key sites (the tokens GK002–GK004 trace declared roles to):

* **Trace keys** — every assignment to ``skey`` inside
  ``Sweep._make_launch`` / ``Sweep._superstep_static``, plus the
  ``_STEP_ENV_KNOBS`` env suffix ``Sweep._get_step`` appends.
* **Fuse key** — ``pack_candidate``'s ``key`` tuple PLUS the tokens of
  every early ``return None`` guard there (a knob may satisfy
  fuse-compat either by joining the key or by gating eligibility).
* **Affinity call** — the ``static_affinity_token(...)`` call inside
  ``affinity_token``: keyword names and value tokens.
* **Fingerprint params** — ``sweep_fingerprint``'s parameter names.

Tokens of an expression are every ``Name`` id, ``Attribute`` attr, and
string-constant value appearing anywhere inside it — deliberately
coarse: the contract is "the key spells this token somewhere", which
survives refactors of HOW the value reaches the tuple while still
failing loudly when it stops being spelled at all.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

#: Env-knob name pattern (mirrors ``env.read_env``'s naming contract;
#: kept literal here so graftknob never imports the runtime).
ENV_NAME_RE = re.compile(r"^(?:A5GEN_[A-Z0-9_]+|A5_NATIVE)$")

#: Functions whose ``skey`` assignments form the step-cache key.
TRACE_FUNCS = ("_make_launch", "_superstep_static")
#: The env suffix ``_get_step`` appends to every step-cache key.
STEP_ENV_NAME = "_STEP_ENV_KNOBS"
#: The packed-dispatch admission function and its key variable.
FUSE_FUNC = "pack_candidate"
FUSE_KEY_NAME = "key"
TRACE_KEY_NAME = "skey"
#: The scheduler-prefix seam: ``affinity_token`` must route every
#: affinity-role knob into this call.
AFFINITY_FUNC = "affinity_token"
AFFINITY_CALL = "static_affinity_token"
#: The resume-identity function whose params GK004 checks.
FINGERPRINT_FUNC = "sweep_fingerprint"
#: The config dataclass and the serve-doc/profile literal anchors.
CONFIG_CLASS = "SweepConfig"
SERVE_FIELDS_NAME = "_JOB_CONFIG_FIELDS"
PROFILE_NAME = "PROFILE_KNOBS"
#: The four argparse builder functions whose flags ARE the cli layer.
PARSER_BUILDERS = (
    "build_parser", "_build_serve_parser", "_build_fleet_parser",
    "_build_tune_parser",
)

#: Sentinel for a default the const-folder cannot evaluate.
UNFOLDABLE = "<unfoldable>"


@dataclass(frozen=True)
class EnvRead:
    """One spelled env-knob name."""

    path: str
    line: int
    col: int
    name: str


@dataclass(frozen=True)
class ConfigField:
    """One annotated ``SweepConfig`` field."""

    path: str
    line: int
    col: int
    name: str
    default: Any                    # folded literal or UNFOLDABLE


@dataclass(frozen=True)
class CliFlag:
    """One ``add_argument`` call inside a parser builder."""

    path: str
    line: int
    col: int
    flags: Tuple[str, ...]
    default: Any                    # argparse-effective, folded
    builder: str


@dataclass(frozen=True)
class SurfaceName:
    """One serve-doc field or tune-profile knob name."""

    path: str
    line: int
    col: int
    name: str


@dataclass(frozen=True)
class KeySite:
    """One key expression (tokens collected, coarse)."""

    path: str
    line: int
    col: int
    func: str
    tokens: FrozenSet[str]


@dataclass
class FileSurfaces:
    """Everything extracted from one file."""

    path: str
    env_reads: List[EnvRead] = field(default_factory=list)
    config_fields: List[ConfigField] = field(default_factory=list)
    cli_flags: List[CliFlag] = field(default_factory=list)
    serve_fields: List[SurfaceName] = field(default_factory=list)
    profile_knobs: List[SurfaceName] = field(default_factory=list)
    trace_sites: List[KeySite] = field(default_factory=list)
    step_env_knobs: List[EnvRead] = field(default_factory=list)
    fuse_key_sites: List[KeySite] = field(default_factory=list)
    fuse_guard_sites: List[KeySite] = field(default_factory=list)
    affinity_sites: List[KeySite] = field(default_factory=list)
    fingerprint_sites: List[KeySite] = field(default_factory=list)
    builders_found: Set[str] = field(default_factory=set)
    has_config_class: bool = False
    has_serve_fields: bool = False
    has_profile_knobs: bool = False


def _const_str(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_name(node: ast.expr) -> Optional[str]:
    """The trailing name of ``f(...)`` / ``mod.f(...)``, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def fold_const(node: Optional[ast.expr]) -> Any:
    """Const-fold a default expression; :data:`UNFOLDABLE` otherwise.

    Handles the shapes the repo actually writes: plain constants,
    unary minus, ``1 << 17`` / ``64 * 1024`` arithmetic, and literal
    tuples/lists of foldable elements."""
    if node is None:
        return UNFOLDABLE
    if isinstance(node, ast.Constant):
        return node.value
    if (isinstance(node, ast.UnaryOp)
            and isinstance(node.op, ast.USub)):
        v = fold_const(node.operand)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return -v
        return UNFOLDABLE
    if isinstance(node, ast.BinOp):
        left, right = fold_const(node.left), fold_const(node.right)
        ok = all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in (left, right)
        )
        if not ok:
            return UNFOLDABLE
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.Mult):
            return left * right
        if (isinstance(node.op, ast.LShift)
                and isinstance(left, int) and isinstance(right, int)):
            return left << right
        return UNFOLDABLE
    if isinstance(node, (ast.Tuple, ast.List)):
        elts = [fold_const(e) for e in node.elts]
        if UNFOLDABLE in elts:
            return UNFOLDABLE
        return list(elts) if isinstance(node, ast.List) else tuple(elts)
    return UNFOLDABLE


def expr_tokens(node: ast.expr) -> FrozenSet[str]:
    """Every Name id, Attribute attr, and str constant inside."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
        elif (isinstance(sub, ast.Constant)
                and isinstance(sub.value, str)):
            out.add(sub.value)
    return frozenset(out)


def _is_return_none(stmt: ast.stmt) -> bool:
    return isinstance(stmt, ast.Return) and (
        stmt.value is None
        or (isinstance(stmt.value, ast.Constant)
            and stmt.value.value is None)
    )


def _assign_names(node: ast.stmt) -> List[ast.Name]:
    if isinstance(node, ast.Assign):
        return [t for t in node.targets if isinstance(t, ast.Name)]
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if isinstance(node.target, ast.Name):
            return [node.target]
    return []


def _assign_value(node: ast.stmt) -> Optional[ast.expr]:
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        return node.value
    if isinstance(node, ast.AnnAssign):
        return node.value
    return None


class _Extractor(ast.NodeVisitor):
    def __init__(self, path: str) -> None:
        self.out = FileSurfaces(path)
        self._path = path
        self._func_stack: List[str] = []

    # -- env names (any string constant spelling one) -------------------

    def visit_Constant(self, node: ast.Constant) -> None:
        if (isinstance(node.value, str)
                and ENV_NAME_RE.fullmatch(node.value)):
            self.out.env_reads.append(EnvRead(
                self._path, node.lineno, node.col_offset, node.value,
            ))

    # -- config fields ---------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if node.name == CONFIG_CLASS:
            self.out.has_config_class = True
            for stmt in node.body:
                if (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)):
                    self.out.config_fields.append(ConfigField(
                        self._path, stmt.lineno, stmt.col_offset,
                        stmt.target.id, fold_const(stmt.value),
                    ))
        self.generic_visit(node)

    # -- functions: builders, key sites, fingerprint ---------------------

    def _visit_function(self, node: ast.FunctionDef) -> None:
        if node.name == FINGERPRINT_FUNC:
            args = node.args
            params = [a.arg for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
            )]
            self.out.fingerprint_sites.append(KeySite(
                self._path, node.lineno, node.col_offset,
                node.name, frozenset(params),
            ))
        if node.name in PARSER_BUILDERS:
            self.out.builders_found.add(node.name)
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and _call_name(sub) == "add_argument"):
                    self._add_argument(sub, node.name)
        if node.name == FUSE_FUNC:
            for sub in ast.walk(node):
                if isinstance(sub, ast.If) and any(
                    _is_return_none(s) for s in sub.body
                ):
                    self.out.fuse_guard_sites.append(KeySite(
                        self._path, sub.lineno, sub.col_offset,
                        node.name, expr_tokens(sub.test),
                    ))
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function  # type: ignore[assignment]

    def _add_argument(self, node: ast.Call, builder: str) -> None:
        flags = tuple(
            s for s in (_const_str(a) for a in node.args)
            if s is not None
        )
        if not flags:
            return
        default: Any = None
        action: Optional[str] = None
        has_default = False
        for kw in node.keywords:
            if kw.arg == "default":
                default = fold_const(kw.value)
                has_default = True
            elif kw.arg == "action":
                action = _const_str(kw.value)
        if not has_default:
            if action == "store_true":
                default = False
            elif action == "store_false":
                default = True
        self.out.cli_flags.append(CliFlag(
            self._path, node.lineno, node.col_offset,
            flags, default, builder,
        ))

    # -- key sites & literal anchors -------------------------------------

    def _handle_assign(self, node: ast.stmt) -> None:
        names = _assign_names(node)
        value = _assign_value(node)
        if value is None or not names:
            return
        in_trace = any(f in TRACE_FUNCS for f in self._func_stack)
        in_fuse = FUSE_FUNC in self._func_stack
        for t in names:
            if t.id == TRACE_KEY_NAME and in_trace:
                fn = next(f for f in reversed(self._func_stack)
                          if f in TRACE_FUNCS)
                self.out.trace_sites.append(KeySite(
                    self._path, node.lineno, node.col_offset,
                    fn, expr_tokens(value),
                ))
            if t.id == FUSE_KEY_NAME and in_fuse:
                self.out.fuse_key_sites.append(KeySite(
                    self._path, node.lineno, node.col_offset,
                    FUSE_FUNC, expr_tokens(value),
                ))
            if t.id == STEP_ENV_NAME and not self._func_stack:
                folded = fold_const(value)
                if isinstance(folded, (tuple, list)):
                    for name in folded:
                        if isinstance(name, str):
                            self.out.step_env_knobs.append(EnvRead(
                                self._path, node.lineno,
                                node.col_offset, name,
                            ))
            if t.id == SERVE_FIELDS_NAME and not self._func_stack:
                if isinstance(value, ast.Dict):
                    self.out.has_serve_fields = True
                    for key in value.keys:
                        k = _const_str(key) if key is not None else None
                        if k is not None:
                            self.out.serve_fields.append(SurfaceName(
                                self._path, key.lineno,
                                key.col_offset, k,
                            ))
            if t.id == PROFILE_NAME and not self._func_stack:
                folded = fold_const(value)
                if isinstance(folded, (tuple, list)):
                    self.out.has_profile_knobs = True
                    for name in folded:
                        if isinstance(name, str):
                            self.out.profile_knobs.append(SurfaceName(
                                self._path, node.lineno,
                                node.col_offset, name,
                            ))

    def visit_Assign(self, node: ast.Assign) -> None:
        self._handle_assign(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._handle_assign(node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._handle_assign(node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if (_call_name(node) == AFFINITY_CALL
                and AFFINITY_FUNC in self._func_stack):
            tokens: Set[str] = set()
            for kw in node.keywords:
                if kw.arg is not None:
                    tokens.add(kw.arg)
                tokens |= expr_tokens(kw.value)
            for a in node.args:
                tokens |= expr_tokens(a)
            self.out.affinity_sites.append(KeySite(
                self._path, node.lineno, node.col_offset,
                AFFINITY_FUNC, frozenset(tokens),
            ))
        self.generic_visit(node)


def extract_surfaces(
    tree: ast.Module, path: str, *, registry_source: bool
) -> FileSurfaces:
    """Extract every audited surface from one parsed module.

    The registry module itself is skipped (its surface SPELLINGS are
    declarations, not reads)."""
    if registry_source:
        return FileSurfaces(path)
    ex = _Extractor(path)
    ex.visit(tree)
    return ex.out

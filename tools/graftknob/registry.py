"""The declared knob registry, extracted — never imported.

graftknob reads ``runtime/knobs.py`` the same way graftwire reads
``runtime/protocol.py``: via AST.  The registry literals
(``KNOBS_VERSION``, ``KNOBS``) are pure by contract, so
``ast.literal_eval`` recovers exactly what the runtime declares
without executing (or even being able to import) the package — the CI
job runs on a bare checkout with no JAX.

The same module owns the KNOBS.json pin discipline (the PROTOCOL.json
pattern): :func:`diff_pin` classifies every change as an addition, a
removal/rename, or metadata, and :func:`check_bump` enforces the
version rule — additions need a minor ``KNOBS_VERSION`` bump,
removals/renames a major one.
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

#: Module-level names that make a scanned file a registry source.
REGISTRY_NAMES = ("KNOBS_VERSION", "KNOBS")

#: The five knob layers and six roles (mirrors ``runtime/knobs.py``;
#: kept literal here so graftknob never imports the runtime — the
#: registry's own LAYERS/ROLES tuples are validated against these).
LAYERS = ("env", "cli", "config", "serve-doc", "tune-profile")
ROLES = ("trace", "fuse-compat", "affinity", "fingerprint",
         "stream-semantics", "host-only")

#: Where the shipped registry and its pin live, relative to the repo
#: root (``tools/graftknob/registry.py`` -> two parents up).
REPO_ROOT = Path(__file__).resolve().parents[2]
REGISTRY_REL = "hashcat_a5_table_generator_tpu/runtime/knobs.py"
PIN_REL = "KNOBS.json"


@dataclass
class Registry:
    """The extracted knob contract (pure data, JSON-serializable)."""

    version: str
    knobs: Dict[str, Dict[str, Any]]
    path: str = ""

    def surfaces_of(self, layer: str) -> Dict[str, str]:
        """``surface spelling -> knob name`` for one layer."""
        out: Dict[str, str] = {}
        for name, spec in self.knobs.items():
            ldecl = spec.get("layers", {}).get(layer)
            if ldecl is None:
                continue
            surface = ldecl.get("surface", name)
            spellings = (
                surface if isinstance(surface, (list, tuple))
                else [surface]
            )
            for s in spellings:
                out[str(s)] = name
        return out

    def declared_default(
        self, name: str, layer: str
    ) -> Tuple[bool, Any]:
        """``(declared?, value)`` of one knob's default at one layer."""
        ldecl = self.knobs.get(name, {}).get("layers", {}).get(layer)
        if ldecl is None or "default" not in ldecl:
            return False, None
        return True, ldecl["default"]

    def role_token(self, name: str, role: str) -> str:
        """The key-site token witnessing ``name`` for ``role``."""
        spec = self.knobs.get(name, {})
        return str(spec.get("keys", {}).get(role, name))

    def role_knobs(self, role: str) -> List[str]:
        """Knob names carrying ``role``, registry order."""
        return [n for n, spec in self.knobs.items()
                if role in spec.get("roles", ())]


def _validate(reg: Registry) -> None:
    for name, spec in reg.knobs.items():
        if not isinstance(spec, dict):
            raise ValueError(
                f"{reg.path}: knob {name!r} entry is not a dict")
        layers = spec.get("layers", {})
        if not isinstance(layers, dict) or not layers:
            raise ValueError(
                f"{reg.path}: knob {name!r} declares no layers")
        for layer in layers:
            if layer not in LAYERS:
                raise ValueError(
                    f"{reg.path}: knob {name!r} has unknown layer "
                    f"{layer!r} (want one of {', '.join(LAYERS)})")
        roles = spec.get("roles", ())
        if not roles:
            raise ValueError(
                f"{reg.path}: knob {name!r} declares no roles")
        for role in roles:
            if role not in ROLES:
                raise ValueError(
                    f"{reg.path}: knob {name!r} has unknown role "
                    f"{role!r} (want one of {', '.join(ROLES)})")


def is_registry_source(tree: ast.Module) -> bool:
    """Whether a module declares the registry (defines ``KNOBS``)."""
    for node in tree.body:
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        if any(
            isinstance(t, ast.Name) and t.id == "KNOBS"
            for t in targets
        ):
            return True
    return False


def extract_registry(tree: ast.Module, path: str) -> Optional[Registry]:
    """Literal-eval the registry assignments out of one module.

    Returns None when the module declares no registry; raises
    :class:`ValueError` when it declares one that is not a pure
    literal or violates the layer/role vocabulary (the module contract
    graftknob exists to keep honest)."""
    found: Dict[str, Any] = {}
    for node in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id in REGISTRY_NAMES:
                try:
                    found[t.id] = ast.literal_eval(value)
                except (ValueError, TypeError) as exc:
                    raise ValueError(
                        f"{path}: registry literal {t.id} is not pure "
                        f"(ast.literal_eval failed: {exc})"
                    ) from None
    if "KNOBS" not in found:
        return None
    reg = Registry(
        version=str(found.get("KNOBS_VERSION", "0.0")),
        knobs=found["KNOBS"],
        path=path,
    )
    _validate(reg)
    return reg


def load_repo_registry() -> Registry:
    """Parse the shipped ``runtime/knobs.py`` (AST only)."""
    path = REPO_ROOT / REGISTRY_REL
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    reg = extract_registry(tree, str(path))
    if reg is None:
        raise ValueError(f"{path}: no knob registry declared")
    return reg


# ---------------------------------------------------------------------------
# The KNOBS.json pin
# ---------------------------------------------------------------------------


def registry_to_pin(reg: Registry) -> Dict[str, Any]:
    """The JSON document ``--update-knobs`` writes and GK006 diffs."""
    return {
        "knobs_version": reg.version,
        "knobs": reg.knobs,
    }


def load_pin(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        pin = json.load(fh)
    if not isinstance(pin, dict):
        raise ValueError(f"{path}: pin must be a JSON object")
    return pin


@dataclass(frozen=True)
class PinChange:
    """One classified difference between the pin and the live registry.

    ``severity`` drives the bump rule: ``addition`` (new knob, layer,
    or role) needs a minor bump, ``removal`` (dropped or renamed — a
    rename IS a removal plus an addition) a major one, ``metadata``
    (defaults, key tokens, precedence, notes, scope) any re-pin."""

    severity: str  # "addition" | "removal" | "metadata"
    kind: str      # "knob" | "layer" | "role" | "version"
    name: str
    detail: str


def _diff_layers(
    name: str,
    pinned: Dict[str, Any],
    live: Dict[str, Any],
) -> List[PinChange]:
    changes: List[PinChange] = []
    for layer in sorted(set(pinned) - set(live)):
        changes.append(PinChange(
            "removal", "layer", f"{name}:{layer}",
            f"knob {name!r} layer {layer!r} removed"))
    for layer in sorted(set(live) - set(pinned)):
        changes.append(PinChange(
            "addition", "layer", f"{name}:{layer}",
            f"knob {name!r} layer {layer!r} added"))
    for layer in sorted(set(pinned) & set(live)):
        old, new = pinned[layer], live[layer]
        if old.get("surface") != new.get("surface"):
            changes.append(PinChange(
                "removal", "layer", f"{name}:{layer}",
                f"knob {name!r} {layer} surface renamed: "
                f"{old.get('surface')!r} -> {new.get('surface')!r}"))
        if old.get("default") != new.get("default") or (
            ("default" in old) != ("default" in new)
        ):
            changes.append(PinChange(
                "metadata", "layer", f"{name}:{layer}",
                f"knob {name!r} {layer} default changed: "
                f"{old.get('default')!r} -> {new.get('default')!r}"))
    return changes


def diff_pin(pin: Dict[str, Any], reg: Registry) -> List[PinChange]:
    """Every difference between the committed pin and the live
    registry, classified for the bump rule.  Empty means in sync."""
    changes: List[PinChange] = []
    pinned: Dict[str, Any] = pin.get("knobs", {})
    live = reg.knobs
    for name in sorted(set(pinned) - set(live)):
        changes.append(PinChange(
            "removal", "knob", name, f"knob {name!r} removed"))
    for name in sorted(set(live) - set(pinned)):
        changes.append(PinChange(
            "addition", "knob", name, f"knob {name!r} added"))
    for name in sorted(set(pinned) & set(live)):
        old, new = pinned[name], live[name]
        old_roles = list(old.get("roles", ()))
        new_roles = list(new.get("roles", ()))
        for r in [x for x in old_roles if x not in new_roles]:
            changes.append(PinChange(
                "removal", "role", f"{name}:{r}",
                f"knob {name!r} role {r!r} removed"))
        for r in [x for x in new_roles if x not in old_roles]:
            changes.append(PinChange(
                "addition", "role", f"{name}:{r}",
                f"knob {name!r} role {r!r} added"))
        changes.extend(_diff_layers(
            name, old.get("layers", {}), new.get("layers", {})))
        for mk in ("keys", "precedence", "note", "scope"):
            if old.get(mk) != new.get(mk):
                changes.append(PinChange(
                    "metadata", "knob", name,
                    f"knob {name!r} {mk} changed: "
                    f"{old.get(mk)!r} -> {new.get(mk)!r}"))
    old_v = str(pin.get("knobs_version", "0.0"))
    if old_v != reg.version:
        changes.append(PinChange(
            "metadata", "version", "knobs_version",
            f"KNOBS_VERSION {old_v!r} -> {reg.version!r}"))
    return changes


def _parse_version(v: str) -> Tuple[int, int]:
    parts = v.split(".")
    try:
        return int(parts[0]), int(parts[1]) if len(parts) > 1 else 0
    except (ValueError, IndexError):
        raise ValueError(
            f"unparseable KNOBS_VERSION {v!r} (want MAJOR.MINOR)"
        ) from None


def check_bump(
    old_version: str,
    new_version: str,
    changes: List[PinChange],
) -> Optional[str]:
    """The ``--update-knobs`` version rule; None when satisfied.

    * any ``removal`` change -> the major must increase;
    * else any ``addition``  -> the minor (or major) must increase;
    * metadata-only          -> any version >= the pinned one."""
    old = _parse_version(old_version)
    new = _parse_version(new_version)
    severities = {c.severity for c in changes
                  if c.kind != "version"}
    if "removal" in severities:
        if new[0] <= old[0]:
            return (
                f"removals/renames need a MAJOR KNOBS_VERSION bump "
                f"(pinned {old_version}, live {new_version})"
            )
        return None
    if "addition" in severities:
        if new > old:
            return None
        return (
            f"additions need a MINOR KNOBS_VERSION bump "
            f"(pinned {old_version}, live {new_version})"
        )
    if new < old:
        return (
            f"KNOBS_VERSION cannot move backwards "
            f"(pinned {old_version}, live {new_version})"
        )
    return None


def write_pin(path: str, reg: Registry) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(registry_to_pin(reg), fh, indent=2, sort_keys=True)
        fh.write("\n")

"""GK001–GK006: the knob-contract checks.

Each check consumes the extracted surfaces (:mod:`.extract`) and the
declared registry (:mod:`.registry`) and yields typed findings — no
printing, no imports of the analyzed package.

Key-site checks (GK002–GK004) run only when their anchor is in the
analyzed file set (fixtures embed miniature anchors; partial scans
skip, like graftrace GT004) — the CLI's repo-default gate separately
asserts that the shipped tree DID surface every anchor, so a rename
cannot silently disarm them.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .extract import (
    AFFINITY_CALL, AFFINITY_FUNC, CONFIG_CLASS, FINGERPRINT_FUNC,
    FUSE_FUNC, FileSurfaces, PROFILE_NAME, SERVE_FIELDS_NAME,
    STEP_ENV_NAME, TRACE_FUNCS, UNFOLDABLE,
)
from .findings import Finding
from .registry import PinChange, Registry, diff_pin

#: Layers whose dead-surface direction needs a per-layer anchor in the
#: scanned set before it can run (partial scans skip).
_REGISTRY_WHERE = "runtime/knobs.py"


def _fmt(value: Any) -> str:
    return repr(value)


def check_declared(
    surfaces: Sequence[FileSurfaces], reg: Registry
) -> Iterator[Finding]:
    """GK001: a knob surface read in the scanned tree but never
    declared — or declared but dead (nothing reads it).  Undeclared
    knobs dodge every role check; dead declarations rot the registry
    the way stale docs rot a README."""
    env_map = reg.surfaces_of("env")
    cli_map = reg.surfaces_of("cli")
    config_map = reg.surfaces_of("config")
    serve_map = reg.surfaces_of("serve-doc")
    profile_map = reg.surfaces_of("tune-profile")

    seen_env: Set[str] = set()
    seen_cli: Set[str] = set()
    seen_config: Set[str] = set()
    seen_serve: Set[str] = set()
    seen_profile: Set[str] = set()
    any_env = any_cli = any_config = any_serve = any_profile = False

    for fs in surfaces:
        for er in fs.env_reads:
            any_env = True
            seen_env.add(er.name)
            if er.name not in env_map:
                yield Finding(
                    fs.path, er.line, er.col, "GK001",
                    f"env knob {er.name!r} is read here but not "
                    "declared in runtime/knobs.py (declare it, role "
                    "it, then re-pin via --update-knobs)",
                    key=f"env:{er.name}",
                )
        for cf in fs.config_fields:
            any_config = True
            seen_config.add(cf.name)
            if cf.name not in config_map:
                yield Finding(
                    fs.path, cf.line, cf.col, "GK001",
                    f"{CONFIG_CLASS} field {cf.name!r} is not declared "
                    "as a config-layer knob in runtime/knobs.py",
                    key=f"config:{cf.name}",
                )
        for fl in fs.cli_flags:
            any_cli = True
            seen_cli.update(fl.flags)
            if not any(f in cli_map for f in fl.flags):
                yield Finding(
                    fs.path, fl.line, fl.col, "GK001",
                    f"CLI flag {fl.flags[0]!r} ({fl.builder}) is not "
                    "declared as a cli-layer knob in runtime/knobs.py",
                    key=f"cli:{fl.flags[0]}",
                )
        for sf in fs.serve_fields:
            any_serve = True
            seen_serve.add(sf.name)
            if sf.name not in serve_map:
                yield Finding(
                    fs.path, sf.line, sf.col, "GK001",
                    f"{SERVE_FIELDS_NAME} field {sf.name!r} is not "
                    "declared as a serve-doc-layer knob in "
                    "runtime/knobs.py",
                    key=f"serve-doc:{sf.name}",
                )
        for pk in fs.profile_knobs:
            any_profile = True
            seen_profile.add(pk.name)
            if pk.name not in profile_map:
                yield Finding(
                    fs.path, pk.line, pk.col, "GK001",
                    f"{PROFILE_NAME} entry {pk.name!r} is not declared "
                    "as a tune-profile-layer knob in runtime/knobs.py",
                    key=f"tune-profile:{pk.name}",
                )

    dead_legs: List[Tuple[bool, Dict[str, str], Set[str], str]] = [
        (any_env, env_map, seen_env, "env"),
        (any_cli, cli_map, seen_cli, "cli"),
        (any_config, config_map, seen_config, "config"),
        (any_serve, serve_map, seen_serve, "serve-doc"),
        (any_profile, profile_map, seen_profile, "tune-profile"),
    ]
    for anchored, decl_map, seen, layer in dead_legs:
        if not anchored:
            continue  # partial file set: this layer is not on screen
        for surface in sorted(set(decl_map) - seen):
            knob = decl_map[surface]
            if reg.knobs[knob].get("scope") == "tests":
                continue  # documented test-suite knobs never read here
            yield Finding(
                reg.path or _REGISTRY_WHERE, 1, 0, "GK001",
                f"knob {knob!r} declares {layer} surface {surface!r} "
                "but nothing in the scanned tree spells it (dead "
                "declaration — drop the layer or fix the reader)",
                key=f"dead:{layer}:{surface}",
            )


def _union_tokens(sites: Sequence[Any]) -> Set[str]:
    out: Set[str] = set()
    for site in sites:
        out |= site.tokens
    return out


def _first_site(
    surfaces: Sequence[FileSurfaces], attr: str
) -> Optional[Tuple[str, int]]:
    for fs in surfaces:
        sites = getattr(fs, attr)
        if sites:
            return fs.path, sites[0].line
    return None


def check_trace_keys(
    surfaces: Sequence[FileSurfaces], reg: Registry
) -> Iterator[Finding]:
    """GK002: a ``trace``-role knob whose token never appears in the
    step-cache key (the ``skey`` tuples of ``_make_launch`` /
    ``_superstep_static``, or the ``_STEP_ENV_KNOBS`` suffix) — two
    jobs differing only on that knob would silently reuse one
    compiled program."""
    trace_sites = [s for fs in surfaces for s in fs.trace_sites]
    step_env = {er.name for fs in surfaces
                for er in fs.step_env_knobs}
    if not trace_sites and not step_env:
        return  # partial file set: no step-cache key on screen
    tokens = _union_tokens(trace_sites) | step_env
    where = _first_site(surfaces, "trace_sites")
    path, line = where if where else (reg.path, 1)
    for knob in reg.role_knobs("trace"):
        token = reg.role_token(knob, "trace")
        if token not in tokens:
            yield Finding(
                path, line, 0, "GK002",
                f"trace-role knob {knob!r}: token {token!r} is in "
                f"neither {'/'.join(TRACE_FUNCS)}'s skey nor "
                f"{STEP_ENV_NAME} — cross-job compiled-program reuse "
                "would ignore it (add it to the key, or fix the "
                "registry's keys.trace token)",
                key=f"trace:{knob}",
            )


def check_fuse_keys(
    surfaces: Sequence[FileSurfaces], reg: Registry
) -> Iterator[Finding]:
    """GK003: a ``fuse-compat``-role knob absent from
    ``pack_candidate``'s compatibility key AND from its eligibility
    guards — jobs with conflicting policies could fuse into one packed
    group (the PR 12 bug class, mechanized)."""
    key_sites = [s for fs in surfaces for s in fs.fuse_key_sites]
    guard_sites = [s for fs in surfaces for s in fs.fuse_guard_sites]
    if not key_sites and not guard_sites:
        return  # partial file set: pack_candidate not on screen
    tokens = _union_tokens(key_sites) | _union_tokens(guard_sites)
    where = _first_site(surfaces, "fuse_key_sites")
    path, line = where if where else (reg.path, 1)
    for knob in reg.role_knobs("fuse-compat"):
        token = reg.role_token(knob, "fuse-compat")
        if token not in tokens:
            yield Finding(
                path, line, 0, "GK003",
                f"fuse-compat-role knob {knob!r}: token {token!r} is "
                f"in neither {FUSE_FUNC}'s key tuple nor its "
                "return-None guards — jobs disagreeing on it could "
                "fuse (add it to the key, gate eligibility, or fix "
                "the registry's keys.fuse-compat token)",
                key=f"fuse:{knob}",
            )


def check_schedule_keys(
    surfaces: Sequence[FileSurfaces], reg: Registry
) -> Iterator[Finding]:
    """GK004: an ``affinity``-role knob missing from
    ``affinity_token``'s scheduler-visible prefix (the router would
    place jobs where nothing can be reused), or a ``fingerprint``-role
    knob missing from ``sweep_fingerprint``'s parameters (checkpoints
    could resume across semantically different sweeps)."""
    affinity_sites = [s for fs in surfaces for s in fs.affinity_sites]
    if affinity_sites:
        tokens = _union_tokens(affinity_sites)
        where = _first_site(surfaces, "affinity_sites")
        path, line = where if where else (reg.path, 1)
        for knob in reg.role_knobs("affinity"):
            token = reg.role_token(knob, "affinity")
            if token not in tokens:
                yield Finding(
                    path, line, 0, "GK004",
                    f"affinity-role knob {knob!r}: token {token!r} "
                    f"never reaches the {AFFINITY_CALL} call in "
                    f"{AFFINITY_FUNC} — the router would place "
                    "compatible jobs apart (route it, or fix the "
                    "registry's keys.affinity token)",
                    key=f"affinity:{knob}",
                )
    fp_sites = [s for fs in surfaces for s in fs.fingerprint_sites]
    if fp_sites:
        tokens = _union_tokens(fp_sites)
        where = _first_site(surfaces, "fingerprint_sites")
        path, line = where if where else (reg.path, 1)
        for knob in reg.role_knobs("fingerprint"):
            token = reg.role_token(knob, "fingerprint")
            if token not in tokens:
                yield Finding(
                    path, line, 0, "GK004",
                    f"fingerprint-role knob {knob!r}: {token!r} is "
                    f"not a parameter of {FINGERPRINT_FUNC} — resume "
                    "identity would ignore it (thread it through, or "
                    "fix the registry's keys.fingerprint token)",
                    key=f"fingerprint:{knob}",
                )


def check_default_drift(
    surfaces: Sequence[FileSurfaces], reg: Registry
) -> Iterator[Finding]:
    """GK005: the declared default drifted from the code — the
    ``SweepConfig`` dataclass default or an ``add_argument`` default
    disagrees with the registry row.  (The README row cannot drift: it
    is rendered FROM the registry and staleness-gated by
    ``--check-readme``.)"""
    config_map = reg.surfaces_of("config")
    cli_map = reg.surfaces_of("cli")
    for fs in surfaces:
        for cf in fs.config_fields:
            knob = config_map.get(cf.name)
            if knob is None:
                continue  # GK001's problem
            declared, value = reg.declared_default(knob, "config")
            if not declared:
                continue
            if cf.default == UNFOLDABLE or value != cf.default:
                yield Finding(
                    fs.path, cf.line, cf.col, "GK005",
                    f"config default drift for knob {knob!r}: "
                    f"{CONFIG_CLASS}.{cf.name} defaults to "
                    f"{_fmt(cf.default)} but runtime/knobs.py declares "
                    f"{_fmt(value)}",
                    key=f"default:config:{knob}",
                )
        for fl in fs.cli_flags:
            knob = next(
                (cli_map[f] for f in fl.flags if f in cli_map), None)
            if knob is None:
                continue  # GK001's problem
            declared, value = reg.declared_default(knob, "cli")
            if not declared:
                continue
            if fl.default == UNFOLDABLE or value != fl.default:
                yield Finding(
                    fs.path, fl.line, fl.col, "GK005",
                    f"cli default drift for knob {knob!r}: "
                    f"{fl.flags[0]} ({fl.builder}) defaults to "
                    f"{_fmt(fl.default)} but runtime/knobs.py declares "
                    f"{_fmt(value)}",
                    key=f"default:cli:{knob}",
                )


def check_pin_drift(
    reg: Registry,
    pin: Optional[Dict[str, Any]],
    pin_path: str,
) -> Iterator[Finding]:
    """GK006: drift between the live registry and the committed
    KNOBS.json pin — either direction fails (the PROTOCOL.json
    discipline).  Deliberate changes re-pin via ``python -m
    tools.graftknob --update-knobs``, which also enforces the version
    bump rule."""
    where = reg.path or pin_path
    if pin is None:
        yield Finding(
            where, 1, 0, "GK006",
            f"no knob pin at {pin_path} — bootstrap it with "
            "python -m tools.graftknob --update-knobs",
            key="pin:missing",
        )
        return
    changes: List[PinChange] = diff_pin(pin, reg)
    for ch in changes:
        yield Finding(
            where, 1, 0, "GK006",
            f"registry drifted from {pin_path}: {ch.detail} "
            "(deliberate? re-pin via --update-knobs, which enforces "
            "the KNOBS_VERSION bump rule)",
            key=f"pin:{ch.kind}:{ch.name}",
        )

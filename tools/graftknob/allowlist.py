"""The shrink-only grandfather list (the GL013 discipline).

Findings whose ``(path suffix, key)`` matches an entry here are
suppressed by default — each with a one-line justification for WHY the
pattern is benign.  The list only shrinks: new code gets no entries
(declare the knob in ``runtime/knobs.py``, role it, and wire its key
site — or fix the site), and
``tests/test_graftknob.py::test_allowlist_is_live`` fails when an
entry no longer matches anything, so a fixed pattern cannot linger
here.  ``--no-allowlist`` surfaces the suppressed findings.

Deliberate knob SPLITS do not belong here: a knob that looks like
drift (``--retries`` vs ``retry_attempts``) is declared as two knobs
with notes saying why — an annotation the report renders, not a
suppression.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .findings import Finding

#: ``(path suffix, finding key)`` -> one-line justification.
ALLOWLIST: Dict[Tuple[str, str], str] = {}


def match(finding: Finding) -> bool:
    """Whether ``finding`` is grandfathered."""
    path = finding.path.replace("\\", "/")
    return any(
        path.endswith(suffix) and finding.key == key
        for (suffix, key) in ALLOWLIST
    )


def split(
    findings: List[Finding],
) -> Tuple[List[Finding], List[Finding]]:
    """``(live, grandfathered)`` partition, order preserved."""
    live: List[Finding] = []
    grand: List[Finding] = []
    for f in findings:
        (grand if match(f) else live).append(f)
    return live, grand

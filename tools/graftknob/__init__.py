"""graftknob — configuration-knob contract static analysis.

The knob tier of the repo's static stack (PERF.md §25–§30): graftlint
checks single-file AST hazards, graftaudit checks what XLA compiles,
graftrace checks what the threads do, graftwire checks what goes over
the wire, and graftknob checks what CONFIGURATION can change — every
env var, CLI flag, ``SweepConfig`` field, serve-doc config field, and
tune-profile knob, audited against the single declared registry in
``runtime/knobs.py`` and the committed ``KNOBS.json`` pin, with each
declared ROLE mechanically traced to the cache key that must honor it.

Checks:

* **GK001** — knob surface read in the scanned tree but undeclared,
  or declared but dead
* **GK002** — ``trace``-role knob missing from the step-cache key
  (silent cross-job compiled-program reuse)
* **GK003** — ``fuse-compat``-role knob absent from
  ``pack_candidate``'s compatibility key and guards (jobs with
  conflicting policies could fuse — the PR 12 bug class, mechanized)
* **GK004** — ``affinity``-role knob missing from ``affinity_token``'s
  scheduler-visible prefix, or ``fingerprint``-role knob missing from
  ``sweep_fingerprint``
* **GK005** — default-value drift: registry vs ``SweepConfig``
  dataclass vs ``argparse`` declarations
* **GK006** — drift between the live registry and the committed
  ``KNOBS.json`` pin (re-pin via ``--update-knobs``, which enforces
  the KNOBS_VERSION bump rule)

Typed public API::

    from tools.graftknob import analyze_paths

    findings, model = analyze_paths(
        ["hashcat_a5_table_generator_tpu", "bench.py"])

Run as ``python -m tools.graftknob`` (see ``scripts/lint.sh``
layer 7).  Stdlib-only: the registry is extracted via AST, never
imported.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tools.graftlint import iter_python_files

from . import allowlist
from .checks import check_declared, check_default_drift, \
    check_fuse_keys, check_pin_drift, check_schedule_keys, \
    check_trace_keys
from .extract import FileSurfaces, extract_surfaces
from .findings import Finding
from .registry import PIN_REL, PinChange, Registry, REPO_ROOT, \
    diff_pin, extract_registry, is_registry_source, load_pin, \
    load_repo_registry

__all__ = [
    "ALL_CHECKS",
    "Finding",
    "KnobModel",
    "Registry",
    "analyze_sources",
    "analyze_paths",
    "repo_floor_errors",
]

#: code -> one-line summary (the ``--list-checks`` table).
ALL_CHECKS: Dict[str, str] = {
    "GK001": "knob surface read but undeclared, or declared but dead",
    "GK002": "trace-role knob missing from the step-cache key",
    "GK003": "fuse-compat-role knob absent from pack_candidate's "
             "key/guards",
    "GK004": "affinity-role knob missing from affinity_token, or "
             "fingerprint-role knob missing from sweep_fingerprint",
    "GK005": "default drift: registry vs SweepConfig vs argparse",
    "GK006": "live registry drifted from the committed KNOBS.json pin",
}

#: The committed pin the repo-default analysis diffs against.
DEFAULT_PIN_PATH = str(REPO_ROOT / PIN_REL)


@dataclass
class KnobModel:
    """Everything one analysis extracted (feeds the report)."""

    registry: Optional[Registry]
    surfaces: List[FileSurfaces] = field(default_factory=list)
    pin: Optional[Dict[str, object]] = None
    pin_path: str = ""
    changes: List[PinChange] = field(default_factory=list)

    @property
    def n_env_reads(self) -> int:
        return sum(len(fs.env_reads) for fs in self.surfaces)

    @property
    def n_cli_flags(self) -> int:
        return sum(len(fs.cli_flags) for fs in self.surfaces)

    @property
    def n_config_fields(self) -> int:
        return sum(len(fs.config_fields) for fs in self.surfaces)

    @property
    def n_trace_sites(self) -> int:
        return sum(len(fs.trace_sites) for fs in self.surfaces)

    @property
    def n_fuse_key_sites(self) -> int:
        return sum(len(fs.fuse_key_sites) for fs in self.surfaces)

    @property
    def n_fuse_guards(self) -> int:
        return sum(len(fs.fuse_guard_sites) for fs in self.surfaces)

    @property
    def n_affinity_sites(self) -> int:
        return sum(len(fs.affinity_sites) for fs in self.surfaces)

    @property
    def n_fingerprint_sites(self) -> int:
        return sum(len(fs.fingerprint_sites) for fs in self.surfaces)

    @property
    def n_serve_fields(self) -> int:
        return sum(len(fs.serve_fields) for fs in self.surfaces)

    @property
    def n_profile_knobs(self) -> int:
        return sum(len(fs.profile_knobs) for fs in self.surfaces)

    @property
    def n_step_env_knobs(self) -> int:
        return sum(len(fs.step_env_knobs) for fs in self.surfaces)

    @property
    def builders_found(self) -> int:
        found = set()
        for fs in self.surfaces:
            found |= fs.builders_found
        return len(found)


#: Extraction floors the repo-default run must clear (the non-vacuity
#: gate: a rename that silently disarms a key-site check trips these
#: before it can pretend the tree is clean).  Fixture runs pass
#: explicit paths and are exempt.
REPO_FLOORS: Dict[str, int] = {
    "knobs": 40,
    "env_reads": 15,
    "cli_flags": 40,
    "config_fields": 15,
    "trace_sites": 2,
    "step_env_knobs": 3,
    "fuse_key_sites": 1,
    "fuse_guards": 3,
    "affinity_sites": 1,
    "fingerprint_sites": 1,
    "serve_fields": 10,
    "profile_knobs": 4,
    "builders": 4,
}


def repo_floor_errors(model: KnobModel) -> List[str]:
    """Floor violations of one repo-default analysis (empty = armed)."""
    reg = model.registry
    actual: Dict[str, int] = {
        "knobs": len(reg.knobs) if reg is not None else 0,
        "env_reads": model.n_env_reads,
        "cli_flags": model.n_cli_flags,
        "config_fields": model.n_config_fields,
        "trace_sites": model.n_trace_sites,
        "step_env_knobs": model.n_step_env_knobs,
        "fuse_key_sites": model.n_fuse_key_sites,
        "fuse_guards": model.n_fuse_guards,
        "affinity_sites": model.n_affinity_sites,
        "fingerprint_sites": model.n_fingerprint_sites,
        "serve_fields": model.n_serve_fields,
        "profile_knobs": model.n_profile_knobs,
        "builders": model.builders_found,
    }
    errors: List[str] = []
    for name, floor in REPO_FLOORS.items():
        if actual[name] < floor:
            errors.append(
                f"extraction floor not met: {name}={actual[name]} "
                f"< {floor} (a rename disarmed the check? fix the "
                "anchor names in tools/graftknob/extract.py)"
            )
    return errors


def _selected(select: Optional[Iterable[str]]) -> List[str]:
    if select is None:
        return list(ALL_CHECKS)
    codes = [c for c in select]
    unknown = [c for c in codes if c not in ALL_CHECKS]
    if unknown:
        raise ValueError(
            f"unknown check code(s): {', '.join(unknown)}"
        )
    return codes


def analyze_sources(
    items: Sequence[Tuple[str, str]],
    *,
    select: Optional[Iterable[str]] = None,
    use_allowlist: bool = True,
    registry: Optional[Registry] = None,
    pin: Optional[Dict[str, object]] = None,
    pin_path: Optional[str] = None,
) -> Tuple[List[Finding], KnobModel]:
    """Analyze ``(source, path)`` pairs as one program.

    The registry comes from (first match wins) the ``registry``
    argument, a scanned file that declares ``KNOBS`` (basename
    ``knobs.py`` preferred — fixtures embed miniature registries), or
    the shipped ``runtime/knobs.py``.  ``pin``/``pin_path`` feed
    GK006; with neither, the repo's committed ``KNOBS.json`` is used
    when present.  Returns ``(findings, model)``; raises
    ``SyntaxError`` on an unparseable file and ``ValueError`` on an
    unknown check code or an impure/invalid registry literal."""
    codes = _selected(select)
    surfaces: List[FileSurfaces] = []
    scanned_registries: List[Registry] = []
    for source, path in items:
        tree = ast.parse(source, filename=path)
        source_file = is_registry_source(tree)
        if source_file:
            reg = extract_registry(tree, path)
            if reg is not None:
                scanned_registries.append(reg)
        surfaces.append(
            extract_surfaces(tree, path, registry_source=source_file)
        )
    if registry is None and scanned_registries:
        preferred = [r for r in scanned_registries
                     if os.path.basename(r.path) == "knobs.py"]
        registry = (preferred or scanned_registries)[0]
    if registry is None:
        registry = load_repo_registry()

    if pin_path is None:
        pin_path = DEFAULT_PIN_PATH
    if pin is None and os.path.exists(pin_path):
        pin = load_pin(pin_path)
    rel_pin = os.path.basename(pin_path)

    findings: List[Finding] = []
    if "GK001" in codes:
        findings.extend(check_declared(surfaces, registry))
    if "GK002" in codes:
        findings.extend(check_trace_keys(surfaces, registry))
    if "GK003" in codes:
        findings.extend(check_fuse_keys(surfaces, registry))
    if "GK004" in codes:
        findings.extend(check_schedule_keys(surfaces, registry))
    if "GK005" in codes:
        findings.extend(check_default_drift(surfaces, registry))
    if "GK006" in codes:
        findings.extend(check_pin_drift(registry, pin, rel_pin))
    if use_allowlist:
        findings, _grandfathered = allowlist.split(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    model = KnobModel(
        registry=registry, surfaces=surfaces,
        pin=pin, pin_path=pin_path,
        changes=diff_pin(pin, registry) if pin is not None else [],
    )
    return findings, model


def analyze_paths(
    paths: Sequence[str],
    *,
    select: Optional[Iterable[str]] = None,
    use_allowlist: bool = True,
    registry: Optional[Registry] = None,
    pin: Optional[Dict[str, object]] = None,
    pin_path: Optional[str] = None,
) -> Tuple[List[Finding], KnobModel]:
    """Analyze every ``.py`` file under ``paths`` as one program."""
    items: List[Tuple[str, str]] = []
    for file_path in iter_python_files(paths):
        with open(file_path, "r", encoding="utf-8") as fh:
            items.append((fh.read(), file_path))
    return analyze_sources(
        items, select=select, use_allowlist=use_allowlist,
        registry=registry, pin=pin, pin_path=pin_path,
    )

"""Finding type — graftknob's typed output surface.

Same contract as graftlint/graftaudit/graftrace/graftwire's:
everything the CLI prints and the tests assert on is a
:class:`Finding`; checks produce them and never print, so one check
implementation drives the CLI, the fixtures, and CI.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    """One knob-contract violation at a source location.

    ``path`` is the path the file was analyzed AS (fixture tests feed
    snippets under virtual paths); ``line``/``col`` are 1-based line
    and 0-based column, matching ``ast`` node coordinates.  ``key`` is
    the stable allowlist key (``env:<NAME>`` / ``cli:<flag>`` /
    ``trace:<knob>`` / ``pin:<kind>:<name>`` …) — the grandfather list
    matches on it, never on line numbers."""

    path: str
    line: int
    col: int
    code: str
    message: str
    key: str = ""

    def render(self) -> str:
        """``path:line:col: CODE message`` — the CLI output line."""
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

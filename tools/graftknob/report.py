"""The knob report: registry table, pin drift, metrics.

``--report`` renders the knob section (also embedded in README between
the markers below and kept fresh by ``--check-readme`` in CI);
``--summary`` appends it plus the drift table to the CI job summary;
``--metrics-json`` emits the counters CI uploads as an artifact.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from .registry import PinChange, Registry

#: README markers delimiting the rendered section (the region
#: ``--update-readme`` rewrites and ``--check-readme`` verifies).
BEGIN_MARK = "<!-- graftknob:knobs:begin -->"
END_MARK = "<!-- graftknob:knobs:end -->"


def _surface_cell(spec: Dict[str, Any]) -> str:
    parts: List[str] = []
    for layer in ("env", "cli", "config", "serve-doc", "tune-profile"):
        ldecl = spec.get("layers", {}).get(layer)
        if ldecl is None:
            continue
        surface = ldecl.get("surface", "")
        spellings = (
            surface if isinstance(surface, (list, tuple))
            else [surface]
        )
        joined = " ".join(f"`{s}`" for s in spellings)
        parts.append(f"{layer} {joined}")
    return "; ".join(parts) if parts else "—"


def _default_cell(spec: Dict[str, Any]) -> str:
    for layer in ("config", "cli", "env"):
        ldecl = spec.get("layers", {}).get(layer)
        if ldecl is not None and "default" in ldecl:
            return f"`{ldecl['default']!r}`"
    return "—"


def _roles_cell(spec: Dict[str, Any]) -> str:
    roles = spec.get("roles", ())
    return ", ".join(f"`{r}`" for r in roles) if roles else "—"


def knob_table(reg: Registry) -> str:
    """The one table of every declared knob."""
    lines: List[str] = []
    lines.append(
        f"Knob registry **{reg.version}** — declared in "
        "`runtime/knobs.py`, pinned in `KNOBS.json` (changes re-pin "
        "via `python -m tools.graftknob --update-knobs`: additions "
        "bump the minor, removals/renames the major).  Roles are "
        "mechanically enforced: `trace` knobs must join the step-cache "
        "key, `fuse-compat` knobs the `pack_candidate` compatibility "
        "key, `affinity` knobs the scheduler token, `fingerprint` "
        "knobs the resume identity."
    )
    lines.append("")
    lines.append("| knob | surfaces | default | roles | note |")
    lines.append("|------|----------|---------|-------|------|")
    for name in sorted(reg.knobs):
        spec = reg.knobs[name]
        cell = f"`{name}`"
        if spec.get("scope") == "tests":
            cell += " (tests)"
        lines.append(
            f"| {cell} | {_surface_cell(spec)} "
            f"| {_default_cell(spec)} | {_roles_cell(spec)} "
            f"| {spec.get('note', '—')} |"
        )
    lines.append("")
    return "\n".join(lines)


def render_section(reg: Registry) -> str:
    """The marker-delimited README region (heading included)."""
    return (
        f"{BEGIN_MARK}\n"
        "### Configuration knobs\n\n"
        f"{knob_table(reg)}"
        f"{END_MARK}\n"
    )


def drift_table(changes: Sequence[PinChange]) -> str:
    """The pin-drift table CI publishes to the job summary."""
    if not changes:
        return ("\n**KNOBS.json**: in sync with the live "
                "registry.\n")
    lines = ["", "**KNOBS.json drift** (GK006):", "",
             "| severity | change |", "|----------|--------|"]
    for ch in changes:
        lines.append(f"| {ch.severity} | {ch.detail} |")
    lines.append("")
    return "\n".join(lines)


def to_markdown(
    reg: Optional[Registry],
    changes: Sequence[PinChange] = (),
) -> str:
    """The full ``--report`` document."""
    if reg is None:
        return "# graftknob\n\nNo knob registry in the analyzed set.\n"
    return (
        "# graftknob — configuration-knob contract\n\n"
        + knob_table(reg)
        + drift_table(changes)
    )


def extract_readme_section(text: str) -> Optional[str]:
    """The marker-delimited region of a README, markers included."""
    start = text.find(BEGIN_MARK)
    end = text.find(END_MARK)
    if start < 0 or end < 0 or end < start:
        return None
    return text[start:end + len(END_MARK)] + "\n"


def replace_readme_section(text: str, section: str) -> str:
    """README text with the marker region replaced by ``section``."""
    start = text.find(BEGIN_MARK)
    end = text.find(END_MARK)
    if start < 0 or end < 0 or end < start:
        raise ValueError(
            f"README has no {BEGIN_MARK} .. {END_MARK} region"
        )
    return text[:start] + section.rstrip("\n") + text[end + len(END_MARK):]


def metrics(
    reg: Optional[Registry],
    counts: Dict[str, float],
) -> Dict[str, Any]:
    """The ``graftknob-metrics.json`` payload."""
    payload: Dict[str, Any] = dict(counts)
    if reg is not None:
        payload["knobs_version"] = reg.version
        payload["knobs"] = len(reg.knobs)
    return {"graftknob": payload}

"""graftknob command line: ``python -m tools.graftknob [paths...]``.

Exit codes: 0 clean, 1 findings (or a stale README section), 2
usage/parse error or an unmet extraction floor — the contract
``scripts/lint.sh`` and CI key on (same as the other graft tiers).

The repo-default run (no explicit paths) additionally asserts the
extraction floors in :data:`tools.graftknob.REPO_FLOORS`: the gate is
non-vacuous BY CONSTRUCTION — if a refactor renames ``pack_candidate``
or ``skey`` out from under the extractor, the floors trip (exit 2)
instead of the checks silently passing over nothing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence

from . import ALL_CHECKS, DEFAULT_PIN_PATH, analyze_paths, \
    repo_floor_errors
from .registry import check_bump, diff_pin, write_pin
from .report import drift_table, extract_readme_section, metrics, \
    render_section, replace_readme_section, to_markdown

#: What ``python -m tools.graftknob`` scans with no arguments: the
#: whole package (env reads live in ops/, native/, parallel/ too)
#: plus bench.py.  tools/ and tests/ stay out — the tiers' own
#: extraction strings and the suites' monkeypatched env vars are not
#: knob reads.
DEFAULT_PATHS = (
    "hashcat_a5_table_generator_tpu",
    "bench.py",
)


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="graftknob",
        description=(
            "Configuration-knob contract audit (env/cli/config/"
            "serve-doc/tune-profile surfaces and the trace/fuse/"
            "affinity/fingerprint key sites vs the declared "
            "runtime/knobs.py registry and the KNOBS.json pin)."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=list(DEFAULT_PATHS),
        help="files or directories to analyze "
             f"(default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument(
        "--select",
        metavar="CODES",
        help="comma-separated check codes to run (default: all)",
    )
    parser.add_argument(
        "--list-checks",
        action="store_true",
        help="print the check table and exit",
    )
    parser.add_argument(
        "--knobs-json",
        metavar="PATH",
        default=DEFAULT_PIN_PATH,
        help="the committed knob pin GK006 diffs against "
             "(default: KNOBS.json at the repo root)",
    )
    parser.add_argument(
        "--update-knobs",
        action="store_true",
        help="re-pin KNOBS.json from the live registry (enforces the "
             "KNOBS_VERSION bump rule: additions need a minor bump, "
             "removals/renames a major), then analyze",
    )
    parser.add_argument(
        "--report",
        metavar="PATH",
        help="write the knob markdown report to PATH ('-' for stdout)",
    )
    parser.add_argument(
        "--check-readme",
        metavar="PATH",
        help="fail (exit 1) when PATH's marker-delimited knob section "
             "is stale vs the live registry",
    )
    parser.add_argument(
        "--update-readme",
        metavar="PATH",
        help="rewrite PATH's marker-delimited knob section from the "
             "live registry",
    )
    parser.add_argument(
        "--summary",
        metavar="PATH",
        help="append the knob report + drift table + finding counts "
             "to PATH (CI: pass \"$GITHUB_STEP_SUMMARY\")",
    )
    parser.add_argument(
        "--metrics-json",
        metavar="PATH",
        help="write run metrics (knob/surface/key-site/finding "
             "counts) as JSON to PATH; CI uploads it as a job "
             "artifact",
    )
    parser.add_argument(
        "--no-allowlist",
        action="store_true",
        help="surface grandfathered findings (the shrink-only list in "
             "tools/graftknob/allowlist.py)",
    )
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_checks:
        for code, summary in ALL_CHECKS.items():
            print(f"{code}  {summary}")
        return 0
    select: Optional[List[str]] = None
    if args.select:
        select = [c.strip() for c in args.select.split(",") if c.strip()]
    repo_gate = args.paths == list(DEFAULT_PATHS)
    t0 = time.monotonic()
    try:
        findings, model = analyze_paths(
            args.paths,
            select=select,
            use_allowlist=not args.no_allowlist,
            pin_path=args.knobs_json,
        )
    except ValueError as exc:
        print(f"graftknob: error: {exc}", file=sys.stderr)
        return 2
    except SyntaxError as exc:
        print(f"graftknob: parse error: {exc}", file=sys.stderr)
        return 2

    if args.update_knobs:
        reg = model.registry
        if reg is None:
            print("graftknob: error: no registry to pin",
                  file=sys.stderr)
            return 2
        if model.pin is not None:
            changes = diff_pin(model.pin, reg)
            err = check_bump(
                str(model.pin.get("knobs_version", "0.0")),
                reg.version, changes,
            )
            if err is not None:
                print(f"graftknob: --update-knobs refused: {err}",
                      file=sys.stderr)
                return 2
        write_pin(args.knobs_json, reg)
        print(f"graftknob: pinned knobs {reg.version} -> "
              f"{args.knobs_json}")
        # the fresh pin supersedes the pre-update drift findings
        try:
            findings, model = analyze_paths(
                args.paths,
                select=select,
                use_allowlist=not args.no_allowlist,
                pin_path=args.knobs_json,
            )
        except (ValueError, SyntaxError) as exc:
            print(f"graftknob: error: {exc}", file=sys.stderr)
            return 2
    elapsed = time.monotonic() - t0

    if repo_gate:
        floor_errors = repo_floor_errors(model)
        if floor_errors:
            for err in floor_errors:
                print(f"graftknob: error: {err}", file=sys.stderr)
            return 2

    readme_stale = False
    if args.update_readme or args.check_readme:
        reg = model.registry
        if reg is None:
            print("graftknob: error: no registry for the README "
                  "section", file=sys.stderr)
            return 2
        section = render_section(reg)
        readme_path = args.update_readme or args.check_readme
        with open(readme_path, "r", encoding="utf-8") as fh:
            text = fh.read()
        if args.update_readme:
            try:
                updated = replace_readme_section(text, section)
            except ValueError as exc:
                print(f"graftknob: error: {exc}", file=sys.stderr)
                return 2
            with open(readme_path, "w", encoding="utf-8") as fh:
                fh.write(updated)
            print(f"graftknob: wrote knob section -> {readme_path}")
        else:
            current = extract_readme_section(text)
            if current is None or current.strip() != section.strip():
                readme_stale = True
                print(
                    f"graftknob: {readme_path} knob section is stale "
                    "— refresh with python -m tools.graftknob "
                    f"--update-readme {readme_path}",
                    file=sys.stderr,
                )

    report_md = to_markdown(model.registry, model.changes)
    if args.report == "-":
        print(report_md, end="")
    elif args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            fh.write(report_md)
    if args.summary:
        with open(args.summary, "a", encoding="utf-8") as fh:
            fh.write(report_md)
            fh.write(drift_table(model.changes))
            fh.write(
                f"\n**graftknob**: {len(findings)} finding(s) over "
                f"{model.n_env_reads} env reads / "
                f"{model.n_cli_flags} cli flags / "
                f"{model.n_config_fields} config fields in "
                f"{elapsed:.2f}s\n"
            )
            for f in findings:
                fh.write(f"- `{f.render()}`\n")
    if args.metrics_json:
        counts: Dict[str, float] = {
            "findings": len(findings), "elapsed_s": elapsed,
            "env_reads": model.n_env_reads,
            "cli_flags": model.n_cli_flags,
            "config_fields": model.n_config_fields,
            "serve_fields": model.n_serve_fields,
            "profile_knobs": model.n_profile_knobs,
            "trace_sites": model.n_trace_sites,
            "fuse_key_sites": model.n_fuse_key_sites,
            "fuse_guards": model.n_fuse_guards,
            "affinity_sites": model.n_affinity_sites,
            "fingerprint_sites": model.n_fingerprint_sites,
            "pin_changes": len(model.changes),
        }
        for code in ALL_CHECKS:
            counts[f"findings_{code.lower()}"] = sum(
                1 for f in findings if f.code == code
            )
        payload = metrics(model.registry, counts)
        with open(args.metrics_json, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")

    try:
        for finding in findings:
            print(finding.render())
    except BrokenPipeError:  # piped into head; keep the exit contract
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
    if findings or readme_stale:
        n = len(findings) + (1 if readme_stale else 0)
        print(f"graftknob: {n} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

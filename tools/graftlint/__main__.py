"""``python -m tools.graftlint`` entry point."""

from __future__ import annotations

import sys

from .cli import main

sys.exit(main())

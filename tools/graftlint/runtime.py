"""Runtime analyzer: JAX compilation-cache misses around hot paths.

Static rules catch *shapes* of recompilation hazards (GL006); this
watcher catches the actual event.  The engine's hot loop launches one
compiled program per (geometry, config) — a cache-busting argument
signature (a Python scalar that varies per launch, a weak-typed const,
an accidentally-traced config) shows up as a growing ``jax.jit`` cache,
and on TPU each miss is a multi-second stall mid-sweep.

Usage (see the ``compile_watcher`` pytest fixture in
``tests/conftest.py``)::

    watcher = CompileWatcher(step_fn)
    with watcher.expect(1):          # first launch: one compile
        step_fn(plan, table, blocks, digests)
    with watcher.expect(0):          # same signature: cache hit only
        step_fn(plan2, table, blocks, digests)

``CompileWatcher`` prefers per-function cache sizes (``_cache_size()``
on jitted callables — exact and local); when a watched callable does
not expose one it falls back to the process-global backend-compile
event counter from ``jax.monitoring``.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Iterator, List, Optional, Sequence

#: Module-level counter fed by the jax.monitoring listener (registered
#: once; listeners cannot be unregistered).
_BACKEND_COMPILES = 0
_LISTENER_READY = False

#: The duration event JAX records once per backend (XLA) compilation.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _ensure_listener() -> bool:
    """Register the global compile-event listener (idempotent).

    Returns False when ``jax.monitoring`` is unavailable."""
    global _LISTENER_READY
    if _LISTENER_READY:
        return True
    try:
        import jax.monitoring as monitoring

        def _on_duration(name: str, secs: float, **kw: Any) -> None:
            global _BACKEND_COMPILES
            if name == _COMPILE_EVENT:
                _BACKEND_COMPILES += 1

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _LISTENER_READY = True
    return True


def backend_compile_count() -> int:
    """Process-global count of backend compilations seen so far."""
    _ensure_listener()
    return _BACKEND_COMPILES


def _cache_size(fn: Callable[..., Any]) -> Optional[int]:
    """The jitted callable's signature-cache entry count, if exposed."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


class CompileWatcher:
    """Counts new compilation-cache entries across a code region.

    Watches specific jitted callables when given (exact, per-function);
    otherwise watches the process-global backend-compile counter (off
    by nested jits, but catches every miss).
    """

    def __init__(self, *functions: Callable[..., Any]) -> None:
        self.functions: Sequence[Callable[..., Any]] = functions
        self._have_sizes = bool(functions) and all(
            _cache_size(fn) is not None for fn in functions
        )
        if not self._have_sizes and not _ensure_listener():
            # A guard with no counting source would pass every expect()
            # vacuously; a broken gate must be loud, never silently
            # clean (same principle as iter_python_files).
            raise RuntimeError(
                "CompileWatcher has no counting source: the watched "
                "callable(s) expose no _cache_size() and "
                "jax.monitoring's duration-event listener is "
                "unavailable on this jax version"
            )
        self._baseline: List[int] = []
        self.snapshot()

    def snapshot(self) -> None:
        """Re-baseline: subsequent :meth:`new_entries` counts from here."""
        if self._have_sizes:
            self._baseline = [
                _cache_size(fn) or 0 for fn in self.functions
            ]
        else:
            self._baseline = [backend_compile_count()]

    def new_entries(self) -> int:
        """Cache entries (or backend compiles) added since the last
        snapshot."""
        if self._have_sizes:
            sizes = [_cache_size(fn) or 0 for fn in self.functions]
            return sum(s - b for s, b in zip(sizes, self._baseline))
        return backend_compile_count() - self._baseline[0]

    @contextlib.contextmanager
    def expect(self, at_most: int, *, label: str = "") -> Iterator[None]:
        """Fail (AssertionError) when the region compiles more than
        ``at_most`` new programs — the cache-busting-signature guard."""
        self.snapshot()
        yield
        got = self.new_entries()
        if got > at_most:
            where = f" [{label}]" if label else ""
            raise AssertionError(
                f"compilation-cache guard{where}: {got} new compiled "
                f"program(s), expected at most {at_most}. A hot-path "
                "argument signature is cache-busting (varying Python "
                "scalar, weak-typed const, or config traced instead of "
                "static)."
            )

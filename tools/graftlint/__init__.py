"""graftlint — repo-specific static analysis for the TPU hash engine.

A stdlib-only (``ast`` + ``tokenize``) pass with rules for the hazards
this codebase actually has: implicit dtype promotion in the uint32 hash
arithmetic, host-side escapes inside jitted/Pallas bodies, recompiling
``jax.jit`` call sites, nondeterminism in parity-critical layers, and
the shape/dtype docstring contract on the public op surface.

Typed public API::

    from tools.graftlint import lint_source, lint_paths, ALL_RULES

    findings = lint_source(src, path="hashcat_a5_table_generator_tpu/ops/x.py")
    findings = lint_paths(["hashcat_a5_table_generator_tpu"])

Suppress a finding on one line with ``# graftlint: disable=GL001``.
Run as ``python -m tools.graftlint`` (see ``scripts/lint.sh``).
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence

from .context import FileContext, build_context
from .findings import Finding
from .rules import ALL_RULES, RULES_BY_CODE, Rule

__all__ = [
    "ALL_RULES",
    "RULES_BY_CODE",
    "Finding",
    "Rule",
    "FileContext",
    "lint_source",
    "lint_file",
    "lint_paths",
]


def _select_rules(select: Optional[Iterable[str]]) -> List[Rule]:
    if select is None:
        return list(ALL_RULES)
    codes = list(select)
    unknown = [c for c in codes if c not in RULES_BY_CODE]
    if unknown:
        raise ValueError(f"unknown rule code(s): {', '.join(unknown)}")
    return [RULES_BY_CODE[c] for c in codes]


def lint_source(
    source: str,
    path: str,
    *,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint ``source`` as though it lived at ``path``.

    ``path`` drives rule scoping (ops/ rules, library rules, ...), so
    fixture tests lint snippets under virtual package paths.  ``select``
    restricts to specific rule codes.  Raises ``SyntaxError`` on an
    unparseable file.
    """
    ctx = build_context(source, path)
    findings: List[Finding] = []
    for rule in _select_rules(select):
        if not rule.applies(ctx):
            continue
        for finding in rule.check(ctx):
            if not ctx.is_suppressed(finding.line, finding.code):
                findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def lint_file(
    path: str, *, select: Optional[Iterable[str]] = None
) -> List[Finding]:
    """Lint one file from disk."""
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    return lint_source(source, path, select=select)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted list of ``.py`` files.

    A path that does not exist, or is a file without a ``.py`` suffix,
    raises ``ValueError`` — a typo'd path in CI must be a loud usage
    error, never a silently-vacuous (clean) lint run."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [
                    d
                    for d in dirnames
                    if d not in ("__pycache__", ".git", ".venv", "node_modules")
                ]
                for name in filenames:
                    if name.endswith(".py"):
                        out.append(os.path.join(dirpath, name))
        elif os.path.isfile(path):
            if not path.endswith(".py"):
                raise ValueError(f"not a Python file: {path}")
            out.append(path)
        else:
            raise ValueError(f"no such file or directory: {path}")
    return sorted(out)


def lint_paths(
    paths: Sequence[str],
    *,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint every ``.py`` file under ``paths`` (files or directories)."""
    findings: List[Finding] = []
    for file_path in iter_python_files(paths):
        findings.extend(lint_file(file_path, select=select))
    return findings

"""graftlint command line: ``python -m tools.graftlint [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/parse error — the contract
``scripts/lint.sh`` and CI key on.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence

from . import ALL_RULES, lint_paths

#: What ``python -m tools.graftlint`` scans with no arguments.
DEFAULT_PATHS = ("hashcat_a5_table_generator_tpu", "tools")


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description=(
            "JAX/Pallas-aware static analysis for the TPU hash engine "
            "(dtype promotion, trace escapes, recompilation hazards, "
            "determinism, op doc contracts)."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=list(DEFAULT_PATHS),
        help=f"files or directories to lint (default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument(
        "--select",
        metavar="CODES",
        help="comma-separated rule codes to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    parser.add_argument(
        "-v",
        "--verbose",
        action="store_true",
        help="with --list-rules, include each rule's rationale",
    )
    return parser


def _list_rules(verbose: bool) -> None:
    for rule in ALL_RULES:
        print(f"{rule.code}  {rule.name}: {rule.summary}")
        if verbose:
            print(f"       {rule.rationale}")


def _silence_stdout() -> None:
    """Point stdout at devnull after EPIPE so the interpreter's exit
    flush cannot re-raise and clobber the documented exit code."""
    devnull = os.open(os.devnull, os.O_WRONLY)
    os.dup2(devnull, sys.stdout.fileno())


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        try:
            _list_rules(args.verbose)
        except BrokenPipeError:  # e.g. piped into head
            _silence_stdout()
        return 0
    select: Optional[List[str]] = None
    if args.select:
        select = [c.strip() for c in args.select.split(",") if c.strip()]
    try:
        findings = lint_paths(args.paths, select=select)
    except ValueError as exc:
        print(f"graftlint: error: {exc}", file=sys.stderr)
        return 2
    except SyntaxError as exc:
        print(f"graftlint: parse error: {exc}", file=sys.stderr)
        return 2
    try:
        for finding in findings:
            print(finding.render())
    except BrokenPipeError:  # e.g. piped into head; keep the exit contract
        _silence_stdout()
    if findings:
        print(
            f"graftlint: {len(findings)} finding(s)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Finding and rule-metadata types — graftlint's typed public surface.

Everything the CLI prints and the tests assert on is a
:class:`Finding`; rules produce them and never print directly, so the
same rule code drives the CLI, the pytest fixtures, and any future
editor integration.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location.

    ``path`` is the path the file was linted AS (fixture tests lint
    snippets under a *virtual* path so path-scoped rules apply);
    ``line``/``col`` are 1-based line and 0-based column, matching
    ``ast`` node coordinates.
    """

    path: str
    line: int
    col: int
    code: str
    message: str

    def render(self) -> str:
        """``path:line:col: CODE message`` — the CLI output line."""
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

"""Per-file analysis context shared by every graftlint rule.

One parse per file: the :class:`FileContext` owns the AST, the
suppression-comment map, and the *traced-body* analysis (which function
bodies execute under ``jax.jit`` tracing or inside a Pallas kernel) that
the dtype- and tracing-hazard rules all need.  Rules stay tiny AST walks
over this shared state.

Traced-body detection is lexical and intentionally conservative:

* functions decorated with ``jax.jit`` / ``jit`` / ``pjit`` (bare or via
  ``functools.partial``),
* functions whose name is passed to a ``jax.jit(...)`` call anywhere in
  the same module,
* Pallas kernels: functions passed as the first argument to
  ``pl.pallas_call`` / ``pallas_call``, or whose name is ``kernel`` /
  ends in ``_kernel`` (this repo's kernel-factory idiom builds ``def
  kernel(...)`` closures and launches them through a shared epilogue, so
  the ``pallas_call`` site only ever sees a parameter name),
* anything lexically nested inside one of the above.

Cross-module tracing (a body built here, jitted elsewhere) is invisible
to a single-file pass; the rules accept that as a false-negative rather
than risk flagging host-side numpy code (``ops/blocks.py`` does heavy
deliberate ``int64`` work on the host).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Union

#: The package whose layout the path-scoped rules understand.
PACKAGE = "hashcat_a5_table_generator_tpu"

#: ``# graftlint: disable=GL001[,GL002...]`` on a line suppresses those
#: codes for that line.
_DISABLE_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9, ]+)")

#: Kernel naming idiom: ``def kernel`` / ``def _md5_kernel`` — but NOT
#: the ``_make_*kernel`` factories, whose bodies are host-side closure
#: prep around the inner ``def kernel``.
_KERNEL_NAME_RE = re.compile(r"^(?!_?make_)(?!_make_).*?(^|_)kernel$")

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """The Name at the root of an Attribute/Subscript/Call chain.

    ``x``, ``x.foo``, ``x[0].bar``, ``x.astype(...)`` all root at ``x``;
    used to decide whether an expression derives from a traced-function
    parameter."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def param_names(fn: FunctionNode) -> Set[str]:
    """All parameter names of a function/lambda."""
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _is_jit_callable(func: ast.AST) -> bool:
    """Does this expression name ``jax.jit`` (or bare ``jit``/``pjit``)?"""
    name = dotted_name(func)
    return name in ("jax.jit", "jit", "pjit", "jax.pjit")


def _partial_of_jit(call: ast.Call) -> bool:
    """``partial(jax.jit, ...)`` / ``functools.partial(jax.jit, ...)``."""
    if dotted_name(call.func) not in ("partial", "functools.partial"):
        return False
    return bool(call.args) and _is_jit_callable(call.args[0])


def _jit_decorated(fn: Union[ast.FunctionDef, ast.AsyncFunctionDef]) -> bool:
    for dec in fn.decorator_list:
        if _is_jit_callable(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jit_callable(dec.func) or _partial_of_jit(dec):
                return True
    return False


@dataclass
class FileContext:
    """Parsed file + shared analyses, handed to every rule."""

    path: str
    source: str
    tree: ast.Module
    #: line -> set of suppressed rule codes on that line
    suppressed: Dict[int, Set[str]] = field(default_factory=dict)
    #: traced roots (jitted functions and pallas kernels)
    traced_roots: List[FunctionNode] = field(default_factory=list)
    #: every node lexically inside a traced root (by id())
    _traced_ids: Set[int] = field(default_factory=set)
    #: union of the enclosing traced functions' parameter names per node
    _traced_params: Dict[int, Set[str]] = field(default_factory=dict)

    # -- path scoping ---------------------------------------------------

    @property
    def posix_path(self) -> str:
        return self.path.replace("\\", "/")

    def _in_package_dir(self, sub: str) -> bool:
        return f"{PACKAGE}/{sub}/" in self.posix_path

    @property
    def in_ops(self) -> bool:
        return self._in_package_dir("ops")

    @property
    def in_tables(self) -> bool:
        return self._in_package_dir("tables")

    @property
    def in_utils(self) -> bool:
        return self._in_package_dir("utils")

    @property
    def in_package(self) -> bool:
        return f"{PACKAGE}/" in self.posix_path

    @property
    def is_library(self) -> bool:
        """Package module that is not a CLI entry point (whose stdout IS
        the candidate stream contract)."""
        if not self.in_package:
            return False
        base = self.posix_path.rsplit("/", 1)[-1]
        return base not in ("cli.py", "__main__.py")

    # -- suppression ----------------------------------------------------

    def is_suppressed(self, line: int, code: str) -> bool:
        return code in self.suppressed.get(line, set())

    # -- traced bodies --------------------------------------------------

    def is_traced(self, node: ast.AST) -> bool:
        """Is this node lexically inside a jitted/Pallas body?"""
        return id(node) in self._traced_ids

    def traced_params_at(self, node: ast.AST) -> Set[str]:
        """Parameter names of the traced function(s) enclosing ``node``
        (empty set when the node is not traced)."""
        return self._traced_params.get(id(node), set())

    def functions(self) -> Iterator[FunctionNode]:
        for node in ast.walk(self.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                yield node


def _jitted_names(tree: ast.Module) -> Set[str]:
    """Names passed to ``jax.jit(...)`` / ``pl.pallas_call(...)`` calls
    anywhere in the module, in any of the three call forms:
    ``jax.jit(fn)``, ``partial(jax.jit, ...)(fn)``, and
    ``partial(jax.jit, fn, ...)``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Name):
            if _is_jit_callable(node.func):
                names.add(first.id)
            elif dotted_name(node.func) in ("pl.pallas_call", "pallas_call"):
                names.add(first.id)
            elif isinstance(node.func, ast.Call) and _partial_of_jit(
                node.func
            ):
                # partial(jax.jit, ...)(fn)
                names.add(first.id)
        if (
            _partial_of_jit(node)
            and len(node.args) >= 2
            and isinstance(node.args[1], ast.Name)
        ):
            # partial(jax.jit, fn, ...): the wrapped target is arg 1.
            names.add(node.args[1].id)
    return names


def _collect_suppressions(source: str) -> Dict[int, Set[str]]:
    suppressed: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            match = _DISABLE_RE.search(tok.string)
            if match:
                codes = {c.strip() for c in match.group(1).split(",")}
                suppressed.setdefault(tok.start[0], set()).update(
                    c for c in codes if c
                )
    except tokenize.TokenError:
        pass
    return suppressed


def build_context(source: str, path: str) -> FileContext:
    """Parse ``source`` (linted as ``path``) into a FileContext.

    Raises ``SyntaxError`` for unparseable files — the CLI reports those
    as hard errors rather than findings."""
    tree = ast.parse(source, filename=path)
    ctx = FileContext(
        path=path,
        source=source,
        tree=tree,
        suppressed=_collect_suppressions(source),
    )

    jitted = _jitted_names(tree)
    for fn in ctx.functions():
        is_root = False
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _jit_decorated(fn):
                is_root = True
            elif fn.name in jitted:
                is_root = True
            elif _KERNEL_NAME_RE.search(fn.name):
                is_root = True
        if is_root:
            ctx.traced_roots.append(fn)

    # Mark everything lexically inside a traced root, accumulating the
    # parameter names of every enclosing function (nested defs inside a
    # kernel still close over the kernel's refs).
    def mark(node: ast.AST, params: Set[str]) -> None:
        ctx._traced_ids.add(id(node))
        ctx._traced_params.setdefault(id(node), set()).update(params)
        for child in ast.iter_child_nodes(node):
            child_params = params
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                child_params = params | param_names(child)
            mark(child, child_params)

    for root in ctx.traced_roots:
        mark(root, param_names(root))

    return ctx


def module_imports(tree: ast.Module) -> Iterator[Union[ast.Import, ast.ImportFrom]]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node


def public_top_level_functions(
    tree: ast.Module,
) -> Iterator[ast.FunctionDef]:
    """Top-level ``def``s not starting with ``_`` (the module's public
    API surface)."""
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and not node.name.startswith("_"):
            yield node


def call_keywords(call: ast.Call) -> Set[str]:
    return {kw.arg for kw in call.keywords if kw.arg is not None}


def literal_ints(tree: ast.AST) -> Iterator[ast.Constant]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            yield node


def iter_calls(tree: ast.AST) -> Iterator[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def first_line(doc: Optional[str]) -> str:
    return (doc or "").strip().splitlines()[0] if doc else ""


def walk_scoped(
    roots: Sequence[ast.AST],
) -> Iterator[ast.AST]:
    for root in roots:
        yield from ast.walk(root)

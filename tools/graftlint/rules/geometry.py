"""Geometry hygiene: the autotune seam owns geometry numbers in ``runtime/``.

PERF.md §29 made launch geometry a resolved artifact — explicit flag >
per-device-kind autotune profile > ``tune.builtin_geometry`` — so a
throughput number is never ambiguous about where its geometry came
from.  A hardcoded ``lanes = 1 << 20`` (or ``num_blocks=1024`` keyword)
in a runtime module bypasses that seam: it silently pins a geometry the
profile can never override and the provenance stamp never reports.

The rule flags geometry-named bindings to integer literals (including
``1 << n`` / literal products) in ``runtime/`` — assignments, call
keywords, and function defaults alike.  ``tune.py`` IS the seam
(``builtin_geometry`` lives there), and ``sweep.py`` keeps its
grandfathered ``SweepConfig`` dataclass defaults (the library-caller
contract predating the autotuner); the list is shrink-only.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..context import PACKAGE, FileContext
from ..findings import Finding
from .base import Rule

#: Binding names that denote launch geometry (SweepConfig knobs and
#: their local-variable spellings).
_GEOMETRY_NAMES = frozenset(
    {"lanes", "num_blocks", "blocks", "block_stride", "stride",
     "superstep"}
)

#: The geometry-resolution seam itself — builtin_geometry and the arm
#: matrix are the ONE sanctioned home for geometry numbers.
_SEAM_SUFFIX = "/runtime/tune.py"

#: Pre-§29 geometry literals kept for the library-caller contract
#: (``SweepConfig``'s dataclass defaults).  Shrink-only: new runtime
#: modules get no pass, and entries leave as the defaults migrate to
#: ``tune.builtin_geometry``.
_GRANDFATHERED = (
    f"{PACKAGE}/runtime/sweep.py",
)


def _is_int_literal(node: ast.AST) -> bool:
    """An int constant, or arithmetic over int constants (``1 << 17``,
    ``4 * 1024``) — the spellings geometry numbers are written in."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, int) and not isinstance(
            node.value, bool
        )
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.LShift, ast.Mult, ast.FloorDiv, ast.Add)
    ):
        return _is_int_literal(node.left) and _is_int_literal(node.right)
    return False


class HardcodedGeometry(Rule):
    code = "GL014"
    name = "hardcoded-geometry"
    summary = (
        "geometry literal (lanes/num_blocks/stride/superstep) in "
        "runtime/ outside the autotune resolution seam"
    )
    rationale = (
        "Launch geometry resolves explicit flag > autotune profile > "
        "tune.builtin_geometry (PERF.md §29); a literal bound to a "
        "geometry name in runtime/ pins a value the profile can never "
        "override and the geometry_source stamp never reports. Leave "
        "the knob None and let the Sweep resolve it, or add the number "
        "to tune.builtin_geometry / the tune matrix."
    )

    def applies(self, ctx: FileContext) -> bool:
        path = ctx.posix_path
        if f"{PACKAGE}/runtime/" not in path:
            return False
        if path.endswith(_SEAM_SUFFIX):
            return False
        return not any(path.endswith(g) for g in _GRANDFATHERED)

    def _bindings(self, node: ast.AST):
        """(name, value, lineno, col) pairs this node binds."""
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    yield t.id, node.value, node.lineno, node.col_offset
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                yield (node.target.id, node.value, node.lineno,
                       node.col_offset)
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg is not None:
                    yield (kw.arg, kw.value, kw.value.lineno,
                           kw.value.col_offset)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            pos = a.posonlyargs + a.args
            for arg, default in zip(pos[len(pos) - len(a.defaults):],
                                    a.defaults):
                yield arg.arg, default, default.lineno, default.col_offset
            for arg, default in zip(a.kwonlyargs, a.kw_defaults):
                if default is not None:
                    yield (arg.arg, default, default.lineno,
                           default.col_offset)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            for name, value, lineno, col in self._bindings(node):
                if name in _GEOMETRY_NAMES and _is_int_literal(value):
                    yield self.finding(
                        ctx, lineno, col,
                        f"hardcoded geometry literal for '{name}'; "
                        "geometry resolves explicit > profile > "
                        "builtin (runtime/tune.py) — leave it None or "
                        "move the number into the resolution seam",
                    )

"""Dtype-promotion hazards in the uint32 hash arithmetic (``ops/``).

The engine's correctness contract is byte-exact parity with the Go
reference; every hash kernel works in uint32 lanes.  NumPy/JAX silently
promote mixed-width arithmetic, so a Python int literal that does not
fit uint32 — or a float literal reaching a kernel — produces an
int64/float intermediate that truncates differently from the reference
(or errors only on TPU where x64 is disabled).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..context import FileContext, dotted_name, literal_ints
from ..findings import Finding
from .base import Rule

#: uint32 ceiling: literals above this cannot be uint32 operands.
_U32_MAX = 0xFFFFFFFF

#: dtype constructors/names that widen uint32 lanes when they appear in
#: traced kernel arithmetic.
_WIDE_DTYPES = frozenset(
    {
        "np.int64",
        "np.uint64",
        "np.float64",
        "np.float32",
        "jnp.int64",
        "jnp.uint64",
        "jnp.float64",
        "jnp.float32",
    }
)


class UnmaskedWideInt(Rule):
    code = "GL001"
    name = "unmasked-wide-int"
    summary = (
        "integer literal wider than uint32 in an ops/ module"
    )
    rationale = (
        "ops/ kernels do uint32 hash arithmetic; a literal > 0xFFFFFFFF "
        "promotes the whole expression to int64 (or raises on TPU with "
        "x64 disabled), silently breaking byte-exact parity with the Go "
        "reference. Mask host-side (utils/) or split the constant."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_ops

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in literal_ints(ctx.tree):
            if node.value > _U32_MAX:
                yield self.finding(
                    ctx,
                    node.lineno,
                    node.col_offset,
                    f"int literal {node.value:#x} does not fit uint32; "
                    "uint32 hash arithmetic would promote to int64 "
                    "(mask host-side or split the constant)",
                )


class FloatLiteralInKernel(Rule):
    code = "GL002"
    name = "float-in-kernel"
    summary = (
        "float literal or widening dtype inside a jitted/Pallas body "
        "in ops/"
    )
    rationale = (
        "The hash pipeline is integer-only end to end; a float literal "
        "(or an int64/float dtype constructor) inside a traced ops/ "
        "body promotes uint32 lanes and diverges from the reference "
        "bit patterns. Host-side ops/ code (e.g. blocks.py int64 rank "
        "math) is deliberately out of scope."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_ops

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not ctx.is_traced(node):
                continue
            if isinstance(node, ast.Constant) and isinstance(
                node.value, float
            ):
                yield self.finding(
                    ctx,
                    node.lineno,
                    node.col_offset,
                    f"float literal {node.value!r} inside a traced "
                    "kernel body (integer-only uint32 pipeline)",
                )
            elif isinstance(node, ast.Attribute):
                name = dotted_name(node)
                if name in _WIDE_DTYPES:
                    yield self.finding(
                        ctx,
                        node.lineno,
                        node.col_offset,
                        f"widening dtype {name} inside a traced kernel "
                        "body (uint32 lanes would promote)",
                    )

"""Rule protocol: path scoping + one AST pass over a FileContext."""

from __future__ import annotations

import abc
from typing import Iterator

from ..context import FileContext
from ..findings import Finding


class Rule(abc.ABC):
    """One static check.  Subclasses set the class metadata and
    implement :meth:`applies` (path scope) and :meth:`check`."""

    #: Stable id, ``GLnnn``; fixture files and suppression comments key
    #: on it.
    code: str = ""
    #: Short kebab-case name for ``--list-rules``.
    name: str = ""
    #: One-line description of what is flagged.
    summary: str = ""
    #: Why this matters *in this repo* (shown by ``--list-rules -v``).
    rationale: str = ""

    @abc.abstractmethod
    def applies(self, ctx: FileContext) -> bool:
        """Whether this rule runs on the file at all (path scope)."""

    @abc.abstractmethod
    def check(self, ctx: FileContext) -> Iterator[Finding]:
        """Yield findings; suppression filtering happens in the driver."""

    def finding(
        self, ctx: FileContext, line: int, col: int, message: str
    ) -> Finding:
        return Finding(
            path=ctx.path, line=line, col=col, code=self.code, message=message
        )

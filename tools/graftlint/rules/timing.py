"""Timing hygiene: the telemetry registry owns timing in ``runtime/``.

PERF.md §21 moved wall-clock instrumentation into
``runtime/telemetry.py`` (span timeline + registry histograms); ad-hoc
``t0 = time.monotonic(); acc += time.monotonic() - t0`` accumulation
scattered through the runtime is exactly the drift the registry exists
to end — each pattern re-invents merge/report semantics and none of it
is visible to the ``metrics`` op or ``--metrics-json``.

The rule flags timing *accumulation* (a subtraction or augmented
assignment involving a clock call), not bare stamps: passing a single
``time.monotonic()`` reading through a deque as data — the drive
loop's dispatch stamp — is the sanctioned pattern (the arithmetic
happens inside the timeline, at the fetch boundary).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..context import PACKAGE, FileContext, dotted_name
from ..findings import Finding
from .base import Rule

#: Clock reads whose arithmetic belongs to the registry.
_CLOCK_CALLS = frozenset(
    {"time.monotonic", "time.time", "time.perf_counter",
     "monotonic", "perf_counter"}
)

#: The module timing belongs to.
_TELEMETRY_SUFFIX = "/runtime/telemetry.py"

#: Pre-§21 runtime modules with existing accumulation patterns
#: (wall_s bookkeeping, adaptive drain cycles, overlap windows) —
#: grandfathered rather than rewritten in the same PR that lands the
#: rule.  New runtime modules (and new files) get no pass; shrink this
#: list as the patterns migrate into the timeline.
_GRANDFATHERED = (
    f"{PACKAGE}/runtime/sweep.py",
    f"{PACKAGE}/runtime/bucketed.py",
)


def _is_clock_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and dotted_name(node.func) in _CLOCK_CALLS
    )


def _has_clock_arith(node: ast.AST) -> bool:
    """A subtraction with a clock call on either side anywhere under
    ``node`` — the elapsed-seconds idiom."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Sub):
            if _is_clock_call(sub.left) or _is_clock_call(sub.right):
                return True
    return False


class TimingAccumulation(Rule):
    code = "GL013"
    name = "timing-accumulation"
    summary = (
        "direct time.monotonic()/time.time() timing accumulation in "
        "runtime/ outside telemetry.py (the registry owns timing)"
    )
    rationale = (
        "Scattered elapsed-time arithmetic re-invents merge and report "
        "semantics per call site and is invisible to the metrics "
        "registry (PERF.md §21). Record through the SpanTimeline / "
        "registry histograms instead; bare clock stamps passed as data "
        "are fine — only the arithmetic is the registry's job."
    )

    def applies(self, ctx: FileContext) -> bool:
        path = ctx.posix_path
        if f"{PACKAGE}/runtime/" not in path:
            return False
        if path.endswith(_TELEMETRY_SUFFIX):
            return False
        return not any(path.endswith(g) for g in _GRANDFATHERED)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AugAssign):
                if _has_clock_arith(node.value) or _is_clock_call(
                    node.value
                ):
                    yield self.finding(
                        ctx, node.lineno, node.col_offset,
                        "timing accumulation outside the telemetry "
                        "registry; record via runtime/telemetry.py "
                        "(SpanTimeline.record_fetch / histogram "
                        ".observe)",
                    )
            elif isinstance(node, ast.Assign):
                if _has_clock_arith(node.value):
                    yield self.finding(
                        ctx, node.lineno, node.col_offset,
                        "elapsed-time arithmetic outside the telemetry "
                        "registry; record via runtime/telemetry.py "
                        "(the registry owns timing; bare stamps as "
                        "data are fine)",
                    )

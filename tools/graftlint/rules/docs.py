"""Shape/dtype docstring contract on the public op surface.

Every public function in ``ops/`` is a tensor program whose caller must
know exact shapes and dtypes — the kernels are byte-layout-sensitive
(packed uint32 words, ``[B, M]`` match matrices, ``[NB]`` block
cursors).  The repo's convention documents these inline (``uint8 [B,
L]`` etc.); this rule makes the convention load-bearing.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..context import FileContext, public_top_level_functions
from ..findings import Finding
from .base import Rule

#: Evidence that a docstring states shapes/dtypes: a bracketed shape
#: (``[B, L]``), an explicit dtype word, or shape/dtype/scalar prose.
_SHAPE_DTYPE_RE = re.compile(
    r"\[[^\]]+\]"
    r"|\b(u?int(8|16|32|64)|float(16|32|64)|bool|bfloat16)\b"
    r"|\b(shape[sd]?|dtypes?|scalar|array|bytes)\b",
    re.IGNORECASE,
)


class OpDocstringContract(Rule):
    code = "GL008"
    name = "op-docstring-contract"
    summary = (
        "public ops/ function without a shape/dtype-stating docstring"
    )
    rationale = (
        "ops/ functions pass byte-layout-sensitive tensors (packed "
        "uint32 words, [B, M] match matrices); an undocumented shape "
        "contract is how dtype drift between the XLA and Pallas paths "
        "slips in. State shapes/dtypes like the rest of the package: "
        "``uint8 [B, L]``."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_ops

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in public_top_level_functions(ctx.tree):
            doc = ast.get_docstring(fn)
            if not doc:
                yield self.finding(
                    ctx,
                    fn.lineno,
                    fn.col_offset,
                    f"public op {fn.name}() has no docstring; state "
                    "its shape/dtype contract",
                )
                continue
            # Inline `# dtype [shape]` comments on the signature count:
            # the repo annotates parameters that way.  The header is the
            # signature span BEFORE the docstring statement (computed
            # from the docstring node's line, not quote-style splitting,
            # so '''-quoted docstrings can't leak body text into it).
            seg = ast.get_source_segment(ctx.source, fn) or ""
            doc_stmt = fn.body[0]  # the docstring Expr (doc is non-empty)
            header = "\n".join(
                seg.splitlines()[: max(doc_stmt.lineno - fn.lineno, 0)]
            )
            if not _SHAPE_DTYPE_RE.search(doc) and not _SHAPE_DTYPE_RE.search(
                header
            ):
                yield self.finding(
                    ctx,
                    fn.lineno,
                    fn.col_offset,
                    f"docstring of public op {fn.name}() states no "
                    "shape/dtype contract (no [shape] or dtype word)",
                )

"""``jax.jit`` call sites missing ``static_argnames`` for config params.

Passing a config-like value (``algo``, ``out_width``, ``block_stride``,
...) as a traced argument does not error — JAX hashes the abstract
value, so every distinct config retraces and recompiles the program.
On the sweep hot path a recompile is tens of seconds of TPU stall; the
repo's convention is that config travels as static keyword arguments
(or is closed over by a builder, the ``make_*_step`` idiom).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Union

from ..context import (
    FileContext,
    call_keywords,
    dotted_name,
)
from ..findings import Finding
from .base import Rule

#: Parameter names that are launch-static configuration in this repo.
_CONFIG_PARAM_RE = re.compile(
    r"^(algo|mode|interpret|windowed|radix2|scalar_units|k_opts"
    r"|num_(lanes|blocks|slots|segments)"
    r"|(block|out|token|key|val)_(stride|width)"
    r"|(min|max)_(substitute|options|val_len|key_len))$"
)

_FuncDef = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]


def _config_params(fn: _FuncDef) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    return [n for n in names if _CONFIG_PARAM_RE.match(n)]


def _module_functions(tree: ast.Module) -> Dict[str, _FuncDef]:
    """Top-level defs and ``name = lambda ...`` assignments."""
    out: Dict[str, _FuncDef] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Lambda
        ):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = node.value
    return out


def _is_jit_call(call: ast.Call) -> bool:
    return dotted_name(call.func) in ("jax.jit", "jit", "pjit", "jax.pjit")


class JitMissingStaticArgnames(Rule):
    code = "GL006"
    name = "jit-missing-static-argnames"
    summary = (
        "jax.jit over a function with config-like params but no "
        "static_argnames/static_argnums"
    )
    rationale = (
        "Config params (algo/mode/out_width/...) traced as device "
        "values make every distinct config a fresh trace+compile — a "
        "silent multi-second stall per sweep configuration. Mark them "
        "static or close over them in a builder (make_*_step idiom)."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_package

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        functions = _module_functions(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not _is_jit_call(node):
                continue
            if {"static_argnames", "static_argnums"} & call_keywords(node):
                continue
            target: Optional[_FuncDef] = None
            target_desc = ""
            if node.args and isinstance(node.args[0], ast.Name):
                target = functions.get(node.args[0].id)
                target_desc = node.args[0].id
            elif node.args and isinstance(node.args[0], ast.Lambda):
                target = node.args[0]
                target_desc = "<lambda>"
            if target is None:
                continue  # built elsewhere: the builder idiom, not checkable
            config = _config_params(target)
            if config:
                yield self.finding(
                    ctx,
                    node.lineno,
                    node.col_offset,
                    f"jax.jit({target_desc}) traces config param(s) "
                    f"{', '.join(repr(c) for c in config)}; mark them "
                    "static_argnames or close over them in a builder",
                )

        # Decorator form: @jax.jit / @partial(jax.jit, ...) directly on a
        # def with config-like params.
        for name, fn in functions.items():
            if isinstance(fn, ast.Lambda):
                continue
            for dec in fn.decorator_list:
                has_static = False
                is_jit = False
                if dotted_name(dec) in ("jax.jit", "jit", "pjit", "jax.pjit"):
                    is_jit = True
                elif isinstance(dec, ast.Call):
                    inner = dec.args[0] if dec.args else None
                    if _is_jit_call(dec) or (
                        dotted_name(dec.func)
                        in ("partial", "functools.partial")
                        and inner is not None
                        and dotted_name(inner)
                        in ("jax.jit", "jit", "pjit", "jax.pjit")
                    ):
                        is_jit = True
                        has_static = bool(
                            {"static_argnames", "static_argnums"}
                            & call_keywords(dec)
                        )
                if is_jit and not has_static:
                    config = _config_params(fn)
                    if config:
                        yield self.finding(
                            ctx,
                            fn.lineno,
                            fn.col_offset,
                            f"@jax.jit on {name}() traces config "
                            f"param(s) {', '.join(repr(c) for c in config)};"
                            " add static_argnames",
                        )

"""Rule registry: every graftlint rule, in code order.

Each rule module groups one hazard family; add new rules by appending
to the family module and they are picked up here.  ``ALL_RULES`` is the
single source the CLI, the public API, and the fixture tests iterate.
"""

from __future__ import annotations

from typing import Dict, List

from .base import Rule
from .docs import OpDocstringContract
from .dtype import FloatLiteralInKernel, UnmaskedWideInt
from .envvars import EnvVarSprawl
from .geometry import HardcodedGeometry
from .hygiene import MutableDefaultArg, Nondeterminism, StdoutPrint
from .jit import JitMissingStaticArgnames
from .timing import TimingAccumulation
from .tracing import (
    HostEscapeInTrace,
    HostSyncInLoopBody,
    LoopOverTracer,
    NumpyInTrace,
)

ALL_RULES: List[Rule] = [
    UnmaskedWideInt(),
    FloatLiteralInKernel(),
    HostEscapeInTrace(),
    NumpyInTrace(),
    LoopOverTracer(),
    JitMissingStaticArgnames(),
    Nondeterminism(),
    OpDocstringContract(),
    StdoutPrint(),
    MutableDefaultArg(),
    HostSyncInLoopBody(),
    EnvVarSprawl(),
    TimingAccumulation(),
    HardcodedGeometry(),
]

RULES_BY_CODE: Dict[str, Rule] = {rule.code: rule for rule in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_CODE", "Rule"]

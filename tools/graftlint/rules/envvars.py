"""Env-knob hygiene: the ``A5GEN_*`` surface has ONE read point.

The engine's escape hatches (``A5GEN_PALLAS``, ``A5GEN_SUPERSTEP``,
``A5GEN_CASCADE_CLOSE``, ``A5GEN_DCN_TIMEOUT``, …) each started as a
one-off ``os.environ`` read; sprawled reads make the knob surface
unauditable and let "off" vocabularies drift between subsystems.
``runtime/env.py`` is now the single accessor — every library read goes
through it.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..context import FileContext, dotted_name
from ..findings import Finding
from .base import Rule

#: The accessor module — the one place direct reads are the point.
_ACCESSOR_SUFFIX = "/runtime/env.py"

#: Call forms that read the process environment.
_ENV_GET_CALLS = ("os.environ.get", "environ.get", "os.getenv", "getenv")

#: Subscript bases that read the process environment.
_ENV_MAPS = ("os.environ", "environ")


#: Grandfathered pre-``A5GEN_`` knobs (mirrors ``runtime/env.py``).
_LEGACY_KNOBS = frozenset({"A5_NATIVE"})


def _env_name_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_knob(name: Optional[str]) -> bool:
    return name is not None and (
        name.startswith("A5GEN_") or name in _LEGACY_KNOBS
    )


class EnvVarSprawl(Rule):
    code = "GL012"
    name = "env-var-sprawl"
    summary = (
        "direct os.environ/os.getenv read of an A5GEN_*/A5_NATIVE knob "
        "outside runtime/env.py"
    )
    rationale = (
        "Every A5GEN_* escape hatch must read through the "
        "runtime/env.py accessor: one grep-able knob surface, one "
        "shared off-spelling vocabulary, and graftaudit/bench can "
        "reason about what the environment changes. Writes (probe "
        "scripts and tests pinning a configuration) are fine — only "
        "reads sprawl."
    )

    def applies(self, ctx: FileContext) -> bool:
        # Everything we lint except the accessor itself; fixture tests
        # lint under virtual package paths, so path scoping is enough.
        return not ctx.posix_path.endswith(_ACCESSOR_SUFFIX)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                if dotted_name(node.func) not in _ENV_GET_CALLS:
                    continue
                if not node.args:
                    continue
                name = _env_name_literal(node.args[0])
                if _is_knob(name):
                    yield self.finding(
                        ctx,
                        node.lineno,
                        node.col_offset,
                        f"direct read of {name}; use the "
                        "runtime/env.py accessor (read_env/env_str/"
                        "env_is)",
                    )
            elif isinstance(node, ast.Subscript):
                if not isinstance(node.ctx, ast.Load):
                    continue  # writes/deletes are probe/test plumbing
                if dotted_name(node.value) not in _ENV_MAPS:
                    continue
                name = _env_name_literal(node.slice)
                if _is_knob(name):
                    yield self.finding(
                        ctx,
                        node.lineno,
                        node.col_offset,
                        f"direct read of {name}; use the "
                        "runtime/env.py accessor (read_env/env_str/"
                        "env_is)",
                    )

"""Repo-hygiene rules: determinism, the stdout contract, defaults.

The candidate engine must be bit-reproducible (checkpoint/resume and
multi-host stripes assume identical re-enumeration) and its stdout is a
*data channel* — the reference streams raw candidate bytes, so a stray
``print()`` corrupts the wordlist a consumer pipes into hashcat.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..context import FileContext, call_keywords, dotted_name
from ..findings import Finding
from .base import Rule

#: Modules whose import into deterministic code is a red flag.
_NONDET_MODULES = frozenset({"random", "secrets", "uuid"})

#: Call prefixes that read wall clock or entropy.
_NONDET_CALLS = (
    "time.time",
    "time.time_ns",
    "time.perf_counter",
    "time.monotonic",
    "datetime.now",
    "datetime.utcnow",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "os.urandom",
    "np.random",
    "numpy.random",
    "random.",
    "secrets.",
    "uuid.",
)


class Nondeterminism(Rule):
    code = "GL007"
    name = "nondeterminism"
    summary = (
        "entropy/wall-clock use in deterministic packages "
        "(ops/, tables/, utils/)"
    )
    rationale = (
        "Enumeration order and table compilation must be bit-stable: "
        "checkpoints resume by (word, rank) cursor and multi-host "
        "stripes re-derive their slice independently. Randomness or "
        "time-dependent behavior in these layers silently breaks "
        "resume parity. (runtime/ progress reporting may read clocks; "
        "it is out of scope.)"
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_ops or ctx.in_tables or ctx.in_utils

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    base = alias.name.split(".", 1)[0]
                    if base in _NONDET_MODULES:
                        yield self.finding(
                            ctx,
                            node.lineno,
                            node.col_offset,
                            f"import of {alias.name!r} in a "
                            "deterministic package",
                        )
            elif isinstance(node, ast.ImportFrom):
                base = (node.module or "").split(".", 1)[0]
                if base in _NONDET_MODULES:
                    yield self.finding(
                        ctx,
                        node.lineno,
                        node.col_offset,
                        f"import from {node.module!r} in a "
                        "deterministic package",
                    )
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name is None:
                    continue
                if name.startswith(_NONDET_CALLS):
                    yield self.finding(
                        ctx,
                        node.lineno,
                        node.col_offset,
                        f"{name}() reads entropy/wall clock in a "
                        "deterministic package",
                    )


class StdoutPrint(Rule):
    code = "GL009"
    name = "stdout-print"
    summary = "print() without file= in a library module"
    rationale = (
        "stdout is the candidate byte stream (reference parity: raw "
        "bytes piped into hashcat); a bare print() interleaves text "
        "with candidate data and corrupts the wordlist. Diagnostics "
        "must go to stderr (file=sys.stderr); cli.py/__main__.py own "
        "their stdout and are exempt."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.is_library

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and "file" not in call_keywords(node)
            ):
                yield self.finding(
                    ctx,
                    node.lineno,
                    node.col_offset,
                    "print() without file= writes to the candidate "
                    "stdout stream; use file=sys.stderr",
                )


class MutableDefaultArg(Rule):
    code = "GL010"
    name = "mutable-default-arg"
    summary = "mutable default argument (list/dict/set literal or call)"
    rationale = (
        "A mutable default is created once at def time and shared "
        "across calls; sweep/runtime objects are long-lived, so state "
        "leaks across launches and table reloads."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_package or "tools/" in ctx.posix_path

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ctx.functions():
            if isinstance(fn, ast.Lambda):
                continue
            defaults = list(fn.args.defaults) + [
                d for d in fn.args.kw_defaults if d is not None
            ]
            for default in defaults:
                bad = isinstance(
                    default, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)
                ) or (
                    isinstance(default, ast.Call)
                    and dotted_name(default.func)
                    in ("list", "dict", "set", "bytearray")
                )
                if bad:
                    yield self.finding(
                        ctx,
                        default.lineno,
                        default.col_offset,
                        f"mutable default argument in {fn.name}(); "
                        "use None and construct inside the body",
                    )

"""Host-side escapes inside traced (jit / Pallas) bodies.

A jitted body runs once at trace time over abstract tracers; anything
that forces a concrete value — ``.item()``, host numpy on a tracer, a
Python loop iterating a tracer — either raises ``TracerArrayConversion``
at trace time or (worse) silently bakes a trace-time constant into the
compiled program, which for this engine means a wrong table for every
launch after the first.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from ..context import FileContext, dotted_name, param_names, root_name
from ..findings import Finding
from .base import Rule

#: Methods that force a concrete host value out of a device array.
_ESCAPE_METHODS = frozenset(
    {"item", "tolist", "block_until_ready", "copy_to_host_async"}
)

#: Builtins that concretize a tracer when applied to one.  ``len()`` is
#: deliberately absent: on a JAX array it returns the static leading
#: dimension and is trace-safe.
_ESCAPE_BUILTINS = frozenset({"int", "float", "bool"})

#: numpy module aliases as imported across this repo.
_NP_ALIASES = frozenset({"np", "numpy", "onp"})


def _param_rooted(node: ast.AST, params: Set[str]) -> Optional[str]:
    """The traced parameter an expression derives from, if any."""
    root = root_name(node)
    return root if root in params else None


#: Attribute accesses on a tracer that yield STATIC (trace-safe) values.
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "weak_type"})


def _tracer_valued(node: ast.AST, params: Set[str]) -> Optional[str]:
    """The traced parameter an expression's VALUE derives from — None
    when the chain passes through a static attribute (``x.shape[0]`` is
    a Python int at trace time, not a tracer)."""
    while True:
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return None
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            return node.id if node.id in params else None
        else:
            return None


def _loop_condition_tracer(test: ast.AST, params: Set[str]) -> Optional[str]:
    """A traced parameter the loop condition's value depends on, if any.

    Checks the bare test plus the operands of top-level Compare/BoolOp/
    UnaryOp chains and the arguments of calls (``jnp.any(mask)``) —
    ``len(xs)`` is exempt (static leading dim)."""
    stack = [test]
    while stack:
        node = stack.pop()
        root = _tracer_valued(node, params)
        if root is not None:
            return root
        if isinstance(node, ast.Compare):
            stack.append(node.left)
            stack.extend(node.comparators)
        elif isinstance(node, ast.BoolOp):
            stack.extend(node.values)
        elif isinstance(node, ast.UnaryOp):
            stack.append(node.operand)
        elif isinstance(node, ast.Call):
            if not (isinstance(node.func, ast.Name)
                    and node.func.id == "len"):
                stack.extend(node.args)
    return None


class HostEscapeInTrace(Rule):
    code = "GL003"
    name = "host-escape-in-trace"
    summary = (
        ".item()/.tolist()/int()/float() on a tracer inside a "
        "jitted/Pallas body"
    )
    rationale = (
        "Concretizing a tracer raises at trace time at best; at worst "
        "(e.g. on a weak-typed scalar) it bakes the first launch's "
        "value into the compiled program and every later launch "
        "silently reuses it. Hot-path wrappers must pull host values "
        "BEFORE entering the traced body."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_package

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not ctx.is_traced(node):
                continue
            params = ctx.traced_params_at(node)
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _ESCAPE_METHODS
                and not node.args
            ):
                yield self.finding(
                    ctx,
                    node.lineno,
                    node.col_offset,
                    f".{func.attr}() inside a traced body forces a "
                    "host value out of a tracer",
                )
            elif (
                isinstance(func, ast.Name)
                and func.id in _ESCAPE_BUILTINS
                and len(node.args) == 1
                and _param_rooted(node.args[0], params)
            ):
                root = _param_rooted(node.args[0], params)
                yield self.finding(
                    ctx,
                    node.lineno,
                    node.col_offset,
                    f"{func.id}() applied to traced argument {root!r} "
                    "concretizes a tracer inside a jitted body",
                )


class NumpyInTrace(Rule):
    code = "GL004"
    name = "numpy-in-trace"
    summary = "host numpy applied to a traced argument in a jitted body"
    rationale = (
        "np.* on a tracer either raises TracerArrayConversionError or "
        "constant-folds at trace time — the launch-invariant result of "
        "the FIRST launch gets compiled in. Static precomputes on "
        "Python/np constants inside kernels are fine and not flagged; "
        "only calls whose arguments derive from traced parameters are."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_package

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not ctx.is_traced(node):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            alias = name.split(".", 1)[0]
            if alias not in _NP_ALIASES:
                continue
            params = ctx.traced_params_at(node)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                root = _param_rooted(arg, params)
                if root is not None:
                    yield self.finding(
                        ctx,
                        node.lineno,
                        node.col_offset,
                        f"{name}(...) applied to traced argument "
                        f"{root!r}: host numpy does not trace (use jnp, "
                        "or hoist to the host wrapper)",
                    )
                    break


#: ``lax`` loop combinators and the (positional index, keyword name) of
#: each per-iteration function argument — the functions whose purity the
#: superstep executor (and any future scan) depends on.  Keyword names
#: are jax's own signature names, so keyword-style calls resolve too.
_LAX_LOOP_BODIES = {
    "scan": ((0, "f"),),
    "while_loop": ((0, "cond_fun"), (1, "body_fun")),
    "fori_loop": ((2, "body_fun"),),
}

_LAX_PREFIXES = ("lax", "jax.lax")


def _scope_defs(scope: ast.AST):
    """FunctionDefs defined DIRECTLY in ``scope`` (descending through
    plain statements, pruning at nested function/lambda bodies) — the
    defs a bare name used in that scope can lexically resolve to."""
    out = []
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(node)
            continue  # a nested def's own body is a different scope
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _resolve_body_name(name: str, site: ast.AST, parents) -> list:
    """Lexical resolution of a loop-body reference: walk the call site's
    enclosing function scopes outward (then the module) and return the
    same-named defs of the FIRST scope that has any — never defs that
    merely share the name inside unrelated functions."""
    scope = parents.get(id(site))
    while scope is not None:
        if isinstance(
            scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)
        ):
            defs = [d for d in _scope_defs(scope) if d.name == name]
            if defs:
                return defs
        scope = parents.get(id(scope))
    return []


def _loop_bodies(tree: ast.Module):
    """Loop-body function nodes passed to ``lax.scan`` /
    ``lax.while_loop`` / ``lax.fori_loop`` anywhere in the module:
    inline lambdas at the call sites plus named defs resolved through
    the call site's lexical scope chain."""
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    bodies = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        head, _, tail = name.rpartition(".")
        if tail not in _LAX_LOOP_BODIES or (
            head and head not in _LAX_PREFIXES
        ):
            continue
        kwmap = {kw.arg: kw.value for kw in node.keywords}
        for pos, kwname in _LAX_LOOP_BODIES[tail]:
            arg = (node.args[pos] if pos < len(node.args)
                   else kwmap.get(kwname))
            if isinstance(arg, ast.Lambda):
                bodies.append(arg)
            elif isinstance(arg, ast.Name):
                bodies.extend(_resolve_body_name(arg.id, node, parents))
    return bodies


class HostSyncInLoopBody(Rule):
    code = "GL011"
    name = "host-sync-in-loop-body"
    summary = (
        "int()/.item()/np.asarray()/block_until_ready() inside a "
        "lax.scan/while_loop/fori_loop body"
    )
    rationale = (
        "A lax loop body runs entirely on device; forcing a host value "
        "out of its carry or activations (int(), .item(), np.asarray, "
        "block_until_ready) raises at trace time at best and silently "
        "bakes a first-iteration constant in at worst. The superstep "
        "executor's whole point is that NO host sync happens between "
        "scan steps — the one fetch per superstep is the completion "
        "barrier; keep loop bodies pure and fetch after the loop."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_package

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        seen: Set[int] = set()
        for fn in _loop_bodies(ctx.tree):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            yield from self._scan_body(ctx, fn)

    def _scan_body(self, ctx: FileContext, fn) -> Iterator[Finding]:
        params = param_names(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _ESCAPE_METHODS
                and not node.args
            ):
                yield self.finding(
                    ctx, node.lineno, node.col_offset,
                    f".{func.attr}() inside a lax loop body is a "
                    "host sync per iteration; fetch once after the "
                    "loop instead",
                )
            elif (
                isinstance(func, ast.Name)
                and func.id in _ESCAPE_BUILTINS
                and len(node.args) == 1
                and _param_rooted(node.args[0], params)
            ):
                yield self.finding(
                    ctx, node.lineno, node.col_offset,
                    f"{func.id}() on loop-carried value "
                    f"{root_name(node.args[0])!r} inside a lax loop "
                    "body concretizes the carry per iteration",
                )
            else:
                name = dotted_name(func)
                if name is None:
                    continue
                alias = name.split(".", 1)[0]
                if alias not in _NP_ALIASES:
                    continue
                for arg in (list(node.args)
                            + [kw.value for kw in node.keywords]):
                    if _param_rooted(arg, params) is not None:
                        yield self.finding(
                            ctx, node.lineno, node.col_offset,
                            f"{name}(...) on loop-carried value "
                            "inside a lax loop body forces a host "
                            "round trip per iteration (use jnp)",
                        )
                        break


class LoopOverTracer(Rule):
    code = "GL005"
    name = "loop-over-tracer"
    summary = "Python for/while loop iterating a traced argument"
    rationale = (
        "A Python loop over a tracer unrolls over its (concrete) length "
        "at best and raises at worst; per-element iteration belongs in "
        "lax.fori_loop/scan or vectorized lane math. Loops over "
        "range(static) — the kernels' round-unroll idiom — are fine."
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.in_package

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not ctx.is_traced(node):
                continue
            if isinstance(node, ast.For):
                params = ctx.traced_params_at(node)
                if isinstance(node.iter, ast.Call):
                    # range(n)/zip(a, b)/enumerate(xs): the loop bound
                    # itself must be static — range(x.shape[0]) is fine,
                    # range(n) over a traced scalar is not.
                    for arg in node.iter.args:
                        root = _tracer_valued(arg, params)
                        if root is not None:
                            yield self.finding(
                                ctx,
                                node.lineno,
                                node.col_offset,
                                f"for-loop bound derives from traced "
                                f"argument {root!r}; use "
                                "lax.fori_loop/scan or a static shape",
                            )
                            break
                    continue
                root = _tracer_valued(node.iter, params)
                if root is not None:
                    yield self.finding(
                        ctx,
                        node.lineno,
                        node.col_offset,
                        f"for-loop iterates traced argument {root!r}; "
                        "use lax.fori_loop/scan or vectorize",
                    )
            elif isinstance(node, ast.While):
                params = ctx.traced_params_at(node)
                root = _loop_condition_tracer(node.test, params)
                if root is not None:
                    yield self.finding(
                        ctx,
                        node.lineno,
                        node.col_offset,
                        f"while-loop condition reads traced argument "
                        f"{root!r}; trace-time Python control flow "
                        "cannot depend on device values",
                    )

"""Repo-local developer tooling (not shipped with the package)."""

"""Check (b): dead-stage detection — the PERF.md §15 DCE trap, gated.

XLA dead-code-eliminates any stage whose outputs are unused: PR 3 found
a timed loop that accumulated only ``n_emitted`` silently dropped the
whole digest-membership stage (3× flattering at 2048 lanes) — and no
test failed, because parity tests consume the hits.  This check makes
that class mechanical: every fused entry point is lowered and
XLA-COMPILED (CPU, optimization on), and each declared pipeline stage
must leave at least one instruction in the optimized module.

Stage survival is detected from instruction *source metadata*: XLA
preserves each op's ``source_file`` through optimization and drops it
with the op, so "some instruction still points into
``ops/membership.py``" is exactly "the membership stage survived".
This is robust to fusion/reassociation (which constant- or
opcode-matching is not) and needs no knowledge of the kernel's shape.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from .findings import AuditFinding

#: Source files whose surviving instructions prove each stage alive.
#: The fused Pallas kernel implements expand AND hash in one file, so
#: ``pallas_expand.py`` witnesses both.
STAGE_MARKERS: Dict[str, Tuple[str, ...]] = {
    "expand": (
        "/ops/expand_matches.py",
        "/ops/expand_suball.py",
        "/ops/pallas_expand.py",
    ),
    "hash": (
        "/ops/hashes.py",
        "/ops/pallas_md5.py",
        "/ops/pallas_expand.py",
    ),
    "membership": ("/ops/membership.py",),
}


def compiled_text(fn, args) -> str:
    """Lower + XLA-compile ``fn(*args)`` on the current (CPU) backend and
    return the optimized module text.  ``fn`` may already be jitted —
    jit-of-jit lowers fine and keeps one code path here."""
    import jax

    return jax.jit(fn).lower(*args).compile().as_text()


def stage_survival(text: str) -> Dict[str, bool]:
    """Which pipeline stages left instructions in an optimized module."""
    return {
        stage: any(marker in text for marker in markers)
        for stage, markers in STAGE_MARKERS.items()
    }


def audit_stage_text(
    text: str, entry: str, stages: Sequence[str]
) -> List[AuditFinding]:
    """Findings for every declared stage missing from ``text``."""
    if "source_file=" not in text:
        # Metadata stripped (nonstandard XLA flags): the check cannot
        # run — failing loudly beats vacuously passing.
        return [
            AuditFinding(
                "config", entry,
                "optimized HLO carries no source_file metadata; "
                "dead-stage detection needs it (check XLA/JAX flags)",
            )
        ]
    alive = stage_survival(text)
    return [
        AuditFinding(
            "dead-stage", entry,
            f"the {stage} stage left no instructions in the optimized "
            f"module — XLA dead-code-eliminated it (the PERF.md §15 "
            f"trap class: some consumer of its outputs was dropped)",
        )
        for stage in stages
        if not alive.get(stage, False)
    ]


def audit_stages(fn, args, entry: str, stages: Sequence[str]) -> List[AuditFinding]:
    """Compile ``fn(*args)`` and check every declared stage survived."""
    try:
        text = compiled_text(fn, args)
    except Exception as exc:  # noqa: BLE001 — report, don't crash
        return [
            AuditFinding(
                "config", entry,
                f"body failed to lower/compile on CPU: "
                f"{type(exc).__name__}: {exc}",
            )
        ]
    return audit_stage_text(text, entry, stages)

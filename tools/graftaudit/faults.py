"""Check: fault-injection hooks stay no-op-guarded (PERF.md §23).

The fault layer's production-cost contract is ONE module-attribute
``None`` check per seam::

    if faults.ACTIVE is not None:
        faults.ACTIVE.fire("superstep.dispatch")

A bare ``fire(...)`` call — or one guarded by anything other than the
``ACTIVE is not None`` test — runs rule matching (a lock, a dict
lookup, an RNG draw) on every arrival, and the seams sit in the drive
loops' dispatch fill windows, where host work between dispatches
narrows the pipeline overlap the §18 instrument exists to protect.
``audit_fault_hooks`` statically walks a drive/pump function and flags
every ``fire`` call site that is not (transitively) inside an ``if``
whose test is an ``is not None`` comparison mentioning ``ACTIVE``.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List

from .findings import AuditFinding


def _dotted_parts(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return parts


def _is_fire_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "fire"
    return isinstance(f, ast.Attribute) and f.attr == "fire"


def _is_active_guard(test: ast.AST) -> bool:
    """``<...>.ACTIVE is not None`` (any module spelling on the left)."""
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return False
    if not isinstance(test.ops[0], ast.IsNot):
        return False
    comp = test.comparators[0]
    if not (isinstance(comp, ast.Constant) and comp.value is None):
        return False
    return "ACTIVE" in _dotted_parts(test.left)


def audit_fault_hooks(fn, entry: str) -> List[AuditFinding]:
    """Flag every ``fire(...)`` call in ``fn`` not guarded by the
    sanctioned ``ACTIVE is not None`` test — the no-op-guarded shape
    the hot path requires (PERF.md §23)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as exc:
        return [
            AuditFinding(
                "config", entry,
                f"source unavailable for fault-hook audit: {exc}",
            )
        ]
    findings: List[AuditFinding] = []

    def flag_if_bare(node: ast.AST, guarded: bool) -> None:
        if _is_fire_call(node) and not guarded:
            findings.append(
                AuditFinding(
                    "fault-hook", entry,
                    "fault-injection fire() without the ACTIVE-is-not-"
                    "None guard — the production no-op contract is ONE "
                    "attribute check per seam; a bare hook runs rule "
                    "matching in the drive loop's dispatch window "
                    "(PERF.md §23)",
                )
            )

    def walk(node: ast.AST, guarded: bool) -> None:
        if isinstance(node, ast.If):
            inner = guarded or _is_active_guard(node.test)
            for sub in ast.walk(node.test):
                flag_if_bare(sub, guarded)  # the test runs pre-guard
            for child in node.body:
                walk(child, inner)
            for child in node.orelse:
                walk(child, guarded)
            return
        flag_if_bare(node, guarded)
        for sub in ast.iter_child_nodes(node):
            # If statements recurse above; every other child keeps the
            # current guard state.
            walk(sub, guarded)

    walk(tree, False)
    return findings

"""Concrete launch configurations for every registered audit entry.

The ``@audited_entry`` registry (``hashcat_a5_table_generator_tpu.audit``)
names WHAT must be audited; this module supplies HOW — the example
plans, tables, digest sets and geometries each entry is traced/lowered
with.  Everything here is CPU-only and trace/lower-only: no kernel ever
executes, so the whole audit runs on the tier-1 host inside its 120 s
budget.

The budget configs reproduce the exact geometries PERF.md §7a counts
(qwerty-cyrillic × rockyou-class words, stride 128, NB=16) so
``KERNEL_BUDGETS.json`` pins the same numbers the perf narrative quotes.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass
from typing import Callable, Dict, List, Tuple

# Trace/lower-only: force the CPU backend before jax initializes (the
# audit must behave identically on a TPU host and in CI).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

# Importing these populates AUDIT_REGISTRY (decoration side effect).
from hashcat_a5_table_generator_tpu import audit as _audit  # noqa: E402
from hashcat_a5_table_generator_tpu.models import attack as _attack  # noqa: E402
from hashcat_a5_table_generator_tpu.ops import (  # noqa: E402,F401
    hashes as _hashes,
    membership as _membership,
    pallas_expand as _pe,
    pallas_md5 as _pm,
)
from hashcat_a5_table_generator_tpu.parallel import mesh as _mesh  # noqa: E402

registered_entries = _audit.registered_entries


@dataclass(frozen=True)
class BudgetConfig:
    """One pinned kernel geometry: ``build()`` returns a zero-arg trace
    thunk plus the ``(g, s)`` tile the counter normalizes by."""

    key: str
    entry: str  # registry entry the kernel belongs to
    description: str
    build: Callable[[], Tuple[Callable, int, int]]
    #: The kernel tier must trace float-free (K=1 scalar-units / radix2
    #: tiers; the general kernel's f32 ``_exact_div`` decode is exempt).
    float_free: bool = True


@dataclass(frozen=True)
class BodyConfig:
    """One lowerable end-to-end body: ``build()`` returns ``(fn, args)``
    such that ``jax.jit(fn).lower(*args)`` compiles it."""

    entry: str
    build: Callable[[], Tuple[Callable, tuple]]


@dataclass(frozen=True)
class StageConfig:
    """One integer-stage trace: ``build()`` returns ``(fn, args)`` for
    ``jax.make_jaxpr``."""

    entry: str
    build: Callable[[], Tuple[Callable, tuple]]


# ---------------------------------------------------------------------------
# Shared fixture state (built once per process; construction is host-side
# numpy work measured in hundreds of ms)
# ---------------------------------------------------------------------------

_STRIDE = 128
_NB = 16


def _synth_wordlist(n: int, seed: int = 0) -> List[bytes]:
    """``bench.synth_wordlist`` — imported, not copied, so the budget
    geometry and the bench geometry can never drift apart."""
    import bench

    return bench.synth_wordlist(n, seed)


def long_wordlist(n: int = 64, width: int = 60, seed: int = 0) -> List[bytes]:
    """All-lowercase ``width``-byte words: with qwerty-cyrillic's 2-byte
    values the plan's out_width is ``2 * width`` — 120 bytes, the
    2-hash-block tier PERF.md §7a quotes.  Public: ``scripts/
    roofline_count.py --word-width`` reuses it so the roofline's long
    config and the pinned budget tier cannot drift apart."""
    rng = np.random.default_rng(seed)
    return [
        bytes(rng.integers(ord("a"), ord("z") + 1, size=width,
                           dtype=np.uint8))
        for _ in range(n)
    ]


class _Fixtures:
    """Lazily-built, cached plan/table/block trees shared by configs."""

    def __init__(self) -> None:
        self._cache: Dict[tuple, object] = {}

    def table(self, name: str = "qwerty-cyrillic"):
        from hashcat_a5_table_generator_tpu.tables.compile import compile_table
        from hashcat_a5_table_generator_tpu.tables.layouts import get_layout

        key = ("table", name)
        if key not in self._cache:
            self._cache[key] = compile_table(
                get_layout(name).to_substitution_map()
            )
        return self._cache[key]

    def plan(self, mode: str, algo: str, words_key: str = "rockyou"):
        from hashcat_a5_table_generator_tpu.models.attack import (
            AttackSpec,
            build_plan,
        )
        from hashcat_a5_table_generator_tpu.ops.packing import pack_words

        key = ("plan", mode, algo, words_key)
        if key not in self._cache:
            spec = AttackSpec(mode=mode, algo=algo)
            words = (
                long_wordlist() if words_key == "long"
                else _synth_wordlist(256 if words_key == "rockyou" else 64)
            )
            self._cache[key] = (
                spec, build_plan(spec, self.table(), pack_words(words))
            )
        return self._cache[key]

    def digest_set(self, algo: str):
        from hashcat_a5_table_generator_tpu.ops.membership import (
            build_digest_set,
        )

        key = ("digests", algo)
        if key not in self._cache:
            nbytes = {"md5": 16, "md4": 16, "ntlm": 16, "sha1": 20}[algo]
            self._cache[key] = build_digest_set(
                [bytes(nbytes), bytes(range(nbytes))], algo
            )
        return self._cache[key]

    def blocks(self, plan, nb: int = _NB, stride: int = _STRIDE):
        """``stride`` is the RANK stride — the pair tier (PERF.md §24)
        cuts blocks covering ``2 * _STRIDE`` candidate ranks per
        ``_STRIDE``-lane block."""
        from hashcat_a5_table_generator_tpu.ops.blocks import (
            make_blocks,
            pad_batch,
        )

        batch, _, _ = make_blocks(
            plan, start_word=0, start_rank=0, max_variants=nb * stride,
            max_blocks=nb, fixed_stride=stride,
        )
        return pad_batch(batch, nb)


_FIX = _Fixtures()


# ---------------------------------------------------------------------------
# Budget configs (KERNEL_BUDGETS.json keys)
# ---------------------------------------------------------------------------


def _fused_thunk(mode: str, algo: str, *, scalar_units: bool = True,
                 words_key: str = "rockyou",
                 pair: str = "auto") -> Tuple[Callable, int, int]:
    """The roofline trace: one fused-kernel launch at the §7a geometry.

    ``pair``: the pair-lane tier (PERF.md §24) — ``"auto"`` matches
    production (K=2 when the schema's pair gate passes; the counter's
    tile then normalizes per CANDIDATE, ``2 * _STRIDE`` per block row),
    ``"off"`` pins the K=1 tier (the ``--pair off`` reproducibility
    arm)."""
    from hashcat_a5_table_generator_tpu.models.attack import (
        block_arrays,
        plan_arrays,
        table_arrays,
    )

    from hashcat_a5_table_generator_tpu.ops.packing import piece_schema_for

    spec, plan = _FIX.plan(mode, algo, words_key)
    ct = _FIX.table()
    pieces = piece_schema_for(plan, ct)
    pair_k = None
    if pair != "off":
        pair_k = _pe.pair_for_config(
            spec, plan, pieces, block_stride=_STRIDE
        )
    rank_stride = _STRIDE * (pair_k or 1)
    batch = _FIX.blocks(plan, stride=rank_stride)
    p = plan_arrays(plan)
    t = table_arrays(ct)
    b = block_arrays(batch, num_blocks=_NB)
    k = _pe.k_vals_for(plan)
    vb = p.get("cval_bytes", t["val_bytes"])
    vl = p.get("cval_len", t["val_len"])
    common = dict(
        num_lanes=_NB * _STRIDE, out_width=int(plan.out_width),
        min_substitute=spec.effective_min,
        max_substitute=spec.max_substitute,
        block_stride=_STRIDE, k_opts=k, algo=algo, interpret=True,
        scalar_units=scalar_units and _pe.scalar_units_for(plan),
        # The production emission scheme: per-slot pieces when the plan
        # qualifies (A5GEN_EMIT=bytescan pins the legacy scan instead).
        pieces=pieces,
        pair=pair_k is not None,
    )
    if mode in ("default", "reverse"):
        fn = lambda: _pe.fused_expand_md5(  # noqa: E731
            p["tokens"], p["lengths"], p["match_pos"], p["match_len"],
            p["match_radix"], p["match_val_start"],
            t["val_bytes"], t["val_len"],
            b["word"], b["base"], b["count"], **common,
        )
    else:
        fn = lambda: _pe.fused_expand_suball_md5(  # noqa: E731
            p["tokens"], p["lengths"], p["pat_radix"], p["pat_val_start"],
            p["seg_orig_start"], p["seg_orig_len"], p["seg_pat"],
            vb, vl,
            b["word"], b["base"], b["count"],
            close_next=p.get("close_next"), close_mul=p.get("close_mul"),
            **common,
        )
    return fn, _pe._G, rank_stride


def budget_configs() -> Dict[str, BudgetConfig]:
    """The pinned kernel tiers, keyed as in ``KERNEL_BUDGETS.json``.

    Tiers whose §7a geometry passes the pair gate (scalar / sha1 /
    ntlm / general — single hash block, even innermost radix) pin the
    PRODUCTION default since PERF.md §24: the pair-lane (K=2) kernel,
    counted per candidate.  ``scalar-solo`` pins the K=1 tier of the
    same geometry (the ``A5GEN_PAIR=off`` escape hatch and the
    ``--pair off`` roofline arm); suball (slot 0 not bound to column
    0 at this geometry) and 2-hash-block (multi-block) fall back to
    K=1 automatically and pin that."""
    mk = BudgetConfig
    return {
        c.key: c
        for c in (
            mk("scalar", "ops.fused_expand_md5",
               "default/md5 scalar-units tier (§7a headline; pair K=2)",
               lambda: _fused_thunk("default", "md5")),
            mk("scalar-solo", "ops.fused_expand_md5",
               "default/md5 scalar-units tier, pair OFF (K=1 — the "
               "A5GEN_PAIR=off arm)",
               lambda: _fused_thunk("default", "md5", pair="off")),
            mk("suball", "ops.fused_expand_suball_md5",
               "suball/md5 scalar-units tier",
               lambda: _fused_thunk("suball", "md5")),
            mk("sha1", "ops.fused_expand_md5",
               "default/sha1 scalar-units tier (80-round schedule; "
               "pair K=2)",
               lambda: _fused_thunk("default", "sha1")),
            mk("general", "ops.fused_expand_md5",
               "default/md5 general kernel (K-way select, f32 decode; "
               "pair K=2)",
               lambda: _fused_thunk("default", "md5", scalar_units=False),
               float_free=False),
            mk("2-hash-block", "ops.fused_expand_md5",
               "default/md5 at out_width 120 (2 chained hash blocks)",
               lambda: _fused_thunk("default", "md5", words_key="long")),
            mk("ntlm", "ops.fused_expand_md5",
               "default/ntlm scalar-units tier (UTF-16LE expansion; "
               "pair K=2)",
               lambda: _fused_thunk("default", "ntlm")),
        )
    }


# ---------------------------------------------------------------------------
# Body configs (dead-stage + host-transfer checks)
# ---------------------------------------------------------------------------


def _crack_args(nb: int = 8, stride: int = _STRIDE):
    from hashcat_a5_table_generator_tpu.models.attack import (
        block_arrays,
        digest_arrays,
        piece_arrays,
        plan_arrays,
        table_arrays,
    )
    from hashcat_a5_table_generator_tpu.ops.packing import piece_schema_for

    spec, plan = _FIX.plan("default", "md5", "small")
    batch = _FIX.blocks(plan, nb=nb, stride=stride)
    parr = plan_arrays(plan)
    pieces = piece_schema_for(plan, _FIX.table())
    parr.update(piece_arrays(pieces))
    return (
        spec, plan, pieces,
        parr,
        table_arrays(_FIX.table()),
        digest_arrays(_FIX.digest_set("md5")),
        block_arrays(batch, num_blocks=nb),
    )


def _fused_body_config() -> Tuple[Callable, tuple]:
    spec, plan, pieces, p, t, d, b = _crack_args()
    body = _attack.make_fused_body(
        spec, num_lanes=8 * _STRIDE, out_width=int(plan.out_width),
        block_stride=_STRIDE, radix2=_pe.k_opts_for(plan) == 1,
        pieces=pieces,
    )
    return body, (p, t, d, b)


def _superstep_args():
    from hashcat_a5_table_generator_tpu.models.attack import superstep_arrays
    from hashcat_a5_table_generator_tpu.ops.blocks import superstep_index

    spec, plan, pieces, p, t, d, _ = _crack_args()
    ss = superstep_arrays(plan, _STRIDE)
    total_blocks = int(superstep_index(plan, _STRIDE)[2])
    return spec, plan, pieces, p, t, d, ss, total_blocks


def _superstep_body_config() -> Tuple[Callable, tuple]:
    spec, plan, pieces, p, t, d, ss, total_blocks = _superstep_args()
    body = _attack.make_superstep_body(
        spec, num_lanes=8 * _STRIDE, out_width=int(plan.out_width),
        block_stride=_STRIDE, num_blocks=8, steps=2, hit_cap=32,
        total_blocks=total_blocks, radix2=_pe.k_opts_for(plan) == 1,
        pieces=pieces,
    )
    return body, (p, t, d, ss, jnp.int32(0), _attack.superstep_buffers(32))


def _sharded_crack_config() -> Tuple[Callable, tuple]:
    from hashcat_a5_table_generator_tpu.parallel.mesh import (
        make_mesh,
        stack_blocks,
    )

    spec, plan, pieces, p, t, d, _ = _crack_args()
    mesh = make_mesh(1)
    batch = _FIX.blocks(plan, nb=8)
    blocks = stack_blocks([batch], num_blocks=8)
    step = _mesh.make_sharded_crack_step(
        spec, mesh, lanes_per_device=8 * _STRIDE,
        out_width=int(plan.out_width), block_stride=_STRIDE,
        radix2=_pe.k_opts_for(plan) == 1, pieces=pieces,
    )
    return step, (p, t, d, blocks)


def _sharded_superstep_config() -> Tuple[Callable, tuple]:
    from hashcat_a5_table_generator_tpu.parallel.mesh import make_mesh

    spec, plan, pieces, p, t, d, ss, total_blocks = _superstep_args()
    mesh = make_mesh(1)
    step = _mesh.make_sharded_superstep_step(
        spec, mesh, lanes_per_device=8 * _STRIDE, num_blocks=8,
        out_width=int(plan.out_width), block_stride=_STRIDE, steps=2,
        hit_cap=32, total_blocks=total_blocks,
        radix2=_pe.k_opts_for(plan) == 1, pieces=pieces,
    )
    bufs = {
        "hit_word": np.full((33,), -1, np.int32),
        "hit_rank": np.zeros((33,), np.int32),
    }
    return step, (p, t, d, ss, np.zeros((1,), np.int32), bufs)


def body_configs() -> Dict[str, BodyConfig]:
    return {
        c.entry: c
        for c in (
            BodyConfig("models.make_fused_body", _fused_body_config),
            BodyConfig("models.make_superstep_body", _superstep_body_config),
            BodyConfig(
                "parallel.make_sharded_crack_step", _sharded_crack_config
            ),
            BodyConfig(
                "parallel.make_sharded_superstep_step",
                _sharded_superstep_config,
            ),
        )
    }


# ---------------------------------------------------------------------------
# Integer-stage configs (float-purity traces)
# ---------------------------------------------------------------------------


def _hash_stage(fn) -> Callable[[], Tuple[Callable, tuple]]:
    def build() -> Tuple[Callable, tuple]:
        msg = jnp.zeros((128, 16), jnp.uint8)
        length = jnp.full((128,), 8, jnp.int32)
        return fn, (msg, length)

    return build


def _membership_stage() -> Tuple[Callable, tuple]:
    ds = _FIX.digest_set("md5")
    digest = jnp.zeros((128, 4), jnp.uint32)
    return _membership.digest_member, (
        digest, jnp.asarray(ds.rows), jnp.asarray(ds.bitmap)
    )


def stage_configs() -> Dict[str, StageConfig]:
    return {
        c.entry: c
        for c in (
            StageConfig("ops.hashes.md5", _hash_stage(_hashes.md5)),
            StageConfig("ops.hashes.md4", _hash_stage(_hashes.md4)),
            StageConfig("ops.hashes.sha1", _hash_stage(_hashes.sha1)),
            StageConfig("ops.hashes.ntlm", _hash_stage(_hashes.ntlm)),
            StageConfig("ops.digest_member", _membership_stage),
        )
    }


# ---------------------------------------------------------------------------
# Standalone pallas-kernel configs without a budget key (bounds checks)
# ---------------------------------------------------------------------------


def _md5_pallas_thunk() -> Tuple[Callable, int, int]:
    n = 128 * 64  # the kernel's minimum whole-tile geometry
    msg = jnp.zeros((n, 16), jnp.uint8)
    length = jnp.full((n,), 8, jnp.int32)
    return (
        lambda: _pm.md5_pallas(msg, length, interpret=True),
        64, 128,
    )


def extra_kernel_configs() -> Dict[str, Callable[[], Tuple[Callable, int, int]]]:
    """Pallas entries audited for bounds/races but not budget-pinned
    (``md5_pallas`` is the hash-only kernel — its op count is the MD5
    floor, not a per-candidate budget)."""
    return {"ops.md5_pallas": _md5_pallas_thunk}


# ---------------------------------------------------------------------------
# Registry/harness sync
# ---------------------------------------------------------------------------


def coverage_findings():
    """Every ``@audited_entry`` must have a harness config and every
    declared budget key must exist (and vice versa — budgets.py checks
    the file side).  Shared by the CLI and tests/test_graftaudit.py so
    an uncovered registration fails BOTH the audit and the suite."""
    from .findings import AuditFinding

    findings = []
    entries = registered_entries()
    bcfgs = budget_configs()
    bodycfgs = body_configs()
    stagecfgs = stage_configs()
    extracfgs = extra_kernel_configs()
    for name, entry in sorted(entries.items()):
        if entry.kind == "pallas_kernel":
            covered = name in extracfgs or any(
                c.entry == name for c in bcfgs.values()
            )
        elif entry.kind == "integer_stage":
            covered = name in stagecfgs
        else:
            covered = name in bodycfgs
        if not covered:
            findings.append(
                AuditFinding(
                    "config", name,
                    f"registered with @audited_entry ({entry.module}) "
                    "but tools/graftaudit/harness.py has no launch "
                    "config for it — add one (the registry and harness "
                    "must cover each other)",
                )
            )
        for key in entry.budget_keys:
            if key not in bcfgs:
                findings.append(
                    AuditFinding(
                        "config", name,
                        f"declares budget key {key!r} but no budget "
                        "config defines it",
                    )
                )
    for key, cfg in bcfgs.items():
        entry = entries.get(cfg.entry)
        if entry is None or key not in entry.budget_keys:
            findings.append(
                AuditFinding(
                    "config", key,
                    f"budget config targets {cfg.entry!r} which does "
                    "not declare this key in @audited_entry",
                )
            )
    return findings

"""Check (a): pinned ops/candidate budgets for every audited kernel.

``KERNEL_BUDGETS.json`` (repo root) pins the jaxpr-counted VPU op budget
of each fused-kernel tier at the PERF.md §7a geometry.  The audit
re-counts every tier and fails on drift beyond the pinned tolerance —
both directions: a silent +2% is a perf regression, a silent −2% means
the kernel changed and the perf narrative (and this file) are stale.

Deliberate updates are one command:

    python -m tools.graftaudit --update-budgets

which rewrites the file from the current counts; the diff then lands in
review next to the kernel change that caused it (workflow: PERF.md §16).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from .findings import AuditFinding

#: Repo-root budgets file (the committed pin).
DEFAULT_BUDGETS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "KERNEL_BUDGETS.json",
)

#: Allowed relative drift before the audit fails, percent.
DEFAULT_TOLERANCE_PCT = 2.0


def load_budgets(path: str = DEFAULT_BUDGETS_PATH) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def save_budgets(
    measured: Dict[str, float],
    descriptions: Dict[str, str],
    path: str = DEFAULT_BUDGETS_PATH,
    tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
) -> None:
    """Rewrite the budgets file from current counts (the deliberate
    update workflow).  Counts are stored to 0.1 op — the counter is
    deterministic, sub-op noise would only churn diffs."""
    doc = {
        "_comment": (
            "Pinned per-candidate VPU op budgets for the fused kernels "
            "(tools/graftaudit, PERF.md §16). Counted from the kernel "
            "jaxpr at the §7a geometry; CI fails on drift beyond "
            "tolerance_pct. Deliberate update: "
            "python -m tools.graftaudit --update-budgets"
        ),
        "tolerance_pct": tolerance_pct,
        "kernels": {
            key: {
                "ops_per_candidate": round(measured[key], 1),
                "config": descriptions.get(key, ""),
            }
            for key in sorted(measured)
        },
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")


def compare_budgets(
    measured: Dict[str, float],
    budgets: dict,
    failed: "frozenset[str] | set[str]" = frozenset(),
) -> Tuple[List[AuditFinding], List[Tuple[str, float, float, float, str]]]:
    """Measured vs pinned.  Returns ``(findings, rows)`` where each row
    is ``(key, pinned, measured, drift_pct, verdict)`` — the CI summary
    table renders rows for EVERY tier, drifted or not.  ``failed``:
    keys whose config exists but crashed (already reported by the
    caller) — they get a FAILED row, not misleading delete-the-pin
    advice."""
    tol = float(budgets.get("tolerance_pct", DEFAULT_TOLERANCE_PCT))
    pinned = budgets.get("kernels", {})
    findings: List[AuditFinding] = []
    rows: List[Tuple[str, float, float, float, str]] = []

    for key in sorted(set(pinned) | set(measured) | set(failed)):
        if key in failed:
            want = pinned.get(key, {}).get(
                "ops_per_candidate", float("nan")
            )
            rows.append((key, float(want), float("nan"), float("nan"),
                         "FAILED"))
            continue
        if key not in measured:
            findings.append(
                AuditFinding(
                    "config", key,
                    "pinned in KERNEL_BUDGETS.json but no audit config "
                    "measures it (delete the pin or add the harness "
                    "config)",
                )
            )
            continue
        if key not in pinned:
            findings.append(
                AuditFinding(
                    "config", key,
                    "audited kernel has no pinned budget; run "
                    "python -m tools.graftaudit --update-budgets and "
                    "commit KERNEL_BUDGETS.json",
                )
            )
            rows.append((key, float("nan"), measured[key], float("nan"),
                         "UNPINNED"))
            continue
        want = float(pinned[key]["ops_per_candidate"])
        got = measured[key]
        drift = (got - want) / want * 100.0 if want else float("inf")
        ok = abs(drift) <= tol
        rows.append((key, want, got, drift, "ok" if ok else "DRIFT"))
        if not ok:
            findings.append(
                AuditFinding(
                    "budget", key,
                    f"ops/candidate {got:.1f} vs pinned {want:.1f} "
                    f"({drift:+.2f}%, tolerance ±{tol:g}%). "
                    "If deliberate: python -m tools.graftaudit "
                    "--update-budgets and commit the diff with the "
                    "kernel change (PERF.md §16).",
                )
            )
    return findings, rows


def render_table(rows, markdown: bool = False) -> str:
    """The per-kernel budget diff table (CLI stderr + CI job summary)."""
    header = ("kernel", "pinned", "measured", "drift", "verdict")
    body = [
        (
            key,
            "-" if pinned != pinned else f"{pinned:.1f}",  # NaN -> "-"
            "-" if got != got else f"{got:.1f}",
            "-" if drift != drift else f"{drift:+.2f}%",
            verdict,
        )
        for key, pinned, got, drift, verdict in rows
    ]
    if markdown:
        lines = [
            "| " + " | ".join(header) + " |",
            "|" + "|".join("---" for _ in header) + "|",
        ]
        lines += ["| " + " | ".join(r) + " |" for r in body]
        return "\n".join(lines)
    widths = [
        max(len(header[i]), *(len(r[i]) for r in body)) if body
        else len(header[i])
        for i in range(len(header))
    ]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    return "\n".join([fmt.format(*header)] + [fmt.format(*r) for r in body])

"""``python -m tools.graftaudit`` entry point."""

import sys

from .cli import main

sys.exit(main())

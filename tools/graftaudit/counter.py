"""The repo's ONE kernel op counter (jaxpr-weighted vreg model).

Moved here from ``scripts/roofline_count.py`` so the roofline CLI, the
``KERNEL_BUDGETS.json`` gate, and PERF.md §7/§7a all read the same
implementation — two counters would inevitably drift and the budget gate
would pin the wrong number.

Model: the fused Pallas kernels are straight-line elementwise code on
``(G, S)`` tiles — every traced op is a VPU vector instruction.  Each
eqn costs ``ceil(elements / 1024)`` native (8, 128) vregs, normalized by
the tile's own vreg span, so

    ops/candidate = weighted_eqns * 1024 / (G * S)

(at the headline stride 128 geometry ``G * S`` is one vreg and
ops/candidate is the plain weighted eqn count).  Divided into the VPU's
per-chip op rate this brackets the hashes/s ceiling — PERF.md §7.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterator, List, Tuple

import numpy as np


def count_kernel_ops(jaxpr, g: int, s: int) -> Tuple[float, Counter]:
    """Weighted eqn count of a Pallas kernel jaxpr.

    Sub-tile ops (e.g. ``(G, 1)`` scalars that still burn a whole vreg)
    are charged fairly by the per-eqn ``ceil(elements/1024)`` vreg cost.
    Returns ``(ops_per_candidate, Counter by primitive name)``.
    """
    tile_vregs = max(1, (g * s) // 1024)
    total = 0.0
    by_prim: Counter = Counter()

    def walk(jx) -> None:
        nonlocal total
        for eqn in jx.eqns:
            # Recurse through call-like wrappers (jnp.where etc. trace as
            # nested jit eqns) — only leaf primitives are instructions.
            sub = eqn.params.get("jaxpr")
            if sub is not None and hasattr(sub, "eqns"):
                walk(sub)
                continue
            if sub is not None and hasattr(getattr(sub, "jaxpr", None),
                                           "eqns"):
                walk(sub.jaxpr)
                continue
            outs = eqn.outvars
            elems = max(
                int(np.prod(v.aval.shape)) if v.aval.shape else 1
                for v in outs
            )
            vregs = max(1, -(-elems // 1024))
            w = vregs / tile_vregs
            total += w
            by_prim[eqn.primitive.name] += w

    walk(jaxpr)
    return total, by_prim


def iter_pallas_eqns(jaxpr) -> Iterator:
    """Yield every ``pallas_call`` eqn in ``jaxpr``, recursing through
    nested sub-jaxprs (scan/while/cond bodies, inner jits)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_pallas_eqns(sub)


def _sub_jaxprs(eqn) -> List:
    """Inner jaxprs of one eqn, whatever param shape they hide in."""
    out = []
    if eqn.primitive.name == "pallas_call":
        # The kernel jaxpr is the *kernel body*, not host-level dataflow;
        # pallas-in-pallas does not exist — don't descend.
        return out
    for val in eqn.params.values():
        for cand in val if isinstance(val, (tuple, list)) else (val,):
            if hasattr(cand, "eqns"):
                out.append(cand)
            elif hasattr(getattr(cand, "jaxpr", None), "eqns"):
                out.append(cand.jaxpr)
    return out


def kernel_jaxpr_of(closed_jaxpr):
    """The FIRST pallas kernel jaxpr inside a traced computation (the
    fused wrappers launch exactly one ``pallas_call``).  Raises
    ``ValueError`` when none is present — a budget config that stopped
    reaching the Pallas path must fail loudly, not count XLA ops."""
    for eqn in iter_pallas_eqns(closed_jaxpr.jaxpr):
        return eqn.params["jaxpr"]
    raise ValueError("no pallas_call in trace")


def count_traced_kernel(fn, g: int, s: int) -> Tuple[float, Counter]:
    """Trace ``fn()`` (zero-arg thunk) and count its Pallas kernel."""
    import jax

    return count_kernel_ops(kernel_jaxpr_of(jax.make_jaxpr(fn)()), g, s)

"""Check (d): Pallas memory safety — static bounds + grid write overlap.

Compiled Pallas has no bounds checking: an out-of-range ``pl.load`` /
``pl.store`` (or ``ref[...]`` sugar) reads or clobbers whatever VMEM
neighbors the block, and interpret-mode CPU tests won't necessarily
catch it (numpy wraps negative indices; masked OOB lanes can alias into
valid data).  Two static checks over the traced kernel jaxpr:

* **bounds** — every ``get``/``swap``/``masked_load``/``masked_swap``
  indexer with static components must stay inside the ref's block shape
  (this repo's kernels index with Python-static slices/ints, so almost
  everything is statically decidable; dynamic indices are skipped — the
  check is conservative, never wrong).
* **race** — each *output* BlockSpec ``index_map`` must be injective
  over the grid: two grid steps mapping to the same output block means
  the second silently overwrites the first (on TPU grids are sequential,
  so this "works" nondeterministically in interpret mode and corrupts
  results on chip when the revisit is unintended — no kernel in this
  repo accumulates across grid steps).
"""

from __future__ import annotations

import itertools
from typing import Iterator, List, Optional

from .findings import AuditFinding

#: Kernel-level ref access primitives (name -> index of the ref invar,
#: params key holding the indexer pytree).
_ACCESS_PRIMS = {
    "get": ("tree",),
    "swap": ("tree",),
    "masked_load": ("args_tree",),
    "masked_swap": ("args_tree",),
}

#: Grid enumeration cap for the injectivity check; audit grids are tiny
#: (a handful of steps), the cap only guards against someone auditing a
#: production-size launch.
_MAX_GRID_POINTS = 4096


def _indexers_of(eqn) -> Iterator:
    """NDIndexer objects of one access eqn, robust to the leaf layout
    differences between ``get``/``swap`` (tree) and the masked forms
    (args_tree, value interleaved)."""
    import jax.tree_util as jtu

    (tree_key,) = _ACCESS_PRIMS[eqn.primitive.name]
    tree = eqn.params.get(tree_key)
    if tree is None:
        return
    leaves = list(eqn.invars[1:])
    unflat = None
    # Leaf layouts differ by primitive: get/swap's ``tree`` spans only
    # the indexer leaves (ref and value ride outside it), while the
    # masked forms' ``args_tree`` flattens (ref, indexers, value, mask)
    # — the ref itself is a leaf.  Try the layouts until the treedef
    # accepts one; the NDIndexer scan below ignores non-indexer leaves.
    for cand in (leaves, list(eqn.invars), leaves[1:], leaves[:-1]):
        try:
            unflat = jtu.tree_unflatten(tree, cand)
            break
        except ValueError:
            continue
    if unflat is None:
        return
    stack = [unflat]
    while stack:
        node = stack.pop()
        if type(node).__name__ == "NDIndexer":
            yield node
        elif isinstance(node, (tuple, list)):
            stack.extend(node)


def _static_int(x) -> Optional[int]:
    """Python int of a static index component, else None (dynamic).

    Handles plain ints, numpy integer scalars, and jax ``Literal``s —
    whose ``.val`` is a 0-d numpy ARRAY, not a scalar (a traced-constant
    ``pl.dslice(jnp.int32(6), ...)`` start arrives that way)."""
    if isinstance(x, bool):
        return None
    if isinstance(x, int):
        return x
    import numpy as np

    for cand in (x, getattr(x, "val", None)):  # x itself, or Literal.val
        if isinstance(cand, bool):
            return None
        if isinstance(cand, (int, np.integer)):
            return int(cand)
        if (
            isinstance(cand, np.ndarray)
            and cand.ndim == 0
            and np.issubdtype(cand.dtype, np.integer)
        ):
            return int(cand)
    return None


def _check_indexer(nd, ref_shape, where: str) -> List[str]:
    """Human-readable violations of one NDIndexer against a ref shape."""
    problems: List[str] = []
    indices = getattr(nd, "indices", ())
    for dim, (idx, size) in enumerate(zip(indices, ref_shape)):
        if type(idx).__name__ == "Slice":
            start = _static_int(getattr(idx, "start", None))
            length = _static_int(getattr(idx, "size", None))
            stride = _static_int(getattr(idx, "stride", None)) or 1
            if start is None or length is None:
                continue  # dynamic slice start: not statically decidable
            last = start + (length - 1) * stride
            if start < 0 or (length > 0 and last >= size):
                problems.append(
                    f"dim {dim}: slice [{start}:{start + length * stride}"
                    f":{stride}] outside block extent {size} ({where})"
                )
        else:
            point = _static_int(idx)
            if point is None:
                continue  # dynamic scalar index
            if not 0 <= point < size:
                problems.append(
                    f"dim {dim}: index {point} outside block extent "
                    f"{size} ({where})"
                )
    return problems


def _kernel_access_findings(kernel_jaxpr, entry: str) -> List[AuditFinding]:
    findings: List[AuditFinding] = []
    for eqn in kernel_jaxpr.eqns:
        name = eqn.primitive.name
        if name not in _ACCESS_PRIMS:
            # Recurse into nested control flow inside the kernel body.
            for val in eqn.params.values():
                for cand in (
                    val if isinstance(val, (tuple, list)) else (val,)
                ):
                    inner = (
                        cand if hasattr(cand, "eqns")
                        else getattr(cand, "jaxpr", None)
                    )
                    if hasattr(inner, "eqns"):
                        findings.extend(
                            _kernel_access_findings(inner, entry)
                        )
            continue
        ref_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()) or ())
        if not ref_shape:
            continue
        kind = "load" if name in ("get", "masked_load") else "store"
        for nd in _indexers_of(eqn):
            for problem in _check_indexer(nd, ref_shape, kind):
                findings.append(
                    AuditFinding(
                        "pallas-bounds", entry,
                        f"{kind} {problem}; block shape "
                        f"{ref_shape} (BlockSpec)",
                    )
                )
    return findings


def _race_findings(eqn, entry: str) -> List[AuditFinding]:
    import jax

    gm = eqn.params.get("grid_mapping")
    if gm is None:
        return []
    grid = tuple(getattr(gm, "grid", ()) or ())
    if not grid or not all(isinstance(d, int) for d in grid):
        return []  # dynamic grid: not statically decidable
    total = 1
    for d in grid:
        total *= max(1, d)
    if total > _MAX_GRID_POINTS or total <= 1:
        return []
    findings: List[AuditFinding] = []
    for out_i, bm in enumerate(gm.block_mappings_output):
        im = bm.index_map_jaxpr
        seen = {}
        for point in itertools.product(*(range(d) for d in grid)):
            try:
                block = tuple(
                    int(x)
                    for x in jax.core.eval_jaxpr(
                        im.jaxpr, im.consts, *point
                    )
                )
            except Exception:  # dynamic index map: skip this output
                break
            if block in seen and seen[block] != point:
                findings.append(
                    AuditFinding(
                        "pallas-race", entry,
                        f"output {out_i}: grid steps {seen[block]} and "
                        f"{point} both write block {block} "
                        f"(index_map not injective over grid {grid}) — "
                        "overlapping grid writes race",
                    )
                )
                break
            seen[block] = point
    return findings


def audit_pallas_jaxpr(closed_jaxpr, entry: str) -> List[AuditFinding]:
    """Bounds + race findings for every pallas_call in a traced
    computation."""
    from .counter import iter_pallas_eqns

    findings: List[AuditFinding] = []
    for eqn in iter_pallas_eqns(closed_jaxpr.jaxpr):
        findings.extend(
            _kernel_access_findings(eqn.params["jaxpr"], entry)
        )
        findings.extend(_race_findings(eqn, entry))
    return findings


def audit_pallas(fn, entry: str, *args) -> List[AuditFinding]:
    """Trace ``fn(*args)`` and audit every pallas_call inside."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as exc:  # noqa: BLE001 — report, don't crash
        return [
            AuditFinding(
                "config", entry,
                f"failed to trace for pallas audit: "
                f"{type(exc).__name__}: {exc}",
            )
        ]
    return audit_pallas_jaxpr(closed, entry)

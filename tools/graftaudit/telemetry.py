"""Check: telemetry stays off the hot path (PERF.md §21).

The telemetry layer's whole contract is that it observes the engine
WITHOUT changing its sync structure: span records and registry updates
happen only at already-host-side fetch boundaries.  Two ways to break
that silently:

* a registry/timeline call inside a **jitted or scan body** — at best
  it records once at trace time (lying metrics), at worst it smuggles a
  host callback into the compiled program (a per-step device→host round
  trip, the §15 sin with a new face);
* a registry/timeline call inside the **in-flight window** of the
  pipelined drive loop (the dispatch fill loop, PERF.md §18) — host
  work inserted between dispatches narrows the overlap the pipeline
  exists to create, without failing a single parity test.

``audit_telemetry`` statically walks a function (or a whole module) and
flags telemetry-shaped calls in either context.  Telemetry-shaped =
the dotted call chain mentions the telemetry surface (``telemetry``,
``timeline``, ``metric``, ``registry``) or uses its recording methods
(``record_fetch``/``record_drain``/``observe``).  Bare
``time.monotonic()`` stamps are NOT flagged — passing a dispatch
wall-clock through the in-flight deque as plain data is the sanctioned
pattern (the record itself happens at the fetch boundary).
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Optional, Set

from .findings import AuditFinding

#: Substrings of a dotted call chain that mark the telemetry surface.
_TELEMETRY_SUBSTRINGS = ("telemetry", "timeline", "metric", "registry")

#: Recording method names that are telemetry no matter the receiver.
_TELEMETRY_METHODS = frozenset({"record_fetch", "record_drain", "observe"})

#: Call names whose function argument becomes a device-side body: a
#: telemetry call inside one records at trace time (or worse).
_TRACED_WRAPPERS = frozenset(
    {"scan", "while_loop", "fori_loop", "jit", "pjit", "pallas_call",
     "checkpoint", "remat"}
)

#: Decorator names that make a def's body a traced body.
_JIT_DECORATORS = frozenset({"jit", "pjit"})


def _dotted_parts(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return parts


def _is_telemetry_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    parts = _dotted_parts(node.func)
    if not parts:
        return False
    if parts[0] in _TELEMETRY_METHODS:  # method name (attr chain head)
        return True
    low = ".".join(parts).lower()
    return any(s in low for s in _TELEMETRY_SUBSTRINGS)


def _decorator_names(fdef: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for dec in getattr(fdef, "decorator_list", []):
        node = dec.func if isinstance(dec, ast.Call) else dec
        parts = _dotted_parts(node)
        names.update(parts)
        # functools.partial(jit, ...) / jit(...) with args: the wrapper
        # name may sit in the call's arguments too.
        if isinstance(dec, ast.Call):
            for a in dec.args:
                names.update(_dotted_parts(a))
    return names


def _traced_defs(tree: ast.AST) -> List[ast.AST]:
    """Function/lambda nodes whose bodies are traced: jit-decorated
    defs, and defs/lambdas whose name (or node) is an argument to a
    scan/while_loop/fori_loop/jit/pallas_call call anywhere in the
    tree."""
    defs = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    traced: List[ast.AST] = []
    for name, fdef in defs.items():
        if _decorator_names(fdef) & _JIT_DECORATORS:
            traced.append(fdef)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        parts = _dotted_parts(node.func)
        if not parts or parts[0] not in _TRACED_WRAPPERS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                traced.append(arg)
            elif isinstance(arg, ast.Name) and arg.id in defs:
                traced.append(defs[arg.id])
    return traced


def _audit_tree(tree: ast.AST, entry: str) -> List[AuditFinding]:
    findings: List[AuditFinding] = []

    # (a) telemetry inside traced (jitted / scan / kernel) bodies.
    seen: Set[int] = set()
    for body in _traced_defs(tree):
        if id(body) in seen:
            continue
        seen.add(id(body))
        inner = body.body if isinstance(body.body, list) else [body.body]
        for stmt in inner:
            for sub in ast.walk(stmt):
                if _is_telemetry_call(sub):
                    name = getattr(body, "name", "<lambda>")
                    findings.append(
                        AuditFinding(
                            "telemetry", entry,
                            f"telemetry call inside traced body "
                            f"{name!r} (jit/scan/kernel) — records at "
                            "trace time at best, smuggles a per-step "
                            "host round trip at worst; telemetry "
                            "belongs at host-side fetch boundaries "
                            "(PERF.md §21)",
                        )
                    )

    # (b) telemetry inside the drive loop's in-flight (dispatch fill)
    # window: the nested while of the outermost while loop.
    fdef = next(
        (n for n in ast.walk(tree)
         if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))),
        None,
    )
    outer = next(
        (n for n in (fdef.body if fdef else [])
         if isinstance(n, ast.While)),
        None,
    )
    if outer is not None:
        # The fill loop may sit inside the fault-supervision try
        # (PERF.md §23) — keep finding it there, like the drive-fetch
        # audit's _first_nested_while.
        from .transfers import _first_nested_while

        inner = _first_nested_while(outer.body)
        if inner is not None:
            for sub in ast.walk(inner):
                if _is_telemetry_call(sub):
                    findings.append(
                        AuditFinding(
                            "telemetry", entry,
                            "telemetry call inside the drive loop's "
                            "in-flight window (the dispatch fill loop) "
                            "— host work between dispatches narrows "
                            "the pipeline overlap (PERF.md §18/§21); "
                            "record at the consumed fetch boundary, "
                            "and pass dispatch wall-clocks through the "
                            "deque as plain data",
                        )
                    )
    return findings


def audit_telemetry(fn, entry: str) -> List[AuditFinding]:
    """Statically audit one function (a drive loop, a step builder) for
    telemetry calls in traced bodies or the in-flight window."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as exc:
        return [
            AuditFinding(
                "config", entry,
                f"source unavailable for telemetry audit: {exc}",
            )
        ]
    return _audit_tree(tree, entry)


def audit_telemetry_module(module, entry: Optional[str] = None
                           ) -> List[AuditFinding]:
    """Module-wide variant: every traced body in ``module`` (scan
    bodies in the step builders, Pallas kernels) must be telemetry-
    free.  The in-flight-window check only fires on drive-loop-shaped
    functions, which modules of kernel builders don't have."""
    entry = entry or module.__name__
    try:
        src = inspect.getsource(module)
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as exc:
        return [
            AuditFinding(
                "config", entry,
                f"module source unavailable for telemetry audit: {exc}",
            )
        ]
    # Only the traced-body half applies module-wide: walk each def
    # independently so nested drive-shaped functions elsewhere don't
    # confuse the window check.
    findings: List[AuditFinding] = []
    seen: Set[int] = set()
    for body in _traced_defs(tree):
        if id(body) in seen:
            continue
        seen.add(id(body))
        for sub in ast.walk(body):
            if sub is body:
                continue
            if _is_telemetry_call(sub):
                name = getattr(body, "name", "<lambda>")
                findings.append(
                    AuditFinding(
                        "telemetry", entry,
                        f"telemetry call inside traced body {name!r} "
                        "(jit/scan/kernel) — records at trace time at "
                        "best, smuggles a per-step host round trip at "
                        "worst (PERF.md §21)",
                    )
                )
    return findings

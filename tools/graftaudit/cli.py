"""graftaudit command line: ``python -m tools.graftaudit``.

The semantic audit tier (PERF.md §16): traces and XLA-lowers every
``@audited_entry`` kernel/body on the CPU backend — never executing
anything — and checks

* ``budget``        pinned ops/candidate per kernel (KERNEL_BUDGETS.json, ±tol)
* ``dead-stage``    expand/hash/membership survive XLA optimization (§15 trap)
* ``float-leak``    integer hash pipeline stays float-free
* ``host-transfer`` no callbacks inside compiled sweep/superstep bodies
* ``pallas``        static load/store bounds + grid write-overlap
* ``telemetry``     registry/timeline calls stay off the hot path: none
                    in jitted/scan bodies or the drive loop's in-flight
                    window (PERF.md §21)

Exit codes: 0 clean, 1 findings, 2 usage error — same contract as
graftlint, keyed on by ``scripts/lint.sh`` and CI.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional, Sequence

#: Check-group names accepted by ``--select``.
CHECK_GROUPS = ("budgets", "stages", "purity", "transfers", "pallas",
                "telemetry", "faults")


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="graftaudit",
        description=(
            "jaxpr/HLO-level semantic audit: kernel op budgets, "
            "dead-stage (DCE) detection, float/transfer purity, Pallas "
            "bounds & race checks. Trace/lower only — runs entirely on "
            "the CPU backend."
        ),
    )
    parser.add_argument(
        "--select",
        metavar="GROUPS",
        help=f"comma-separated check groups (default: all of "
             f"{','.join(CHECK_GROUPS)})",
    )
    parser.add_argument(
        "--budgets",
        metavar="PATH",
        help="KERNEL_BUDGETS.json to check against (default: repo root)",
    )
    parser.add_argument(
        "--update-budgets",
        action="store_true",
        help="rewrite the budgets file from current counts (the "
             "deliberate-update workflow, PERF.md §16) and exit 0",
    )
    parser.add_argument(
        "--list-entries",
        action="store_true",
        help="print the audited-entry registry and exit",
    )
    parser.add_argument(
        "--summary",
        metavar="PATH",
        help="append the markdown budget diff table to PATH (CI: pass "
             "\"$GITHUB_STEP_SUMMARY\")",
    )
    parser.add_argument(
        "--metrics-json",
        metavar="PATH",
        help="write the audit run's telemetry snapshot (the process-"
             "wide registry — step/schema cache activity from the "
             "traced builds — plus audit entry/finding/elapsed gauges) "
             "as JSON to PATH; CI uploads it as a job artifact "
             "(PERF.md §21)",
    )
    return parser


def _selected(select: Optional[str]) -> List[str]:
    if not select:
        return list(CHECK_GROUPS)
    groups = [g.strip() for g in select.split(",") if g.strip()]
    unknown = [g for g in groups if g not in CHECK_GROUPS]
    if unknown:
        raise ValueError(
            f"unknown check group(s): {', '.join(unknown)} "
            f"(want {', '.join(CHECK_GROUPS)})"
        )
    return groups


def _list_entries() -> None:
    from . import harness

    entries = harness.registered_entries()
    budgets = harness.budget_configs()
    for name in sorted(entries):
        e = entries[name]
        extra = ""
        if e.budget_keys:
            extra = f"  budgets={','.join(e.budget_keys)}"
        if e.stages:
            extra += f"  stages={','.join(e.stages)}"
        print(f"{e.kind:<14} {name}  [{e.module}]{extra}")
    print(f"{len(entries)} entries, {len(budgets)} budget tiers")


def run_audit(
    groups: Sequence[str],
    budgets_path: Optional[str] = None,
    update_budgets: bool = False,
    summary_path: Optional[str] = None,
    metrics_json: Optional[str] = None,
) -> int:
    """The full audit; returns the process exit code."""
    from . import budgets as budgets_mod
    from . import harness
    from .findings import AuditFinding

    t0 = time.monotonic()
    findings: List[AuditFinding] = []
    entries = harness.registered_entries()
    bcfgs = harness.budget_configs()
    bodycfgs = harness.body_configs()
    stagecfgs = harness.stage_configs()
    extracfgs = harness.extra_kernel_configs()

    # -- registry/harness sync: every entry must be audited ----------------
    findings.extend(harness.coverage_findings())

    path = budgets_path or budgets_mod.DEFAULT_BUDGETS_PATH

    # -- trace each budget config ONCE; budgets/pallas/purity all read the
    # -- same closed jaxpr (tracing is the expensive step in the 120 s
    # -- budget; a failed build is one finding, not one per consumer)
    traced = {}  # key -> (closed_jaxpr, g, s)
    need_budget_counts = "budgets" in groups or update_budgets
    if need_budget_counts or "pallas" in groups or "purity" in groups:
        import jax

        for key, cfg in bcfgs.items():
            try:
                fn, g, s = cfg.build()
                traced[key] = (jax.make_jaxpr(fn)(), g, s)
            except Exception as exc:  # noqa: BLE001 — report, don't crash
                findings.append(
                    AuditFinding(
                        "config", key,
                        f"budget config failed to trace: "
                        f"{type(exc).__name__}: {exc}",
                    )
                )

    if need_budget_counts:
        from .counter import count_kernel_ops, kernel_jaxpr_of

        measured = {}
        for key, (closed, g, s) in traced.items():
            try:
                measured[key] = count_kernel_ops(
                    kernel_jaxpr_of(closed), g, s
                )[0]
            except ValueError as exc:  # no pallas_call in the trace
                findings.append(AuditFinding("config", key, str(exc)))
        if update_budgets:
            if findings:
                # Refuse to rewrite the pins over broken configs: a
                # partial budgets file would silently drop tiers.
                for finding in findings:
                    print(finding.render())
                print(
                    "graftaudit: NOT writing budgets — fix the "
                    f"{len(findings)} finding(s) above first",
                    file=sys.stderr,
                )
                return 1
            try:
                tol = float(
                    budgets_mod.load_budgets(path).get(
                        "tolerance_pct", budgets_mod.DEFAULT_TOLERANCE_PCT
                    )
                )
            except (FileNotFoundError, ValueError):
                tol = budgets_mod.DEFAULT_TOLERANCE_PCT
            budgets_mod.save_budgets(
                measured,
                {k: c.description for k, c in bcfgs.items()},
                path,
                tolerance_pct=tol,
            )
            print(f"graftaudit: wrote {len(measured)} budgets to {path}")
            return 0
        try:
            pinned = budgets_mod.load_budgets(path)
        except FileNotFoundError:
            findings.append(
                AuditFinding(
                    "config", "KERNEL_BUDGETS.json",
                    f"budgets file missing at {path}; seed it with "
                    "python -m tools.graftaudit --update-budgets",
                )
            )
            pinned = {"kernels": {}}
        except ValueError as exc:  # malformed JSON (merge markers, edits)
            findings.append(
                AuditFinding(
                    "config", "KERNEL_BUDGETS.json",
                    f"budgets file at {path} is not valid JSON ({exc}); "
                    "fix it or regenerate with --update-budgets",
                )
            )
            pinned = {"kernels": {}}
        failed = frozenset(bcfgs) - frozenset(measured)
        b_findings, rows = budgets_mod.compare_budgets(
            measured, pinned, failed=failed
        )
        findings.extend(b_findings)
        table = budgets_mod.render_table(rows)
        print(f"per-kernel op budgets (tolerance "
              f"±{pinned.get('tolerance_pct', 2.0):g}%):\n{table}",
              file=sys.stderr)
        if summary_path:
            md = budgets_mod.render_table(rows, markdown=True)
            with open(summary_path, "a", encoding="utf-8") as fh:
                fh.write("### graftaudit kernel budgets\n\n")
                fh.write(md + "\n")

    # -- pallas bounds/races over every kernel trace -----------------------
    if "pallas" in groups:
        import jax

        from .bounds import audit_pallas_jaxpr

        for key, (closed, _, _) in traced.items():
            findings.extend(
                audit_pallas_jaxpr(closed, f"{bcfgs[key].entry}[{key}]")
            )
        for name, build in extracfgs.items():
            try:
                fn, _, _ = build()
                closed = jax.make_jaxpr(fn)()
            except Exception as exc:  # noqa: BLE001
                findings.append(
                    AuditFinding(
                        "config", name,
                        f"failed to trace for pallas audit: "
                        f"{type(exc).__name__}: {exc}",
                    )
                )
                continue
            findings.extend(audit_pallas_jaxpr(closed, name))

    # -- float purity: integer stages + float-free kernel tiers ------------
    if "purity" in groups:
        from .counter import kernel_jaxpr_of
        from .purity import audit_float_purity, audit_float_purity_jaxpr

        for name, cfg in sorted(stagecfgs.items()):
            try:
                fn, args = cfg.build()
            except Exception as exc:  # noqa: BLE001 — report, don't crash
                findings.append(
                    AuditFinding(
                        "config", name,
                        f"stage config failed to build: "
                        f"{type(exc).__name__}: {exc}",
                    )
                )
                continue
            findings.extend(audit_float_purity(fn, args, name))
        for key, (closed, _, _) in traced.items():
            cfg = bcfgs[key]
            if not cfg.float_free:
                continue
            try:
                kernel = kernel_jaxpr_of(closed)
            except ValueError as exc:
                if not need_budget_counts:  # else already reported above
                    findings.append(AuditFinding("config", key, str(exc)))
                continue
            findings.extend(
                audit_float_purity_jaxpr(kernel, f"{cfg.entry}[{key}]")
            )

    # -- bodies: dead-stage + host transfers -------------------------------
    if "stages" in groups or "transfers" in groups:
        from .stages import audit_stage_text, compiled_text
        from .transfers import (
            audit_chunk_ring,
            audit_drive_loop,
            audit_host_transfers,
            audit_merge_loop,
            audit_pack_round,
            audit_serve_loop,
        )

        if "transfers" in groups:
            # Host side of the one-fetch-per-superstep contract: the
            # pipelined drive loop's fetch discipline (PERF.md §18),
            # the streaming chunk ring's consume discipline —
            # worker-owned transfers, unconditional release (§19) —
            # and the resident engine's serve round: callback-free,
            # one machine tick per job per round, no fetches (§20).
            from hashcat_a5_table_generator_tpu.runtime.engine import (
                Engine,
            )
            from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep

            findings.extend(
                audit_drive_loop(
                    Sweep._drive_superstep,
                    "runtime.Sweep._drive_superstep",
                )
            )
            findings.extend(
                audit_chunk_ring(
                    Sweep._sweep_chunks,
                    "runtime.Sweep._sweep_chunks",
                )
            )
            findings.extend(
                audit_serve_loop(
                    Engine._serve_round,
                    "runtime.Engine._serve_round",
                )
            )
            # The packed round (PERF.md §22): _serve_round stays
            # fetch-free — the fused group's pump owns the one packed
            # dispatch + counters fetch per round, with its own pinned
            # discipline.
            from hashcat_a5_table_generator_tpu.runtime.fuse import (
                FusedGroup,
            )

            findings.extend(
                audit_pack_round(
                    FusedGroup.pump,
                    "runtime.fuse.FusedGroup.pump",
                )
            )
            # The split merge (PERF.md §31): the router's k-way shard
            # merge runs once per hit on the reader threads — one wire
            # decode per round, parse-free drain bookkeeping, bounded
            # buffers.
            from hashcat_a5_table_generator_tpu.runtime.fleet import (
                _SplitMerge,
            )

            findings.extend(
                audit_merge_loop(
                    _SplitMerge,
                    "runtime.fleet._SplitMerge._merge_round",
                )
            )

        for name, cfg in sorted(bodycfgs.items()):
            entry = entries.get(name)
            stages = entry.stages if entry is not None else ()
            try:
                fn, args = cfg.build()
            except Exception as exc:  # noqa: BLE001
                findings.append(
                    AuditFinding(
                        "config", name,
                        f"body config failed to build: "
                        f"{type(exc).__name__}: {exc}",
                    )
                )
                continue
            if "transfers" in groups:
                findings.extend(audit_host_transfers(fn, args, name))
            if "stages" in groups and stages:
                try:
                    text = compiled_text(fn, args)
                except Exception as exc:  # noqa: BLE001
                    findings.append(
                        AuditFinding(
                            "config", name,
                            f"body failed to lower/compile on CPU: "
                            f"{type(exc).__name__}: {exc}",
                        )
                    )
                    continue
                findings.extend(audit_stage_text(text, name, stages))

    # -- fault hooks: injection seams keep the no-op-guarded shape ---------
    if "faults" in groups:
        from hashcat_a5_table_generator_tpu.ops.packing import (
            ChunkCompiler,
        )
        from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
            save_checkpoint,
        )
        from hashcat_a5_table_generator_tpu.runtime.autoscale import (
            Autoscaler,
        )
        from hashcat_a5_table_generator_tpu.runtime.engine import Engine
        from hashcat_a5_table_generator_tpu.runtime.fleet import (
            EngineLink,
            FleetRouter,
        )
        from hashcat_a5_table_generator_tpu.runtime.fuse import FusedGroup
        from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep

        from .faults import audit_fault_hooks

        for fn, name in (
            (Sweep._drive_superstep, "runtime.Sweep._drive_superstep"),
            (Sweep._dispatch_launch, "runtime.Sweep._dispatch_launch"),
            (Sweep._make_launch, "runtime.Sweep._make_launch"),
            (FusedGroup.pump, "runtime.fuse.FusedGroup.pump"),
            (Engine._build_slot, "runtime.Engine._build_slot"),
            (ChunkCompiler._timed, "ops.packing.ChunkCompiler._timed"),
            (save_checkpoint, "runtime.checkpoint.save_checkpoint"),
            # The fleet seams (PERF.md §27): placement, the link's
            # outbound writes (op stream + health stream), and the
            # autoscaler's spawn.
            (FleetRouter._dispatch, "runtime.FleetRouter._dispatch"),
            (EngineLink.send, "runtime.fleet.EngineLink.send"),
            (EngineLink.health_request,
             "runtime.fleet.EngineLink.health_request"),
            (Autoscaler._scale_up,
             "runtime.autoscale.Autoscaler._scale_up"),
        ):
            findings.extend(audit_fault_hooks(fn, name))

    # -- telemetry placement: registry/timeline calls off the hot path ----
    if "telemetry" in groups:
        import hashcat_a5_table_generator_tpu.models.attack as _attack
        import hashcat_a5_table_generator_tpu.ops.pallas_expand as _pe
        import hashcat_a5_table_generator_tpu.ops.pallas_md5 as _pm
        import hashcat_a5_table_generator_tpu.parallel.mesh as _mesh
        from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep

        from .telemetry import audit_telemetry, audit_telemetry_module

        findings.extend(
            audit_telemetry(
                Sweep._drive_superstep, "runtime.Sweep._drive_superstep"
            )
        )
        findings.extend(
            audit_telemetry(
                Sweep._launches, "runtime.Sweep._launches"
            )
        )
        for mod in (_attack, _mesh, _pe, _pm):
            findings.extend(audit_telemetry_module(mod))

    for finding in findings:
        print(finding.render())
    elapsed = time.monotonic() - t0
    n_entries = len(entries)
    if metrics_json:
        import json

        from hashcat_a5_table_generator_tpu.runtime import telemetry

        telemetry.gauge("graftaudit.entries").set(n_entries)
        telemetry.gauge("graftaudit.findings").set(len(findings))
        telemetry.gauge("graftaudit.elapsed_s").set(round(elapsed, 3))
        with open(metrics_json, "w", encoding="utf-8") as fh:
            json.dump(
                {
                    "metrics": telemetry.snapshot(),
                    "groups": list(groups),
                    "findings": len(findings),
                },
                fh, indent=2,
            )
            fh.write("\n")
    if findings:
        print(
            f"graftaudit: {len(findings)} finding(s) across {n_entries} "
            f"entries in {elapsed:.1f}s",
            file=sys.stderr,
        )
        return 1
    print(
        f"graftaudit: clean — {n_entries} entries, "
        f"{len(bcfgs)} budget tiers, {elapsed:.1f}s",
        file=sys.stderr,
    )
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    # Trace/lower only: pin the CPU backend before jax ever initializes
    # (idempotent if the caller already set it).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    args = _build_parser().parse_args(argv)
    if args.list_entries:
        _list_entries()
        return 0
    try:
        groups = _selected(args.select)
    except ValueError as exc:
        print(f"graftaudit: error: {exc}", file=sys.stderr)
        return 2
    return run_audit(
        groups,
        budgets_path=args.budgets,
        update_budgets=args.update_budgets,
        summary_path=args.summary,
        metrics_json=args.metrics_json,
    )


if __name__ == "__main__":
    sys.exit(main())

"""Check (c1): float purity of the integer hash/membership pipeline.

The hash rounds and the digest-set membership search are pure uint32/
int32 arithmetic; a float ``convert_element_type`` sneaking in (an
accidental ``jnp.mean``, a ``/`` where ``//`` was meant, a numpy float
scalar promoting a whole chain) silently costs precision above 2^24 —
the exact bug class ``_exact_div``'s ±1 fixup exists to contain, except
*outside* its guarded scope nothing contains it.  The audit traces each
``integer_stage`` entry and the K=1 kernel tiers and fails on ANY
floating-point dtype in the jaxpr.

(The general K-way kernel's f32 mixed-radix decode is the one deliberate
float island — PERF.md §7; its budget config opts out via
``float_free=False``.)
"""

from __future__ import annotations

from typing import List

import numpy as np

from .findings import AuditFinding


def _is_float(aval) -> bool:
    dtype = getattr(aval, "dtype", None)
    return dtype is not None and np.issubdtype(dtype, np.floating)


def float_eqns(jaxpr, _path: str = "") -> List[str]:
    """Descriptions of every float-producing eqn, recursing through
    nested jaxprs (scan/cond bodies, inner jits, pallas kernels)."""
    out: List[str] = []
    for eqn in jaxpr.eqns:
        subs = []
        for val in eqn.params.values():
            for cand in val if isinstance(val, (tuple, list)) else (val,):
                if hasattr(cand, "eqns"):
                    subs.append(cand)
                elif hasattr(getattr(cand, "jaxpr", None), "eqns"):
                    subs.append(cand.jaxpr)
        if subs:
            for sub in subs:
                out.extend(float_eqns(sub, _path))
            continue
        for v in eqn.outvars:
            if _is_float(v.aval):
                out.append(f"{eqn.primitive.name} -> {v.aval.str_short()}")
                break
    return out


def audit_float_purity(fn, args, entry: str) -> List[AuditFinding]:
    """Trace ``fn(*args)`` and fail on any float dtype in the jaxpr."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as exc:  # noqa: BLE001 — report, don't crash
        return [
            AuditFinding(
                "config", entry,
                f"failed to trace for float-purity: "
                f"{type(exc).__name__}: {exc}",
            )
        ]
    return audit_float_purity_jaxpr(closed.jaxpr, entry)


def audit_float_purity_jaxpr(jaxpr, entry: str) -> List[AuditFinding]:
    leaks = float_eqns(jaxpr)
    if not leaks:
        return []
    shown = "; ".join(leaks[:4]) + ("; …" if len(leaks) > 4 else "")
    return [
        AuditFinding(
            "float-leak", entry,
            f"{len(leaks)} float-typed eqn(s) in the integer pipeline "
            f"({shown}) — uint32 hash arithmetic must never pass "
            "through float (precision loss above 2^24)",
        )
    ]

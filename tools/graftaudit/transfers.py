"""Check (c2): no device→host transfers inside compiled sweep bodies.

The launch loop's whole design is "two scalars and two small masks per
fetch" (honest-sync rule, PERF.md §0/§15): a callback smuggled into a
jitted body — ``jax.debug.print``, ``io_callback``, ``pure_callback``,
host ``debug_callback`` — forces a device→host round trip *per
invocation*, and inside a ``lax.scan``/``while_loop`` body it fires per
STEP, turning the superstep executor's one-fetch-per-superstep contract
into S hidden syncs.  graftlint GL011 catches the lexical ``int()``/
``.item()`` forms; this audit catches what only the trace can see.
"""

from __future__ import annotations

from typing import List, Tuple

from .findings import AuditFinding

#: Primitives that are host round trips by construction.
TRANSFER_PRIMITIVES = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "infeed",
        "outfeed",
        "host_callback_call",
    }
)

#: Primitives whose sub-jaxprs re-run per device-side iteration — a
#: transfer inside one is a per-step sync, the worst case.
_LOOP_PRIMITIVES = frozenset({"scan", "while", "fori"})


def find_transfers(jaxpr, in_loop: bool = False) -> List[Tuple[str, bool]]:
    """``(primitive_name, inside_loop_body)`` for every transfer eqn."""
    out: List[Tuple[str, bool]] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in TRANSFER_PRIMITIVES:
            out.append((name, in_loop))
        child_in_loop = in_loop or name in _LOOP_PRIMITIVES
        for val in eqn.params.values():
            for cand in val if isinstance(val, (tuple, list)) else (val,):
                if hasattr(cand, "eqns"):
                    out.extend(find_transfers(cand, child_in_loop))
                elif hasattr(getattr(cand, "jaxpr", None), "eqns"):
                    out.extend(find_transfers(cand.jaxpr, child_in_loop))
    return out


def audit_host_transfers(fn, args, entry: str) -> List[AuditFinding]:
    """Trace ``fn(*args)`` and flag every transfer primitive."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as exc:  # noqa: BLE001 — report, don't crash
        return [
            AuditFinding(
                "config", entry,
                f"failed to trace for host-transfer audit: "
                f"{type(exc).__name__}: {exc}",
            )
        ]
    return audit_host_transfers_jaxpr(closed.jaxpr, entry)


def audit_host_transfers_jaxpr(jaxpr, entry: str) -> List[AuditFinding]:
    found = find_transfers(jaxpr)
    findings: List[AuditFinding] = []
    for name, in_loop in found:
        where = (
            "inside a device loop body (fires per step!)"
            if in_loop
            else "in the compiled body"
        )
        findings.append(
            AuditFinding(
                "host-transfer", entry,
                f"{name} {where} — device->host round trip breaks the "
                "one-fetch-per-launch contract (PERF.md §15)",
            )
        )
    return findings

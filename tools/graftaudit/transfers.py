"""Check (c2): no device→host transfers inside compiled sweep bodies,
and fetch discipline in the PIPELINED superstep drive loop.

The launch loop's whole design is "two scalars and two small masks per
fetch" (honest-sync rule, PERF.md §0/§15): a callback smuggled into a
jitted body — ``jax.debug.print``, ``io_callback``, ``pure_callback``,
host ``debug_callback`` — forces a device→host round trip *per
invocation*, and inside a ``lax.scan``/``while_loop`` body it fires per
STEP, turning the superstep executor's one-fetch-per-superstep contract
into S hidden syncs.  graftlint GL011 catches the lexical ``int()``/
``.item()`` forms; this audit catches what only the trace can see.

The second half (:func:`audit_drive_loop`) audits the HOST side of the
same contract for the double-buffered drive (PERF.md §18): the drive
loop must issue exactly ONE unconditional device→host fetch per
superstep (the stacked counters of the POPPED, i.e. oldest, in-flight
superstep — its lagged completion barrier), may fetch the hit buffers
only behind a hit-count guard, must never fetch a result dispatched in
the same iteration's fill loop (that would barrier the IN-FLIGHT
superstep and undo the overlap), and must never call
``block_until_ready``.  A second unconditional fetch is the classic
double-fetch regression — it turns the pipeline back into a barrier
without failing a single parity test.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Set, Tuple

from .findings import AuditFinding

#: Primitives that are host round trips by construction.
TRANSFER_PRIMITIVES = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "infeed",
        "outfeed",
        "host_callback_call",
    }
)

#: Primitives whose sub-jaxprs re-run per device-side iteration — a
#: transfer inside one is a per-step sync, the worst case.
_LOOP_PRIMITIVES = frozenset({"scan", "while", "fori"})


def find_transfers(jaxpr, in_loop: bool = False) -> List[Tuple[str, bool]]:
    """``(primitive_name, inside_loop_body)`` for every transfer eqn."""
    out: List[Tuple[str, bool]] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in TRANSFER_PRIMITIVES:
            out.append((name, in_loop))
        child_in_loop = in_loop or name in _LOOP_PRIMITIVES
        for val in eqn.params.values():
            for cand in val if isinstance(val, (tuple, list)) else (val,):
                if hasattr(cand, "eqns"):
                    out.extend(find_transfers(cand, child_in_loop))
                elif hasattr(getattr(cand, "jaxpr", None), "eqns"):
                    out.extend(find_transfers(cand.jaxpr, child_in_loop))
    return out


def audit_host_transfers(fn, args, entry: str) -> List[AuditFinding]:
    """Trace ``fn(*args)`` and flag every transfer primitive."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as exc:  # noqa: BLE001 — report, don't crash
        return [
            AuditFinding(
                "config", entry,
                f"failed to trace for host-transfer audit: "
                f"{type(exc).__name__}: {exc}",
            )
        ]
    return audit_host_transfers_jaxpr(closed.jaxpr, entry)


#: Call shapes that coerce a device value to the host: builtins applied
#: to (derivatives of) a fetched result, numpy/jax coercions, and the
#: explicit sync.
_FETCH_BUILTINS = frozenset({"int", "float", "bool"})
_FETCH_ATTRS = frozenset({"asarray", "array", "item", "device_get"})


def _base_names(node: ast.AST) -> Set[str]:
    """Every bare Name referenced under ``node``."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _assigned_names(target: ast.AST) -> Set[str]:
    """Names bound by an assignment target (tuples included)."""
    return {
        n.id
        for n in ast.walk(target)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
    }


def _is_fetch_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in _FETCH_BUILTINS
    if isinstance(f, ast.Attribute):
        return f.attr in _FETCH_ATTRS
    return False


def _first_nested_while(stmts) -> "ast.While | None":
    """The drive loop's dispatch (fill) ``while``, found through the
    container statements that legitimately wrap it — since the fault-
    supervision try (PERF.md §23), the fill loop sits inside a ``Try``;
    the in-flight tracking must keep seeing it there (and under
    ``with`` blocks), or the audit silently stops detecting in-flight
    fetches."""
    for stmt in stmts:
        if isinstance(stmt, ast.While):
            return stmt
        inner: "List[ast.stmt]" = []
        if isinstance(stmt, ast.Try):
            inner = list(stmt.body)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = list(stmt.body)
        if inner:
            found = _first_nested_while(inner)
            if found is not None:
                return found
    return None


def _hostside_names(root: ast.AST) -> Set[str]:
    """Names bound DIRECTLY from a fetch call (``counters =
    np.asarray(out["counters"])``) — and transitively from them — hold
    host-materialized values: a later coercion (``int(counters[0])``)
    is host arithmetic, not another device round trip; the binding
    fetch is the one that counts.  Shared by the drive-loop and
    packed-round audits (one fixed-point, one behavior)."""
    hostside: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for stmt in ast.walk(root):
            if isinstance(stmt, ast.Assign):
                val = stmt.value
                bases = _base_names(val)
                if (
                    isinstance(val, ast.Call) and _is_fetch_call(val)
                ) or (bases and bases <= hostside):
                    new = set()
                    for t in stmt.targets:
                        new |= _assigned_names(t)
                    if new - hostside:
                        hostside |= new
                        changed = True
    return hostside


def audit_drive_loop(fn, entry: str) -> List[AuditFinding]:
    """Statically audit a superstep drive loop's fetch discipline.

    Walks ``fn``'s outermost ``while`` loop: names bound from a
    ``.popleft()`` (and anything derived from them) are the FETCHED
    superstep — the only sanctioned fetch target; names bound inside the
    nested dispatch (fill) ``while`` are IN-FLIGHT and must never be
    coerced to the host.  Exactly one unconditional fetch of the popped
    result per iteration (the counters barrier); any other fetch must
    sit under an ``if`` (the rare hit-slice path).  ``block_until_ready``
    anywhere in the function is a finding on its own.
    """
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as exc:
        return [
            AuditFinding(
                "config", entry,
                f"drive loop source unavailable for fetch audit: {exc}",
            )
        ]
    findings: List[AuditFinding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "block_until_ready"
        ):
            findings.append(
                AuditFinding(
                    "drive-fetch", entry,
                    "block_until_ready in the superstep drive loop — a "
                    "sync on the in-flight buffer set barriers the "
                    "pipeline (PERF.md §18); the popped counters fetch "
                    "is the only sanctioned barrier",
                )
            )
    fdef = next(
        (n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)), None
    )
    outer = next(
        (n for n in (fdef.body if fdef else []) if isinstance(n, ast.While)),
        None,
    )
    if outer is None:
        findings.append(
            AuditFinding(
                "config", entry,
                "drive loop has no top-level while loop to audit",
            )
        )
        return findings

    popped: Set[str] = set()
    inflight: Set[str] = set()
    for stmt in ast.walk(outer):
        if isinstance(stmt, ast.Assign):
            val = stmt.value
            if (
                isinstance(val, ast.Call)
                and isinstance(val.func, ast.Attribute)
                and val.func.attr == "popleft"
            ):
                for t in stmt.targets:
                    popped |= _assigned_names(t)
    # Derived names: assignments whose value mentions a popped name.
    changed = True
    while changed:
        changed = False
        for stmt in ast.walk(outer):
            if isinstance(stmt, ast.Assign):
                if _base_names(stmt.value) & popped:
                    new = set()
                    for t in stmt.targets:
                        new |= _assigned_names(t)
                    if new - popped:
                        popped |= new
                        changed = True
    inner = _first_nested_while(outer.body)
    if inner is not None:
        for stmt in ast.walk(inner):
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    inflight |= _assigned_names(t)
            # The production dispatch binds nothing: it appends the call
            # result straight into the pending deque.  The CONTAINER is
            # then the in-flight handle — a fetch through it (e.g.
            # ``int(pending[-1][1][...])``) barriers the pipeline just
            # as surely as a fetch of a named result.
            if (
                isinstance(stmt, ast.Call)
                and isinstance(stmt.func, ast.Attribute)
                and stmt.func.attr in ("append", "appendleft")
                and isinstance(stmt.func.value, ast.Name)
            ):
                inflight.add(stmt.func.value.id)
    # Aliases of in-flight values bound in the OUTER body (e.g.
    # ``fut = pending[-1]``) are in-flight too; the popped names stay
    # sanctioned (``out = pending.popleft()`` mentions the container but
    # binds the fetched-superstep result).
    changed = True
    while changed:
        changed = False
        for stmt in ast.walk(outer):
            if isinstance(stmt, ast.Assign):
                if _base_names(stmt.value) & inflight:
                    new = set()
                    for t in stmt.targets:
                        new |= _assigned_names(t)
                    if new - popped - inflight:
                        inflight |= new - popped
                        changed = True
    inflight -= popped
    hostside = _hostside_names(outer)

    def fetch_nodes(node, conditional: bool, looped: bool):
        out = []
        for sub in ast.walk(node):
            if _is_fetch_call(sub):
                names = set()
                for arg in sub.args:
                    names |= _base_names(arg)
                out.append((sub, names, conditional, looped))
        return out

    def fetches_in(stmts, conditional: bool, looped: bool = False):
        out = []
        for stmt in stmts:
            if isinstance(stmt, (ast.If, ast.For, ast.While)):
                # The TEST runs every iteration — a fetch written as a
                # condition (``if int(out["n_hits"]):``) is as
                # unconditional as a bare statement.
                test = getattr(stmt, "test", getattr(stmt, "iter", None))
                if test is not None:
                    out += fetch_nodes(test, conditional, looped)
                cond = conditional or isinstance(stmt, ast.If)
                # A nested loop's body runs per-iteration: ONE fetch
                # call node there is MANY round trips per superstep —
                # the double-fetch regression written as a loop.
                loop = looped or not isinstance(stmt, ast.If)
                out += fetches_in(stmt.body, cond, loop)
                out += fetches_in(stmt.orelse, cond, looped)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                # A context manager (profiler annotation, lock) does not
                # gate its body — guards nested INSIDE it must keep
                # their conditionality instead of being walked flat.
                for item in stmt.items:
                    out += fetch_nodes(item.context_expr, conditional,
                                       looped)
                out += fetches_in(stmt.body, conditional, looped)
                continue
            if isinstance(stmt, ast.Try):
                out += fetches_in(stmt.body, conditional, looped)
                for h in stmt.handlers:
                    out += fetches_in(h.body, True, looped)
                out += fetches_in(stmt.orelse, True, looped)
                out += fetches_in(stmt.finalbody, conditional, looped)
                continue
            out += fetch_nodes(stmt, conditional, looped)
        return out

    unconditional_popped = 0
    for node, names, conditional, looped in fetches_in(outer.body, False):
        if names & inflight:
            findings.append(
                AuditFinding(
                    "drive-fetch", entry,
                    "device→host fetch of a just-dispatched (in-flight) "
                    "superstep's result — barriers the pipeline's "
                    "overlap; only the POPPED superstep may be fetched "
                    "(PERF.md §18)",
                )
            )
        elif names & popped:
            if isinstance(node.func, ast.Name):
                # int()/float() on a popped DERIVATIVE (an already-
                # fetched numpy value) is host arithmetic, not a new
                # device round trip; only the coercion landing directly
                # on the device result counts.  An arg that CONTAINS a
                # fetch call (``int(np.asarray(out[...])[0])``) is the
                # inline spelling of the bound form — the inner call is
                # the round trip and is counted on its own.
                direct = any(
                    isinstance(a, ast.Subscript)
                    and _base_names(a) & popped
                    and not _base_names(a) <= hostside
                    and not any(_is_fetch_call(s) for s in ast.walk(a))
                    for a in node.args
                )
                if not direct:
                    continue
            if not conditional:
                # Inside a nested loop one call NODE is N executions —
                # count it as (at least) two round trips so the
                # exactly-one tally trips.
                unconditional_popped += 2 if looped else 1
    if unconditional_popped != 1:
        findings.append(
            AuditFinding(
                "drive-fetch", entry,
                f"{unconditional_popped} unconditional device→host "
                "fetches of the popped superstep per iteration (want "
                "exactly one — the stacked counters barrier; hit-buffer "
                "fetches belong behind the hit-count guard). A second "
                "unconditional fetch is the double-fetch regression "
                "(PERF.md §18)",
            )
        )
    return findings


def audit_serve_loop(fn, entry: str) -> List[AuditFinding]:
    """Statically audit the resident engine's multiplexing round
    (PERF.md §20) — the drive loop that interleaves many tenant sweeps
    by advancing their machines at superstep boundaries.

    The contract that keeps the one-fetch-per-superstep discipline
    (PERF.md §18) alive ACROSS interleaved jobs:

    * the machines own every device→host round trip — any fetch-shaped
      call (``int()``/``np.asarray()``/``.item()``/...) in the serve
      round barriers EVERY tenant behind one job's in-flight device
      work, and ``block_until_ready`` anywhere is the same sin spelled
      explicitly;
    * each runnable job advances by exactly ONE boundary tick per round
      — one ``next()`` call node in the round's job loop.  Zero ticks
      is a round that serves nobody; two is double-stepping (one
      tenant's latency doubles everyone's); a ``next()`` inside a
      NESTED loop is the monopolization regression — draining one job
      to completion while the other tenants starve.
    """
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as exc:
        return [
            AuditFinding(
                "config", entry,
                f"serve loop source unavailable for audit: {exc}",
            )
        ]
    findings: List[AuditFinding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "block_until_ready"
        ):
            findings.append(
                AuditFinding(
                    "serve-loop", entry,
                    "block_until_ready in the serve round — a sync here "
                    "barriers every tenant behind one job's device work "
                    "(PERF.md §20); the machines own the per-superstep "
                    "barrier",
                )
            )
        if _is_fetch_call(node):
            findings.append(
                AuditFinding(
                    "serve-loop", entry,
                    "device→host fetch in the serve round — the sweep "
                    "machines own every round trip (the lagged counters "
                    "barrier, PERF.md §18); a fetch in the scheduler "
                    "barriers every tenant (PERF.md §20)",
                )
            )
    fdef = next(
        (n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)), None
    )
    loop = next(
        (n for n in (fdef.body if fdef else [])
         if isinstance(n, (ast.For, ast.While))),
        None,
    )
    if loop is None:
        findings.append(
            AuditFinding(
                "config", entry,
                "serve round has no top-level job loop to audit",
            )
        )
        return findings

    def is_tick(node) -> bool:
        return (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "next"
        )

    def tick_nodes(stmts, looped: bool):
        # Recurse with the loop flag carried through EVERY nesting
        # shape — a drain loop hidden under if/try/with must still
        # read as looped (the sibling drive-loop audit learned the
        # same lesson about guarded fetches).
        out = []
        for stmt in stmts:
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                # The loop HEAD evaluates per iteration too — a tick in
                # a while condition is the drain written as a test.
                head = (
                    stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor))
                    else stmt.test
                )
                out += [(n, True) for n in ast.walk(head) if is_tick(n)]
                for body in (stmt.body, stmt.orelse):
                    out += tick_nodes(body, True)
                continue
            if isinstance(stmt, ast.If):
                out += [(n, looped) for n in ast.walk(stmt.test)
                        if is_tick(n)]
                out += tick_nodes(stmt.body, looped)
                out += tick_nodes(stmt.orelse, looped)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    out += [(n, looped)
                            for n in ast.walk(item.context_expr)
                            if is_tick(n)]
                out += tick_nodes(stmt.body, looped)
                continue
            if isinstance(stmt, ast.Try):
                for body in (stmt.body, stmt.orelse, stmt.finalbody):
                    out += tick_nodes(body, looped)
                for h in stmt.handlers:
                    out += tick_nodes(h.body, looped)
                continue
            out += [(n, looped) for n in ast.walk(stmt) if is_tick(n)]
        return out

    ticks = tick_nodes(loop.body, False)
    if any(looped for _n, looped in ticks):
        findings.append(
            AuditFinding(
                "serve-loop", entry,
                "next() inside a nested loop of the serve round — "
                "draining one job to completion monopolizes the engine "
                "and starves the other tenants; one boundary tick per "
                "job per round (PERF.md §20)",
            )
        )
    n_ticks = len(ticks)
    if n_ticks != 1:
        findings.append(
            AuditFinding(
                "serve-loop", entry,
                f"{n_ticks} machine tick(s) (next() call nodes) per job "
                "per serve round (want exactly one): each runnable job "
                "advances one fetched superstep boundary per round, so "
                "tenants interleave fairly (PERF.md §20)",
            )
        )
    return findings


def _is_dispatch_call(node: ast.AST) -> bool:
    """The fused group's one device dispatch site: ``self._call(...)``
    (or a bare ``call(...)`` in fixtures)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "call"
    if isinstance(f, ast.Attribute):
        return f.attr in ("call", "_call")
    return False


def audit_pack_round(fn, entry: str) -> List[AuditFinding]:
    """Statically audit the cross-job packed dispatch round
    (``runtime.fuse.FusedGroup.pump``, PERF.md §22).

    The packed round exists to replace N per-job dispatch+fetch round
    trips with ONE — so its own discipline is the whole point:

    * exactly one dispatch call site (``self._call``), and never inside
      a ``for`` loop — a dispatch in the per-member loop is the
      per-job-dispatch regression, the packed round quietly degraded
      back to N round trips per round;
    * exactly one UNCONDITIONAL device→host fetch (the segmented
      counters — the round's single completion barrier); the hit slice
      may be fetched only behind the hit-count guard, exactly the solo
      drive's contract (PERF.md §18);
    * NO fetch of device results inside any ``for`` loop — per-member
      splitting is host bookkeeping over the already-materialized
      arrays; a fetch hidden in the segment bookkeeping barriers the
      round once per member;
    * ``block_until_ready`` nowhere.

    Names bound directly from a fetch call (``counters =
    np.asarray(out["counters"])``) are host-materialized — arithmetic
    on them is not a round trip; device results are the names bound
    from the in-flight ``popleft()`` and the dispatch call itself.
    """
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as exc:
        return [
            AuditFinding(
                "config", entry,
                f"packed round source unavailable for audit: {exc}",
            )
        ]
    findings: List[AuditFinding] = []
    fdef = next(
        (n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)), None
    )
    if fdef is None:
        return [
            AuditFinding("config", entry,
                         "packed round has no function body to audit")
        ]
    for node in ast.walk(fdef):
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "block_until_ready"
        ):
            findings.append(
                AuditFinding(
                    "pack-round", entry,
                    "block_until_ready in the packed round — the one "
                    "counters fetch IS the round's completion barrier "
                    "(PERF.md §22)",
                )
            )
    # Device-result names: bound from the in-flight pop or a dispatch.
    device: Set[str] = set()
    for stmt in ast.walk(fdef):
        if isinstance(stmt, ast.Assign):
            val = stmt.value
            popped = (
                isinstance(val, ast.Call)
                and isinstance(val.func, ast.Attribute)
                and val.func.attr in ("popleft", "pop")
            )
            if popped or _is_dispatch_call(val):
                for t in stmt.targets:
                    device |= _assigned_names(t)
    device -= _hostside_names(fdef)

    dispatches: List[Tuple[ast.Call, bool]] = []
    fetches: List[Tuple[ast.Call, bool, bool]] = []

    def scan(node, conditional: bool, in_for: bool) -> None:
        for sub in ast.walk(node):
            if _is_dispatch_call(sub):
                dispatches.append((sub, in_for))
            elif _is_fetch_call(sub):
                names = set()
                for arg in sub.args:
                    names |= _base_names(arg)
                if not (names & device):
                    continue  # host arithmetic on fetched values
                fetches.append((sub, conditional, in_for))

    def walk(stmts, conditional: bool, in_for: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                scan(stmt.iter, conditional, in_for)
                walk(stmt.body, conditional, True)
                walk(stmt.orelse, conditional, in_for)
            elif isinstance(stmt, ast.While):
                # The dispatch-ahead fill loop is a while by contract;
                # a tick of its TEST runs per iteration like a body
                # statement.
                scan(stmt.test, conditional, in_for)
                walk(stmt.body, conditional, in_for)
                walk(stmt.orelse, conditional, in_for)
            elif isinstance(stmt, ast.If):
                scan(stmt.test, conditional, in_for)
                walk(stmt.body, True, in_for)
                walk(stmt.orelse, True, in_for)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    scan(item.context_expr, conditional, in_for)
                walk(stmt.body, conditional, in_for)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body, conditional, in_for)
                for h in stmt.handlers:
                    walk(h.body, True, in_for)
                walk(stmt.orelse, True, in_for)
                walk(stmt.finalbody, conditional, in_for)
            else:
                scan(stmt, conditional, in_for)

    walk(fdef.body, False, False)
    if any(in_for for _n, in_for in dispatches):
        findings.append(
            AuditFinding(
                "pack-round", entry,
                "device dispatch inside a for loop of the packed round "
                "— the per-job-dispatch regression: the fused group "
                "exists to issue ONE physical dispatch per round, not "
                "one per member (PERF.md §22)",
            )
        )
    if len(dispatches) != 1:
        findings.append(
            AuditFinding(
                "pack-round", entry,
                f"{len(dispatches)} dispatch call site(s) in the packed "
                "round (want exactly one — the dispatch-ahead fill loop "
                "drives it; PERF.md §22)",
            )
        )
    if any(in_for for _n, _c, in_for in fetches):
        findings.append(
            AuditFinding(
                "pack-round", entry,
                "device→host fetch inside a for loop of the packed "
                "round — a fetch hidden in the per-member segment "
                "bookkeeping barriers the round once per member; split "
                "results from the already-fetched arrays (PERF.md §22)",
            )
        )
    n_uncond = sum(
        1 for _n, conditional, _l in fetches if not conditional
    )
    if n_uncond != 1:
        findings.append(
            AuditFinding(
                "pack-round", entry,
                f"{n_uncond} unconditional device→host fetches per "
                "packed round (want exactly one — the segmented "
                "counters barrier; the hit slice belongs behind the "
                "hit-count guard, PERF.md §22)",
            )
        )
    return findings


def _self_attr_of(node: ast.AST) -> "str | None":
    """The ``self`` attribute a call lands on, looked through
    subscripts: ``self._bufs[i].append`` → ``"_bufs"``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


#: Calls that drain a buffer — the release half of the merge's
#: bounded-buffering contract.
_DRAIN_ATTRS = frozenset({"popleft", "pop", "clear"})


def _class_node(cls) -> ast.ClassDef:
    """The ``ClassDef`` for ``cls``, tolerating classes from
    dynamically-loaded modules (the fixture loader) where
    ``inspect.getsource`` can't resolve a class (no ``sys.modules``
    entry) — a method's code object still knows the file."""
    try:
        tree = ast.parse(textwrap.dedent(inspect.getsource(cls)))
    except (OSError, TypeError, SyntaxError):
        path = None
        for val in cls.__dict__.values():
            code = getattr(val, "__code__", None)
            if code is not None and code.co_filename:
                path = code.co_filename
                break
        if path is None:
            raise OSError(f"no source file for {cls!r}") from None
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls.__name__:
            return node
    raise OSError(f"no class body for {cls!r} in its source")


def audit_merge_loop(cls, entry: str) -> List[AuditFinding]:
    """Statically audit the split router's shard-merge round
    (``runtime.fleet._SplitMerge``, PERF.md §31) — the per-hit path
    that folds N shard streams into one ordered client stream.

    The merge sits on the router's reader threads, once per hit, for
    every split job at once — so its own discipline is what keeps
    giant-job striping from moving the bottleneck into the router:

    * exactly ONE unconditional decode (``int()``/``float()``/...) of
      the wire event per merge round — the hit's rank string parses
      once, at ingress; a second decode is per-hit work duplicated
      across the whole merged stream;
    * NO decode inside a ``for`` loop — the k-way drain bookkeeping
      compares already-parsed keys; a parse hidden in the per-shard
      scan re-decodes once per shard per hit (the merge spelling of
      the per-member-fetch regression, PERF.md §22);
    * every buffer the round ``.append``s to must drain — the same
      self attribute must ``.popleft``/``.pop``/``.clear`` somewhere
      in the class.  An append-only buffer is unbounded: one stalled
      shard would hoard every sibling's hits for the rest of the job
      instead of bounding the buffer at the stripe lag.

    Takes the merge CLASS (the drain discipline is class-wide: the
    round appends, the shared drain helper pops) and audits its
    ``_merge_round`` method.
    """
    try:
        cdef = _class_node(cls)
    except (OSError, TypeError, SyntaxError) as exc:
        return [
            AuditFinding(
                "config", entry,
                f"merge round source unavailable for audit: {exc}",
            )
        ]
    fdef = next(
        (
            n for n in ast.walk(cdef)
            if isinstance(n, ast.FunctionDef) and n.name == "_merge_round"
        ),
        None,
    )
    if fdef is None:
        return [
            AuditFinding("config", entry,
                         "merge class has no _merge_round to audit")
        ]
    findings: List[AuditFinding] = []

    decodes: List[Tuple[ast.Call, bool, bool]] = []
    appended: Set[str] = set()

    def scan(node, conditional: bool, in_for: bool) -> None:
        for sub in ast.walk(node):
            if _is_fetch_call(sub):
                decodes.append((sub, conditional, in_for))
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("append", "appendleft")
            ):
                name = _self_attr_of(sub.func.value)
                if name is not None:
                    appended.add(name)

    def walk(stmts, conditional: bool, in_for: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                scan(stmt.iter, conditional, in_for)
                walk(stmt.body, conditional, True)
                walk(stmt.orelse, conditional, in_for)
            elif isinstance(stmt, ast.While):
                scan(stmt.test, conditional, in_for)
                walk(stmt.body, conditional, in_for)
                walk(stmt.orelse, conditional, in_for)
            elif isinstance(stmt, ast.If):
                scan(stmt.test, conditional, in_for)
                walk(stmt.body, True, in_for)
                walk(stmt.orelse, True, in_for)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    scan(item.context_expr, conditional, in_for)
                walk(stmt.body, conditional, in_for)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body, conditional, in_for)
                for h in stmt.handlers:
                    walk(h.body, True, in_for)
                walk(stmt.orelse, True, in_for)
                walk(stmt.finalbody, conditional, in_for)
            else:
                scan(stmt, conditional, in_for)

    walk(fdef.body, False, False)

    if any(in_for for _n, _c, in_for in decodes):
        findings.append(
            AuditFinding(
                "merge-loop", entry,
                "wire decode inside a for loop of the merge round — the "
                "per-shard drain bookkeeping must compare already-parsed "
                "keys, not re-decode the event once per shard per hit "
                "(PERF.md §31)",
            )
        )
    n_uncond = sum(
        1 for _n, conditional, _l in decodes if not conditional
    )
    if n_uncond != 1:
        findings.append(
            AuditFinding(
                "merge-loop", entry,
                f"{n_uncond} unconditional wire decode(s) per merge "
                "round (want exactly one — the hit's rank parses once, "
                "at ingress; every extra decode is per-hit work on the "
                "router's reader threads, PERF.md §31)",
            )
        )
    # The drain half may live anywhere in the class — including a base
    # (the fixture variants subclass the clean skeleton); scan the MRO.
    drained: Set[str] = set()
    for base in getattr(cls, "__mro__", (cls,)):
        if base is object:
            continue
        try:
            node = cdef if base is cls else _class_node(base)
        except (OSError, TypeError, SyntaxError):
            continue
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _DRAIN_ATTRS
            ):
                name = _self_attr_of(sub.func.value)
                if name is not None:
                    drained.add(name)
    for name in sorted(appended - drained):
        findings.append(
            AuditFinding(
                "merge-loop", entry,
                f"merge round appends to self.{name} but nothing in the "
                "class ever pops/clears it — an append-only buffer is "
                "unbounded hit hoarding: one stalled shard holds every "
                "sibling's hits for the rest of the job instead of "
                "bounding the buffer at the stripe lag (PERF.md §31)",
            )
        )
    return findings


#: Call names that move data between host and device — none of them
#: belong in the chunk ring's consume loop (the worker thread owns every
#: transfer; a synchronous one in the drive barriers the sweep behind
#: host work the ring exists to overlap).
_RING_TRANSFER_CALLS = frozenset(
    {
        "device_put",
        "device_get",
        "replicate",
        "shard_leading",
        "asarray",
        "array",
        "plan_arrays",
        "piece_arrays",
        "superstep_arrays",
        "table_arrays",
        "digest_arrays",
        "build_plan",
        "piece_schema_for",
    }
)


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def audit_chunk_ring(fn, entry: str) -> List[AuditFinding]:
    """Statically audit the streaming chunk ring's consume loop
    (PERF.md §19): the loop that pops compiled chunks off the worker
    ring and drives the device over each one.

    The contract the ring's bounded-memory and overlap claims rest on:

    * the loop iterates the compiler ring DIRECTLY (a bare name) —
      wrapping it in ``list(...)``/a comprehension materializes every
      chunk and resurrects the O(dictionary) memory streaming removes;
    * no host↔device transfer or plan/schema compile call in the loop
      body — the worker thread owns those, overlapped with the sweep; a
      synchronous one here re-serializes compile behind the device;
    * the consumed chunk is released exactly once, UNCONDITIONALLY, as
      a top-level statement of the loop body, before the ring advances
      — a skipped or conditional release leaks chunks past the ring
      bound;
    * the loop variable never escapes into a container
      (``.append``/``.add``) — chunk hoarding is the same leak spelled
      differently.
    """
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as exc:
        return [
            AuditFinding(
                "config", entry,
                f"chunk ring source unavailable for audit: {exc}",
            )
        ]
    findings: List[AuditFinding] = []
    fdef = next(
        (n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)), None
    )
    loop = next(
        (n for n in (fdef.body if fdef else []) if isinstance(n, ast.For)),
        None,
    )
    if loop is None:
        findings.append(
            AuditFinding(
                "config", entry,
                "chunk ring has no top-level for loop to audit",
            )
        )
        return findings
    if not isinstance(loop.iter, ast.Name):
        findings.append(
            AuditFinding(
                "chunk-ring", entry,
                "chunk loop does not iterate the compiler ring directly "
                "— materializing the ring (list(...), a comprehension) "
                "holds every chunk at once and voids the O(ring × "
                "chunk) memory bound (PERF.md §19)",
            )
        )
    loop_vars = _assigned_names(loop.target)
    for sub in ast.walk(loop):
        if not isinstance(sub, ast.Call):
            continue
        name = _call_name(sub)
        if name in _RING_TRANSFER_CALLS:
            findings.append(
                AuditFinding(
                    "chunk-ring", entry,
                    f"{name}() inside the chunk consume loop — "
                    "transfers and plan/schema compiles belong to the "
                    "ring's worker thread; a synchronous one here "
                    "serializes host work the ring exists to overlap "
                    "(PERF.md §19)",
                )
            )
        if name in ("append", "appendleft", "add") and any(
            _base_names(a) & loop_vars for a in sub.args
        ):
            findings.append(
                AuditFinding(
                    "chunk-ring", entry,
                    "consumed chunk escapes into a container — hoarded "
                    "chunks outlive the ring and void the bounded-"
                    "memory contract (PERF.md §19)",
                )
            )
    # Release discipline: exactly one unconditional top-level
    # ``<chunk>.release()`` per iteration.
    releases = 0
    for stmt in loop.body:
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "release"
            and _base_names(stmt.value.func.value) & loop_vars
        ):
            releases += 1
    nested_releases = sum(
        1
        for sub in ast.walk(loop)
        if isinstance(sub, ast.Call)
        and isinstance(sub.func, ast.Attribute)
        and sub.func.attr == "release"
        and _base_names(sub.func.value) & loop_vars
    )
    if releases != 1 or nested_releases != releases:
        findings.append(
            AuditFinding(
                "chunk-ring", entry,
                f"{releases} unconditional top-level chunk release(s) "
                f"per iteration ({nested_releases} total) — want exactly "
                "one, before the ring advances: a missing or conditional "
                "release leaks consumed chunks past the ring bound "
                "(PERF.md §19)",
            )
        )
    return findings


def audit_host_transfers_jaxpr(jaxpr, entry: str) -> List[AuditFinding]:
    found = find_transfers(jaxpr)
    findings: List[AuditFinding] = []
    for name, in_loop in found:
        where = (
            "inside a device loop body (fires per step!)"
            if in_loop
            else "in the compiled body"
        )
        findings.append(
            AuditFinding(
                "host-transfer", entry,
                f"{name} {where} — device->host round trip breaks the "
                "one-fetch-per-launch contract (PERF.md §15)",
            )
        )
    return findings

"""graftaudit — jaxpr/HLO-level semantic audits for the TPU hash engine.

Where graftlint (``tools/graftlint``) reads SOURCE, this tier reads what
XLA actually compiles: it traces and lowers every ``@audited_entry``
kernel and pipeline body (``hashcat_a5_table_generator_tpu.audit``) on
the CPU backend — trace/lower only, nothing executes — and checks the
semantic invariants AST analysis cannot see:

* pinned per-kernel op budgets (``KERNEL_BUDGETS.json``, ±2%),
* dead-stage detection (the PERF.md §15 membership-DCE trap),
* float purity of the integer hash pipeline,
* no device→host callbacks inside compiled sweep/superstep bodies,
* Pallas static bounds and grid write-overlap (race) checks.

Typed public API::

    from tools.graftaudit import (
        AuditFinding,
        audit_float_purity, audit_host_transfers,
        audit_pallas, audit_stage_text, stage_survival,
        count_kernel_ops,
    )

Run as ``python -m tools.graftaudit`` (see ``scripts/lint.sh`` and the
CI ``graftaudit`` job); ``--update-budgets`` is the deliberate
budget-update workflow (PERF.md §16).
"""

from __future__ import annotations

from .bounds import audit_pallas, audit_pallas_jaxpr
from .budgets import (
    DEFAULT_BUDGETS_PATH,
    compare_budgets,
    load_budgets,
    render_table,
    save_budgets,
)
from .counter import count_kernel_ops, count_traced_kernel, kernel_jaxpr_of
from .faults import audit_fault_hooks
from .findings import CHECKS, AuditFinding
from .purity import audit_float_purity, audit_float_purity_jaxpr
from .stages import (
    STAGE_MARKERS,
    audit_stage_text,
    audit_stages,
    compiled_text,
    stage_survival,
)
from .transfers import (
    TRANSFER_PRIMITIVES,
    audit_host_transfers,
    audit_host_transfers_jaxpr,
)

__all__ = [
    "AuditFinding",
    "CHECKS",
    "DEFAULT_BUDGETS_PATH",
    "STAGE_MARKERS",
    "TRANSFER_PRIMITIVES",
    "audit_fault_hooks",
    "audit_float_purity",
    "audit_float_purity_jaxpr",
    "audit_host_transfers",
    "audit_host_transfers_jaxpr",
    "audit_pallas",
    "audit_pallas_jaxpr",
    "audit_stage_text",
    "audit_stages",
    "compare_budgets",
    "compiled_text",
    "count_kernel_ops",
    "count_traced_kernel",
    "kernel_jaxpr_of",
    "load_budgets",
    "render_table",
    "save_budgets",
    "stage_survival",
]

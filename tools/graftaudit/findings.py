"""Typed findings — graftaudit's public result surface.

Mirrors ``tools/graftlint/findings.py``: checks produce findings and
never print, so one implementation drives the CLI, the pytest fixture
corpus, and the CI summary table.
"""

from __future__ import annotations

from dataclasses import dataclass

#: Stable check identifiers (the ``check`` field of every finding).
CHECKS = (
    "budget",        # KERNEL_BUDGETS.json ops/candidate drift
    "dead-stage",    # stage primitives DCE'd out of the optimized module
    "float-leak",    # float convert_element_type in the integer pipeline
    "host-transfer", # device->host callback inside a compiled body
    "drive-fetch",   # superstep drive loop breaks fetch discipline (§18)
    "fault-hook",    # fault-injection fire() missing the no-op guard (§23)
    "pallas-bounds", # pl.load/pl.store outside the BlockSpec block
    "pallas-race",   # two grid steps write the same output block
    "config",        # registry/harness/budgets-file disagreement
)


@dataclass(frozen=True)
class AuditFinding:
    """One semantic-audit violation.

    ``check`` is one of :data:`CHECKS`; ``entry`` is the registry entry
    name (or budget key) the violation was found in — the unit a reader
    greps for.
    """

    check: str
    entry: str
    message: str

    def render(self) -> str:
        """``CHECK entry: message`` — the CLI output line."""
        return f"{self.check} {self.entry}: {self.message}"

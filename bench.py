"""Benchmark: fused expand→MD5→membership throughput on one chip.

The headline config from ``BASELINE.json`` configs[2]: a rockyou-class
wordlist × qwerty-cyrillic, default mode, MD5 — candidates expanded, hashed
and membership-tested entirely on device. The reference publishes no numbers
(``BASELINE.md``); the target is the north star ≥1e10 candidate-hashes/sec
per chip, so ``vs_baseline`` is value / 1e10.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "hashes/sec", "vs_baseline": N}

Steady-state methodology: pre-cut real variant blocks for the sweep's head,
warm up (compile), then cycle the pre-cut batches for a fixed wall-clock
window, counting device-reported emitted candidates (each emitted candidate
is exactly one MD5). Host block-cutting is excluded from the timed loop —
in the sweep runtime it overlaps device execution (double-buffered feeds).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def synth_wordlist(n: int, seed: int = 0):
    """Deterministic rockyou-like wordlist: lowercase stems + digit tails."""
    import numpy as np

    rng = np.random.default_rng(seed)
    stems = rng.integers(ord("a"), ord("z") + 1, size=(n, 10), dtype=np.uint8)
    lens = rng.integers(6, 11, size=n)
    digits = rng.integers(0, 3, size=n)  # 0-2 trailing digits
    words = []
    for i in range(n):
        w = bytes(stems[i, : lens[i]])
        if digits[i]:
            w = w[: -digits[i]] + b"123"[: digits[i]]
        words.append(w)
    return words


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--lanes", type=int, default=1 << 19,
                    help="variant lanes per launch")
    ap.add_argument("--blocks", type=int, default=4096,
                    help="static block count per launch")
    ap.add_argument("--words", type=int, default=20000,
                    help="synthetic wordlist size")
    ap.add_argument("--seconds", type=float, default=10.0,
                    help="timed-window length")
    ap.add_argument("--batches", type=int, default=8,
                    help="distinct pre-cut batches to cycle")
    ap.add_argument("--algo", default="md5", help="hash algorithm")
    ap.add_argument("--mode", default="default", help="attack mode")
    ap.add_argument("--init-timeout", type=float, default=180.0,
                    help="seconds to wait for accelerator init before "
                         "aborting with an error record (exit 2)")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) before init")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    # The axon TPU tunnel can wedge (backend init blocks forever in
    # make_c_api_client). Probe device init on a daemon thread; if it does
    # not come up in time, abort with an error record — the hung init holds
    # backend locks, so an in-process CPU retry would deadlock.
    import threading

    init_ok = threading.Event()

    def _probe():
        try:
            jax.devices()
            init_ok.set()
        except Exception as e:  # pragma: no cover - backend-dependent
            print(f"# accelerator init failed: {e}", file=sys.stderr)

    probe = threading.Thread(target=_probe, daemon=True)
    probe.start()
    probe.join(args.init_timeout)
    metric = f"{args.algo}_candidate_hashes_per_sec_per_chip"
    if not init_ok.is_set():
        print(
            f"# accelerator init did not complete in {args.init_timeout}s; "
            "this process cannot recover the wedged backend — exiting",
            file=sys.stderr,
        )
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": "hashes/sec",
            "vs_baseline": 0.0,
            "error": "accelerator init timeout",
        }))
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(2)

    from hashcat_a5_table_generator_tpu.models.attack import (
        AttackSpec,
        block_arrays,
        build_plan,
        digest_arrays,
        make_crack_step,
        plan_arrays,
        table_arrays,
    )
    from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
    from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
    from hashcat_a5_table_generator_tpu.ops.packing import pack_words
    from hashcat_a5_table_generator_tpu.tables.compile import compile_table
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout

    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    spec = AttackSpec(mode=args.mode, algo=args.algo)
    sub_map = get_layout("qwerty-cyrillic").to_substitution_map()
    ct = compile_table(sub_map)
    words = synth_wordlist(args.words)
    packed = pack_words(words)
    plan = build_plan(spec, ct, packed)
    host_digest = HOST_DIGEST[spec.algo]
    targets = [host_digest(b"bench-decoy-%d" % i) for i in range(1024)]
    ds = build_digest_set(targets, spec.algo)

    step = make_crack_step(spec, num_lanes=args.lanes, out_width=plan.out_width)
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)

    # Pre-cut real blocks from the sweep's head (host cost excluded: the
    # sweep runtime overlaps cutting with device execution).
    batches = []
    w, rank = 0, 0
    for _ in range(args.batches):
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank,
            max_variants=args.lanes, max_blocks=args.blocks,
        )
        if batch.total == 0:
            break
        batches.append(block_arrays(batch, num_blocks=args.blocks))
    if not batches:
        raise SystemExit("wordlist produced no variant blocks")

    # Warmup: compile + one pass over every distinct batch, collecting each
    # batch's device-reported emitted count. Block descriptors enumerate the
    # full Π-radix rank space, but `emit` excludes min-window misses (e.g.
    # default mode's rank-0 no-substitution variant) and overlap-clash
    # lanes — only emitted lanes are hashed candidates, so only they count.
    t0 = time.perf_counter()
    per_batch = []
    for b in batches:
        out = step(p, t, b, d)
        per_batch.append(int(out["n_emitted"]))
    print(f"# warmup (incl. compile): {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    hashed = 0
    launches = 0
    start = time.perf_counter()
    deadline = start + args.seconds
    out = None
    while time.perf_counter() < deadline:
        b = batches[launches % len(batches)]
        out = step(p, t, b, d)
        hashed += per_batch[launches % len(batches)]
        launches += 1
    jax.block_until_ready(out)
    elapsed = time.perf_counter() - start

    value = hashed / elapsed
    baseline = 1e10  # north-star target, BASELINE.json / BASELINE.md
    print(f"# {launches} launches, {hashed:.3e} hashes, {elapsed:.2f}s",
          file=sys.stderr)
    print(json.dumps({
        "metric": metric,
        "value": value,
        "unit": "hashes/sec",
        "vs_baseline": value / baseline,
    }))


if __name__ == "__main__":
    main()

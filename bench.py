"""Benchmark: fused expand→MD5→membership throughput on one chip.

The headline config from ``BASELINE.json`` configs[2]: a rockyou-class
wordlist × qwerty-cyrillic, default mode, MD5 — candidates expanded, hashed
and membership-tested entirely on device. The reference publishes no numbers
(``BASELINE.md``); the target is the north star ≥1e10 candidate-hashes/sec
per chip, so ``vs_baseline`` is value / 1e10.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "hashes/sec", "vs_baseline": N}

Two-level structure (the accelerator backend in this environment — the axon
TPU tunnel — can wedge *forever* inside backend init, and a wedged init
thread cannot be killed in-process):

- **Orchestrator** (default entry): runs the measurement as a *subprocess*
  per platform attempt — default resolution (the axon tunnel) RETRIED with
  backoff for as long as ``--wall-budget`` allows (the tunnel wedge is a
  known transient; one try is not a diagnosis), the explicit ``tpu``
  plugin once, and a CPU fallback sized for host execution only when the
  accelerator budget is exhausted — each under a hard kill-timeout.
  Emits exactly one JSON line: the first successful attempt's record,
  augmented with the platform used and the stderr tails of ALL failed
  attempts (so a wedge is diagnosable, not a bare timeout).  Exits 2 if
  every attempt failed (the error record is still printed).
- **Worker** (``--worker``): the actual timed loop.  Probes device init on a
  daemon thread with its own timeout and aborts with rc=2 if init never
  completes (``os._exit`` — the wedged thread holds backend locks).  On a
  kernel-eligible config it times BOTH expand+hash arms — the XLA pair and
  the fused Pallas kernel — and records the winner (``"arm"``), with both
  sub-records under ``"arms"``.

Steady-state methodology: pre-cut real variant blocks for the sweep's head,
warm up (compile), then cycle the pre-cut batches for a fixed wall-clock
window, counting device-reported emitted candidates (each emitted candidate
is exactly one MD5). Host block-cutting is excluded from the timed loop —
in the sweep runtime it overlaps device execution (double-buffered feeds).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

NORTH_STAR = 1e10  # hashes/sec/chip target, BASELINE.json / BASELINE.md

#: Committed last-good on-chip record (bench resilience, VERDICT r5 #2):
#: every successful accelerator measurement overwrites it, and any run
#: that ends on the CPU fallback (or fails outright) embeds it as a
#: labeled "last_tpu" field — so the driver artifact carries TPU evidence
#: across tunnel outages instead of only a cpu number.
TPU_LAST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_LAST.json"
)


def save_tpu_last(record: dict) -> None:
    """Persist a successful accelerator record (best effort — the bench
    number must never be lost to a read-only checkout)."""
    entry = {
        k: record[k]
        for k in ("metric", "value", "unit", "lanes", "blocks", "arm",
                  "kernel", "platform", "device_kind", "mode", "table",
                  "partial_matrix")
        if k in record
    }
    entry["timestamp"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
    )
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(TPU_LAST_PATH), capture_output=True,
            text=True, timeout=10,
        ).stdout.strip()
        if sha:
            entry["git_sha"] = sha
    except Exception:
        pass
    try:
        with open(TPU_LAST_PATH, "w") as fh:
            json.dump(entry, fh, indent=2)
            fh.write("\n")
    except OSError as e:
        print(f"# could not write {TPU_LAST_PATH}: {e}", file=sys.stderr)


def load_tpu_last() -> "dict | None":
    try:
        with open(TPU_LAST_PATH) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def attach_tpu_evidence(record: dict) -> dict:
    """Accelerator attempts failed: label the record with the committed
    last-good on-chip measurement so the artifact still carries TPU
    evidence (clearly marked as historical, not this run's)."""
    last = load_tpu_last()
    if last is not None:
        record["last_tpu"] = last
    return record


#: Geometry provenance of this process's launch geometry (PERF.md §29):
#: "explicit" (user flags), "default" (bench built-ins), "profile"
#: (autotune profile filled the gaps), or "autotune" (the tune matrix
#: itself).  Set once by main() from the parsed flags; the orchestrator
#: forwards it to workers via --geometry-source.
GEOMETRY_SOURCE = "explicit"


def stamp_geometry(record: dict, source: "str | None" = None) -> dict:
    """Stamp geometry provenance into an emitted record: every bench
    record carries ``geometry_source`` and the resolved geometry tuple,
    so no recorded number is ever ambiguous about the geometry that
    produced it (PERF.md §29).  Idempotent — existing stamps win."""
    record.setdefault("geometry_source", source or GEOMETRY_SOURCE)
    if "geometry" not in record:
        geom = {
            k: record[k]
            for k in ("lanes", "blocks")
            if record.get(k) is not None
        }
        if geom:
            record["geometry"] = geom
    return record


def compare_last_tpu(value: "float | None" = None) -> None:
    """--compare-last-tpu: human verdict lines (stderr) against the
    committed last-good on-chip record and the 1e10/chip north star,
    instead of manual JSON diffing."""
    last = load_tpu_last()
    if last is not None and last.get("partial_matrix"):
        # A partial autotune matrix is a checkpoint, not a measurement
        # of the best geometry — comparing against it inflates every
        # later run's verdict.  Skip it and say so.
        print(
            "# compare: last TPU record is a PARTIAL autotune matrix "
            f"({last.get('timestamp', '?')}) — skipped as baseline; "
            "rerun --autotune to completion for a comparable record",
            file=sys.stderr,
        )
        last = None
    if last is None:
        print("# compare: no usable BENCH_TPU_LAST.json on disk",
              file=sys.stderr)
    else:
        lv = float(last.get("value", 0.0))
        print(
            f"# compare: last TPU record {lv:.3e} hashes/s on "
            f"{last.get('device_kind', '?')} "
            f"({last.get('timestamp', '?')}) = {lv / NORTH_STAR:.2%} of "
            "the 1e10/chip target",
            file=sys.stderr,
        )
    if value is None:
        return
    print(
        f"# compare: this run {value:.3e} hashes/s = "
        f"{value / NORTH_STAR:.2%} of the 1e10/chip target",
        file=sys.stderr,
    )
    if last is not None and float(last.get("value", 0.0)) > 0:
        ratio = value / float(last["value"])
        verdict = (
            "AHEAD of" if ratio > 1.0 else
            "LEVEL with" if ratio == 1.0 else "BEHIND"
        )
        print(
            f"# compare: verdict — {verdict} the last TPU record "
            f"({ratio:.2f}x)",
            file=sys.stderr,
        )


def metric_name(algo: str) -> str:
    return f"{algo}_candidate_hashes_per_sec_per_chip"


def error_record(algo: str, error: str, **extra) -> dict:
    rec = {
        "metric": metric_name(algo),
        "value": 0.0,
        "unit": "hashes/sec",
        "vs_baseline": 0.0,
        "error": error,
    }
    rec.update(extra)
    return rec


def synth_wordlist(n: int, seed: int = 0):
    """Deterministic rockyou-like wordlist: lowercase stems + digit tails."""
    import numpy as np

    rng = np.random.default_rng(seed)
    stems = rng.integers(ord("a"), ord("z") + 1, size=(n, 10), dtype=np.uint8)
    lens = rng.integers(6, 11, size=n)
    digits = rng.integers(0, 3, size=n)  # 0-2 trailing digits
    words = []
    for i in range(n):
        w = bytes(stems[i, : lens[i]])
        if digits[i]:
            w = w[: -digits[i]] + b"123"[: digits[i]]
        words.append(w)
    return words


def _build_bench_parser() -> argparse.ArgumentParser:
    # Not named `build_parser`: graftknob's cli knob layer anchors on
    # the engine builder names, and the bench harness's A/B-matrix
    # flags configure experiments, not the engine.
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--lanes", type=int, default=None,
                    help="variant lanes per launch (default 2^22; "
                         "--superstep-ab defaults to the §4c CPU peak, "
                         "2048)")
    ap.add_argument("--blocks", type=int, default=None,
                    help="static block count per launch (default: each arm's "
                         "measured best geometry — xla lanes/128; pallas "
                         "lanes/128 on the K=1 scalar path, else lanes/512 "
                         "or lanes/256 for suball — PERF.md §9b/§11)")
    ap.add_argument("--words", type=int, default=None,
                    help="synthetic wordlist size (default 50000; "
                         "--serve-ab defaults to 1000 — its contract is "
                         "N equal SMALL jobs, the compile-dominant "
                         "regime the service mode amortizes)")
    ap.add_argument("--seconds", type=float, default=None,
                    help="timed-window length (default 10; --autotune "
                         "defaults to 2 — it is PER ARM there)")
    ap.add_argument("--batches", type=int, default=8,
                    help="distinct pre-cut batches to cycle")
    ap.add_argument("--algo", default="md5", help="hash algorithm")
    ap.add_argument("--block-layout", choices=("auto", "packed", "stride"),
                    default="auto",
                    help="variant-block layout (same semantics as the CLI; "
                         "auto = stride whenever blocks divides lanes evenly)")
    ap.add_argument("--mode", default="default", help="attack mode")
    ap.add_argument("--table", default="qwerty-cyrillic",
                    help="built-in layout table (BASELINE.json configs "
                         "3-4 use czech / greek-hebrew)")
    ap.add_argument("--arm", choices=("auto", "xla", "pallas"),
                    default="auto",
                    help="which expand+hash arm to time: the XLA pair, the "
                         "fused Pallas kernel, or (auto) both when the "
                         "config is kernel-eligible — recording the winner")
    ap.add_argument("--wall-budget", type=float, default=540.0,
                    help="orchestrator total wall-clock budget (seconds); "
                         "accelerator attempts retry with backoff until "
                         "only the CPU-fallback reserve remains")
    ap.add_argument("--init-timeout", type=float, default=150.0,
                    help="seconds the worker waits for accelerator init")
    ap.add_argument("--init-retry-budget", type=float, default=240.0,
                    help="cap on CUMULATIVE wall spent on accelerator "
                         "attempts that die before device init; once "
                         "exceeded the orchestrator stops retrying the "
                         "wedged backend and takes the CPU fallback "
                         "(BENCH_r05 burned ~6 min of init timeouts)")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) before init")
    ap.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler trace of the timed window here")
    ap.add_argument("--worker", action="store_true",
                    help="run the measurement in this process (internal)")
    ap.add_argument("--superstep-ab", action="store_true",
                    help="measure the superstep executor against the "
                         "per-launch pipeline instead of the kernel arms: "
                         "records hashes/s, launches-per-fetch, and per-"
                         "step HOST overhead (block cut + dispatch) for "
                         "both loops as one JSON line (PERF.md §15). "
                         "Defaults to the measured CPU peak geometry "
                         "(2048 lanes x 32 blocks, §4c) unless --lanes/"
                         "--blocks override")
    ap.add_argument("--pipeline-ab", action="store_true",
                    help="measure the double-buffered superstep pipeline "
                         "against the barriered superstep drive on the "
                         "production crack contract (PERF.md §18): "
                         "per-step host overhead (fetch-to-dispatch gap), "
                         "dead device time (the non-overlapped share), "
                         "overlap ratio (device busy during the gap), and "
                         "wall — one JSON line. Defaults to the §4c CPU "
                         "peak geometry like --superstep-ab")
    ap.add_argument("--stream-ab", action="store_true",
                    help="measure streaming chunked plan compilation "
                         "against whole-dictionary materialization on "
                         "the production crack contract (PERF.md §19): "
                         "time-to-first-candidate, chunk-compile "
                         "overlap ratio, peak resident plan bytes, and "
                         "wall for both arms — one JSON line. The "
                         "streaming arm chunks --words into >= 4 chunks; "
                         "defaults to the §4c CPU peak geometry like "
                         "--superstep-ab")
    ap.add_argument("--stream-chunks", type=int, default=4,
                    help="--stream-ab: chunk count the streaming arm "
                         "splits --words into (default 4 — the minimum "
                         "the §19 overlap criterion is stated at)")
    ap.add_argument("--serve-ab", action="store_true",
                    help="measure the resident engine service mode "
                         "(PERF.md §20) against N cold CLI-equivalent "
                         "runs of the same N jobs on the production "
                         "crack contract: aggregate jobs/s and wall, "
                         "per-job time-to-first-candidate cold vs warm, "
                         "and compiled-program counts per arm — one "
                         "JSON line. Defaults to the §4c CPU peak "
                         "geometry like --superstep-ab")
    ap.add_argument("--serve-jobs", type=int, default=4,
                    help="--serve-ab: equal small jobs per arm (default "
                         "4 — the N the §20 amortization criterion is "
                         "stated at)")
    ap.add_argument("--fleet-ab", action="store_true",
                    help="measure routed vs direct serve (PERF.md "
                         "§25): the same N equal small jobs driven "
                         "through one engine process directly over "
                         "its unix socket, then through the same "
                         "engine behind a FleetRouter — steady-state "
                         "(each arm pre-warms with one untimed job), "
                         "parity-asserted per-job emitted/hit counts, "
                         "aggregate wall ratio (the router "
                         "passthrough-overhead instrument; bar: "
                         "within 5%%) — one JSON line. Spawns engine "
                         "subprocesses; defaults to the §20 contract "
                         "geometry like --serve-ab")
    ap.add_argument("--fleet-place", choices=("affinity", "round-robin"),
                    default="affinity",
                    help="--fleet-ab: router placement arm (the "
                         "round-robin control measures the same "
                         "passthrough without affinity lookups)")
    ap.add_argument("--elastic-ab", action="store_true",
                    help="measure routed-with-autoscale vs direct "
                         "serve (PERF.md §27): the --fleet-ab "
                         "contract with the elastic tier ARMED on the "
                         "routed arm — admission control on, the "
                         "autoscaler's control loop ticking "
                         "(thresholds set so the steady state never "
                         "scales) — pinning that elasticity costs "
                         "nothing when nothing needs scaling (bar: "
                         "within 5%% aggregate wall, the same §25 "
                         "criterion). One JSON line; spawns engine "
                         "subprocesses")
    ap.add_argument("--pack-ab", action="store_true",
                    help="measure cross-job packed dispatch (PERF.md "
                         "§22) against the per-job round-robin: N "
                         "compatible small jobs per arm through a warm "
                         "resident Engine, parity-asserted per-job "
                         "emitted counts vs solo runs, fill ratio, "
                         "aggregate wall ratio, concurrent-admission "
                         "warm ttfc, and per-job span fairness — one "
                         "JSON line. Defaults to the §4c CPU peak "
                         "geometry like --serve-ab")
    ap.add_argument("--pack-jobs", type=int, default=4,
                    help="--pack-ab: compatible small jobs per arm "
                         "(default 4 — the underfilled-N the §22 "
                         "acceptance criterion is stated at; must "
                         "divide --blocks)")
    ap.add_argument("--pack-churn", action="store_true",
                    help="measure dynamic re-fuse under tenant churn "
                         "(PERF.md §28): waves of N compatible jobs "
                         "submitted to a packed resident Engine with a "
                         "mid-flight cancel of half each wave, re-fuse "
                         "ENABLED vs DISABLED arms — per-arm serve "
                         "wall, post-departure fill decay (min) and "
                         "post-re-fuse recovered fill, refuse count, "
                         "survivor parity vs solo runs — one JSON "
                         "line. Geometry rules follow --pack-ab")
    ap.add_argument("--churn-waves", type=int, default=2,
                    help="--pack-churn: submit/cancel waves per arm "
                         "(default 2)")
    ap.add_argument("--refuse-below", type=float, default=0.8,
                    help="--pack-churn: fill threshold for the "
                         "re-fuse arm (default 0.8 — half the tenants "
                         "cancelling always crosses it)")
    ap.add_argument("--split-ab", action="store_true",
                    help="measure giant-job striping (PERF.md §31): ONE "
                         "oversized crack job scattered across "
                         "--split-engines spawned engines as disjoint "
                         "rank-stride shard ranges (merged back into "
                         "one ordered client stream) vs the identical "
                         "job on one engine — merged-stream parity "
                         "asserted tuple-for-tuple in-bench, per-arm "
                         "wall, speedup, and the router merge "
                         "overhead share — one JSON line. Spawns "
                         "engine subprocesses; no jax in this process")
    ap.add_argument("--split-engines", type=int, default=2,
                    help="--split-ab: engines the split arm scatters "
                         "over (default 2 — the N the §31 acceptance "
                         "criterion is stated at)")
    ap.add_argument("--churn-cross", action="store_true",
                    help="measure cross-group vs within-group re-fuse "
                         "(PERF.md §31): two fused groups on one "
                         "packed Engine each lose one of two members "
                         "mid-flight; the cross scope merges the lone "
                         "survivors into one full group, the within "
                         "scope leaves them solo at the post-"
                         "departure fill floor — per-arm fill "
                         "recovery + refuse_cross counters, survivor "
                         "parity vs solo runs — one JSON line. "
                         "Geometry rules follow --pack-churn")
    ap.add_argument("--pair-ab", action="store_true",
                    help="measure the pair-lane tier (K=2 candidates "
                         "per hash lane, PERF.md §24) against K=1 on "
                         "the production superstep crack contract: "
                         "identical plan/schema/geometry per arm, "
                         "parity-asserted per-sweep emitted counts, "
                         "per-arm hashes/s + the budget counter's "
                         "ops/candidate + the fixture's eligibility "
                         "share — one JSON line")
    ap.add_argument("--telemetry-ab", action="store_true",
                    help="measure the telemetry layer's wall overhead "
                         "(PERF.md §21) on the production crack "
                         "contract: instrumented (registry + span "
                         "timeline) vs A5GEN_TELEMETRY=off arms "
                         "alternating run-for-run, overhead ratio vs "
                         "the ≤1%% bar — one JSON line. Defaults to "
                         "the §4c CPU peak geometry like "
                         "--superstep-ab")
    ap.add_argument("--stride-ab", action="store_true",
                    help="measure block stride 128 vs 256 x emission "
                         "scheme perslot vs bytescan (A5GEN_EMIT arms) "
                         "on the production crack-step contract: per-arm "
                         "hashes/s AND jaxpr-counted kernel ops/candidate "
                         "(tools/graftaudit/counter — the same counter "
                         "that pins KERNEL_BUDGETS.json), winner in one "
                         "JSON line (PERF.md §7a lever 2 / §17)")
    ap.add_argument("--autotune", action="store_true",
                    help="run the geometry autotune matrix "
                         "(runtime/tune.py) as the bench mode: one JSON "
                         "record per completed arm, per-arm stream "
                         "parity asserted, the winner persisted as this "
                         "device kind's profile (PERF.md §29). Under "
                         "the orchestrator the matrix is retry-aware "
                         "inside --init-retry-budget: a killed or "
                         "flaked attempt resumes from the last "
                         "completed arm via --tune-state. The smoke "
                         "matrix runs on cpu, the full matrix on "
                         "accelerators; --seconds is the per-arm "
                         "window (default 2 in this mode)")
    ap.add_argument("--tune-state", default=None,
                    help="--autotune: partial-matrix resume file "
                         "(JSON, rewritten atomically after each "
                         "completed arm). The orchestrator defaults it "
                         "to a per-run temp path so retries skip "
                         "finished arms; pass a stable path to resume "
                         "across bench invocations (delete the file to "
                         "re-measure from scratch)")
    ap.add_argument("--tune-profile-dir", default=None,
                    help="--autotune: write the winning profile here "
                         "instead of the A5GEN_TUNE_PROFILE default "
                         "directory")
    ap.add_argument("--compare-last-tpu", action="store_true",
                    help="print a verdict (stderr) against the "
                         "committed BENCH_TPU_LAST.json record and the "
                         "1e10/chip target. Standalone (no other mode "
                         "flags) it just reports the stored record; "
                         "combined with a measuring run, the verdict "
                         "also compares this run's emitted value")
    ap.add_argument("--geometry-source", default=None,
                    choices=("explicit", "default", "profile"),
                    help=argparse.SUPPRESS)  # orchestrator->worker seam
    return ap


# ------------------------------------------------------- superstep A/B --


def _ab_crack_plan(args: argparse.Namespace):
    """The crack contract every A/B arm benches: spec, compiled table,
    plan over the synthetic wordlist, and a decoy digest set that keeps
    the membership stage live without ever hitting."""
    from hashcat_a5_table_generator_tpu.models.attack import (
        AttackSpec,
        build_plan,
    )
    from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
    from hashcat_a5_table_generator_tpu.ops.packing import pack_words
    from hashcat_a5_table_generator_tpu.tables.compile import compile_table
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    spec = AttackSpec(mode=args.mode, algo=args.algo)
    ct = compile_table(get_layout(args.table).to_substitution_map())
    plan = build_plan(spec, ct, pack_words(synth_wordlist(args.words)))
    host_digest = HOST_DIGEST[spec.algo]
    ds = build_digest_set(
        [host_digest(b"bench-decoy-%d" % i) for i in range(1024)], spec.algo
    )
    return spec, ct, plan, ds


def _ab_superstep_fixture(args: argparse.Namespace, flag: str) -> dict:
    """Shared --superstep-ab / --pipeline-ab setup: the §4c CPU-peak
    geometry (2048 lanes × 32 blocks × 16 steps unless --lanes/--blocks
    override), the crack plan, device arrays, and ONE compiled superstep
    program — so the arms can never drift onto different contracts."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from hashcat_a5_table_generator_tpu.models.attack import (
        digest_arrays,
        make_superstep_step,
        plan_arrays,
        superstep_arrays,
        table_arrays,
    )
    from hashcat_a5_table_generator_tpu.ops.blocks import superstep_index
    from hashcat_a5_table_generator_tpu.ops.pallas_expand import k_opts_for

    dev = jax.devices()[0]
    # Default: the §4c CPU-peak geometry, where the per-launch pipeline is
    # dispatch-bound — exactly the regime the superstep targets (an
    # explicit --lanes/--blocks is honored; main() resolves the None).
    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    steps = 16
    if lanes % nb:
        raise SystemExit(f"{flag} needs blocks dividing lanes")
    stride = lanes // nb
    hit_cap = 256

    spec, ct, plan, ds = _ab_crack_plan(args)
    idx = superstep_index(plan, stride)
    if idx is None:
        raise SystemExit(f"{flag}: plan is not superstep-eligible")
    _cum, _totals, total_blocks = idx
    radix2 = k_opts_for(plan) == 1
    windowed = bool(getattr(plan, "windowed", False))
    sstep = make_superstep_step(
        spec, num_lanes=lanes, num_blocks=nb, out_width=plan.out_width,
        block_stride=stride, steps=steps, hit_cap=hit_cap,
        total_blocks=total_blocks, windowed=windowed, radix2=radix2,
    )
    return {
        "dev": dev, "lanes": lanes, "nb": nb, "steps": steps,
        "stride": stride, "hit_cap": hit_cap, "spec": spec, "plan": plan,
        "total_blocks": total_blocks, "radix2": radix2,
        "n_super": max(1, total_blocks // (steps * nb)),
        "p": plan_arrays(plan), "t": table_arrays(ct),
        "d": digest_arrays(ds), "ss": superstep_arrays(plan, stride),
        "sstep": sstep,
    }


def run_superstep_ab(args: argparse.Namespace) -> None:
    """A/B the device-resident superstep executor against the per-launch
    pipeline (PERF.md §15): both arms hash the SAME block stream through
    the same fused body; the per-launch arm pays a host block cut + a
    dispatch per step, the superstep arm one dispatch per ``fetch_chunk``
    steps and zero host cutting.  Prints ONE JSON line with per-arm
    hashes/s and host-overhead seconds per step."""
    fx = _ab_superstep_fixture(args, "--superstep-ab")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hashcat_a5_table_generator_tpu.models.attack import (
        block_arrays,
        make_fused_body,
        superstep_buffers,
    )
    from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks

    dev, plan = fx["dev"], fx["plan"]
    lanes, nb, steps, stride = (
        fx["lanes"], fx["nb"], fx["steps"], fx["stride"]
    )
    hit_cap, n_super = fx["hit_cap"], fx["n_super"]
    p, t, d, ss, sstep = fx["p"], fx["t"], fx["d"], fx["ss"], fx["sstep"]

    # The per-launch arm runs the PRODUCTION crack-step contract —
    # hit_bits + both counts, with the counts chained into a device
    # accumulator exactly like Sweep.run_crack's chunked loop.  An
    # emitted-count-only accumulator (the kernel bench's shape) lets XLA
    # dead-code-eliminate the membership stage, which the superstep arm
    # necessarily keeps alive — the arms must pay the same device work.
    body = make_fused_body(fx["spec"], num_lanes=lanes,
                           out_width=plan.out_width, block_stride=stride,
                           radix2=fx["radix2"])
    step = jax.jit(lambda p_, t_, b_, d_: body(p_, t_, d_, b_))
    accum = jax.jit(lambda acc, ne, nh: acc + jnp.stack([ne, nh]))
    acc_zero = jnp.zeros((2,), jnp.int32)

    def per_launch_arm() -> dict:
        """`steps`-launch rounds with the production per-launch recipe:
        host cut + dispatch per step, one counter fetch per round."""
        hashed, launches, cut_s, disp_s = 0, 0, 0.0, 0.0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < args.seconds:
            w, rank = 0, 0
            acc = acc_zero
            for _ in range(steps):
                tc = time.perf_counter()
                batch, w, rank = make_blocks(
                    plan, start_word=w, start_rank=rank,
                    max_variants=lanes, max_blocks=nb, fixed_stride=stride,
                )
                blocks = block_arrays(batch, num_blocks=nb)
                td = time.perf_counter()
                out = step(p, t, blocks, d)
                acc = accum(acc, out["n_emitted"], out["n_hits"])
                te = time.perf_counter()
                cut_s += td - tc
                disp_s += te - td
                launches += 1
            hashed += int(acc[0])  # completion barrier per round
        wall = time.perf_counter() - t0
        return {
            "hashes_per_sec": hashed / wall,
            "launches": launches,
            "launches_per_fetch": steps,
            "cut_s_per_step": cut_s / max(launches, 1),
            "dispatch_s_per_step": disp_s / max(launches, 1),
            "host_s_per_step": (cut_s + disp_s) / max(launches, 1),
        }

    def superstep_arm() -> dict:
        hashed, launches, disp_s = 0, 0, 0.0
        bufs = superstep_buffers(hit_cap)
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < args.seconds:
            b0 = (launches // steps) % n_super * (steps * nb)
            td = time.perf_counter()
            out = sstep(p, t, d, ss, np.int32(b0), bufs)
            disp_s += time.perf_counter() - td
            hashed += int(out["n_emitted"])  # completion barrier
            bufs = {"hit_word": out["hit_word"],
                    "hit_rank": out["hit_rank"]}
            launches += steps
        wall = time.perf_counter() - t0
        return {
            "hashes_per_sec": hashed / wall,
            "launches": launches,
            "launches_per_fetch": steps,
            "cut_s_per_step": 0.0,
            "dispatch_s_per_step": disp_s / max(launches, 1),
            "host_s_per_step": disp_s / max(launches, 1),
        }

    # Warm both compiled programs before timing.
    batch0, _, _ = make_blocks(plan, start_word=0, start_rank=0,
                               max_variants=lanes, max_blocks=nb,
                               fixed_stride=stride)
    int(step(p, t, block_arrays(batch0, num_blocks=nb), d)["n_emitted"])
    int(accum(acc_zero, jnp.int32(0), jnp.int32(0))[0])
    int(sstep(p, t, d, ss, np.int32(0),
              superstep_buffers(hit_cap))["n_emitted"])

    per_launch = per_launch_arm()
    superstep = superstep_arm()
    record = {
        "metric": "superstep_host_overhead_ab",
        "unit": "seconds/step (host) + hashes/sec",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": lanes,
        "blocks": nb,
        "per_launch": per_launch,
        "superstep": superstep,
        "host_overhead_ratio": (
            per_launch["host_s_per_step"]
            / max(superstep["host_s_per_step"], 1e-12)
        ),
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


# -------------------------------------------------------- pipeline A/B --


def run_pipeline_ab(args: argparse.Namespace) -> None:
    """A/B the double-buffered superstep pipeline against the barriered
    superstep drive (PERF.md §18).  Both arms run the SAME compiled
    superstep program over the same block stream with the production
    per-superstep host recipe (one counters fetch + buffer recycling);
    they differ ONLY in drive depth — the barriered arm fetches each
    superstep right after dispatching it (the device idles through the
    host's fetch-to-dispatch gap), the pipelined arm dispatches superstep
    N+1 into the second buffer set before fetching N's counters, so the
    gap overlaps the in-flight superstep's compute.  Per-step host
    overhead is that gap; the DEAD share is the portion with no superstep
    in flight (device idle) — the number the pipeline exists to remove.
    Overlap is a HOST-SIDE proxy: a gap counts as overlapped when a
    superstep was in flight (dispatched, not yet fetched) while it ran —
    the host cannot see whether the device finished early, so where the
    gap exceeds a superstep's compute (the ~65 ms tunnel) overlap_ratio
    is an upper bound and dead_s_per_step a lower bound; at the CPU §4c
    geometry (compute >> gap) the proxy is tight.  Prints ONE JSON
    line."""
    from collections import deque

    fx = _ab_superstep_fixture(args, "--pipeline-ab")

    import numpy as np

    from hashcat_a5_table_generator_tpu.models.attack import (
        superstep_buffers,
    )

    dev = fx["dev"]
    lanes, nb, steps = fx["lanes"], fx["nb"], fx["steps"]
    hit_cap, n_super = fx["hit_cap"], fx["n_super"]
    p, t, d, ss, sstep = fx["p"], fx["t"], fx["d"], fx["ss"], fx["sstep"]

    def drive_arm(depth: int) -> dict:
        """One timed window at in-flight depth 1 (barriered) or 2
        (pipelined): the production drive recipe minus hit processing
        (the decoy digests never hit)."""
        free = [superstep_buffers(hit_cap) for _ in range(depth)]
        inflight: deque = deque()
        hashed = supersteps = 0
        gap_s = dead_s = 0.0
        t0 = time.perf_counter()
        mark = t0  # last fetch-return (or start): the gap opens here
        while time.perf_counter() - t0 < args.seconds or inflight:
            had_inflight = bool(inflight)
            dispatched = False
            while (
                len(inflight) < depth and free
                and time.perf_counter() - t0 < args.seconds
            ):
                b0 = supersteps + len(inflight)
                b0 = b0 % n_super * (steps * nb)
                inflight.append(sstep(p, t, d, ss, np.int32(b0),
                                      free.pop()))
                dispatched = True
            if not inflight:
                break
            now = time.perf_counter()
            # The fetch-to-dispatch gap just closed: host-side work the
            # barriered arm pays as dead device time.  Overlapped iff a
            # superstep was already in flight while the gap ran (depth 2
            # steady state); the fill gap before the first dispatch is
            # honestly dead in both arms.
            if supersteps or dispatched:
                gap = now - mark
                gap_s += gap
                if not had_inflight:
                    dead_s += gap
            out = inflight.popleft()
            ne, _nh = (int(x) for x in np.asarray(out["counters"]))
            hashed += ne
            free.append({"hit_word": out["hit_word"],
                         "hit_rank": out["hit_rank"]})
            supersteps += 1
            mark = time.perf_counter()
        wall = time.perf_counter() - t0
        launches = supersteps * steps
        return {
            "hashes_per_sec": hashed / wall,
            "wall_s": wall,
            "supersteps": supersteps,
            "launches": launches,
            "launches_per_fetch": steps,
            "host_s_per_step": gap_s / max(launches, 1),
            "dead_s_per_step": dead_s / max(launches, 1),
            "overlap_ratio": (
                (gap_s - dead_s) / gap_s if gap_s > 0 else 0.0
            ),
        }

    # Warm the one compiled program (both arms share it), then measure.
    warm = sstep(p, t, d, ss, np.int32(0), superstep_buffers(hit_cap))
    int(np.asarray(warm["counters"])[0])
    barriered = drive_arm(1)
    pipelined = drive_arm(2)
    record = {
        "metric": "pipeline_host_overhead_ab",
        "unit": "seconds/step (host gap, dead share) + hashes/sec",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": lanes,
        "blocks": nb,
        "steps_per_superstep": steps,
        "barriered": barriered,
        "pipelined": pipelined,
        # The acceptance ratio: dead device time per step, barriered over
        # pipelined — the pipeline's whole job is sending this to ~0.
        "host_overhead_ratio": (
            barriered["dead_s_per_step"]
            / max(pipelined["dead_s_per_step"], 1e-12)
        ),
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


# --------------------------------------------------------- streaming A/B --


class _TtfcProbe:
    """Minimal progress reporter capturing the wall-clock of the FIRST
    drive update — the sweep runtime reports progress at every drain
    (counters fetch), so the first update IS time-to-first-candidate
    under the same definition for both arms (streaming reports the same
    instant in ``SweepResult.stream['ttfc_s']``; the whole arm has no
    stream stats, hence this probe)."""

    def __init__(self) -> None:
        self.first: "float | None" = None

    def seed_emitted(self, n: int) -> None:
        pass

    def update(self, **kw) -> None:
        if self.first is None:
            self.first = time.perf_counter()

    def final(self, **kw) -> None:
        pass


def run_stream_ab(args: argparse.Namespace) -> None:
    """A/B streaming chunked ingestion against whole-dictionary plan
    materialization (PERF.md §19) on the production crack contract: the
    same wordlist × table × decoy digests swept end-to-end through
    ``Sweep.run_crack`` twice — whole (one plan + schema compile before
    any launch) vs streaming (``--stream-chunks`` chunks, worker-thread
    compile overlapped with the device sweep).  Reports per-arm wall,
    hashes/s, and time-to-first-candidate, plus the streaming arm's
    compile-overlap ratio and peak resident plan bytes, and asserts the
    two arms emitted identical candidate counts (byte parity proper is
    the test suite's job; the bench must still refuse to time diverging
    arms).  Prints ONE JSON line."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
    from hashcat_a5_table_generator_tpu.runtime.sweep import (
        Sweep,
        SweepConfig,
    )
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    dev = jax.devices()[0]
    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    if lanes % nb:
        raise SystemExit("--stream-ab needs blocks dividing lanes")
    spec = AttackSpec(mode=args.mode, algo=args.algo)
    sub_map = get_layout(args.table).to_substitution_map()
    words = synth_wordlist(args.words)
    host_digest = HOST_DIGEST[spec.algo]
    digests = [
        host_digest(b"bench-decoy-%d" % i) for i in range(1024)
    ]
    n_chunks = max(2, int(args.stream_chunks))
    chunk_words = max(1, -(-args.words // n_chunks))

    def arm(stream: bool) -> dict:
        probe = _TtfcProbe()
        cfg = SweepConfig(
            lanes=lanes, num_blocks=nb,
            stream_chunk_words=(chunk_words if stream else "off"),
            progress=probe,
        )
        t0 = time.perf_counter()
        sweep = Sweep(spec, sub_map, words, digests, config=cfg)
        res = sweep.run_crack(resume=False)
        wall = time.perf_counter() - t0
        rec = {
            "wall_s": wall,
            "hashes_per_sec": res.n_emitted / max(res.wall_s, 1e-9),
            "n_emitted": res.n_emitted,
            # From Sweep construction: the whole arm's plan + schema
            # compile and the streaming arm's prescan + first chunk
            # both count — the user-visible time to first results.
            "ttfc_s": (
                probe.first - t0 if probe.first is not None else wall
            ),
            "supersteps": res.superstep.get("supersteps", 0),
        }
        if stream:
            rec["stream"] = dict(res.stream)
        return rec

    whole = arm(stream=False)
    streaming = arm(stream=True)
    if streaming["n_emitted"] != whole["n_emitted"]:
        raise SystemExit(
            f"--stream-ab arms diverged: streaming emitted "
            f"{streaming['n_emitted']}, whole {whole['n_emitted']} — "
            "refusing to report timings for non-identical work"
        )
    st = streaming["stream"]
    record = {
        "metric": "stream_ingestion_ab",
        "unit": "seconds (ttfc, compile overlap) + hashes/sec",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": lanes,
        "blocks": nb,
        "words": args.words,
        "chunk_words": chunk_words,
        "chunks": st.get("chunks", 0),
        "whole": whole,
        "streaming": streaming,
        # The §19 acceptance instruments: ttfc against the whole arm
        # and against one chunk's compile (the <= 1.5x bar), and the
        # share of chunk-compile wall hidden behind the device sweep
        # (the >= 70% bar at >= 4 chunks).
        "ttfc_ratio": streaming["ttfc_s"] / max(whole["ttfc_s"], 1e-9),
        "ttfc_vs_chunk_compile": (
            streaming["ttfc_s"]
            / max(st.get("first_chunk_compile_s", 0.0), 1e-9)
        ),
        "overlap_ratio": st.get("overlap_ratio", 0.0),
        "steady_overlap_ratio": st.get("steady_overlap_ratio", 0.0),
        "peak_resident_plan_bytes": st.get(
            "peak_resident_plan_bytes", 0
        ),
        "chunk_bytes_max": st.get("chunk_bytes_max", 0),
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


# ----------------------------------------------------------- telemetry A/B --


def run_telemetry_ab(args: argparse.Namespace) -> None:
    """A/B the telemetry layer's overhead (PERF.md §21) on the
    production crack contract: the same wordlist × table × decoy
    digests swept end-to-end through ``Sweep.run_crack``, instrumented
    (registry + span timeline live at every fetch boundary) vs
    ``A5GEN_TELEMETRY=off``.  Sweep construction (plan/schema compile —
    identical host work either way) stays OUTSIDE the timed window so
    the ratio measures the per-fetch instrumentation, which is where
    the overhead risk lives; arms alternate run-for-run so host drift
    cannot masquerade as overhead.  Honesty guards: the instrumented
    arm must actually have recorded spans and the off arm must not
    (else the A/B compares off against off), and both arms must emit
    identical counts.  Bar: overhead_ratio ≤ 1% wall.  Prints ONE JSON
    line."""
    import os

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
    from hashcat_a5_table_generator_tpu.runtime import telemetry
    from hashcat_a5_table_generator_tpu.runtime.sweep import (
        Sweep,
        SweepConfig,
    )
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    dev = jax.devices()[0]
    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    if lanes % nb:
        raise SystemExit("--telemetry-ab needs blocks dividing lanes")
    spec = AttackSpec(mode=args.mode, algo=args.algo)
    sub_map = get_layout(args.table).to_substitution_map()
    words = synth_wordlist(args.words)
    host_digest = HOST_DIGEST[spec.algo]
    digests = [
        host_digest(b"bench-decoy-%d" % i) for i in range(1024)
    ]
    from hashcat_a5_table_generator_tpu.runtime.env import read_env

    prior = read_env("A5GEN_TELEMETRY")

    def one_run(off: bool) -> "tuple[float, int, int]":
        """(timed run_crack wall, emitted, fetch spans recorded)."""
        if off:
            os.environ["A5GEN_TELEMETRY"] = "off"
        else:
            os.environ.pop("A5GEN_TELEMETRY", None)
        sweep = Sweep(
            spec, sub_map, words, digests,
            config=SweepConfig(lanes=lanes, num_blocks=nb),
        )
        snap0 = telemetry.snapshot()
        t0 = time.perf_counter()
        res = sweep.run_crack(resume=False)
        wall = time.perf_counter() - t0
        d = telemetry.delta(snap0, telemetry.snapshot())
        spans = sum(
            v["value"] for k, v in d.items()
            if k.startswith("sweep.fetches.")
        )
        return wall, res.n_emitted, spans

    try:
        one_run(off=True)   # warm both arms' compiled steps (shared)
        one_run(off=False)
        arms = {"off": [], "instrumented": []}
        spans = {"off": 0, "instrumented": 0}
        emitted = {"off": None, "instrumented": None}
        t_bench = time.perf_counter()
        while (
            not arms["off"]
            or time.perf_counter() - t_bench < args.seconds
        ):
            for name, off in (("off", True), ("instrumented", False)):
                wall, ne, sp = one_run(off)
                arms[name].append(wall)
                spans[name] += sp
                if emitted[name] is None:
                    emitted[name] = ne
                elif emitted[name] != ne:
                    raise SystemExit(
                        f"--telemetry-ab {name} arm emitted {ne}, "
                        f"expected {emitted[name]} — nondeterministic "
                        "work; refusing to report timings"
                    )
    finally:
        if prior is None:
            os.environ.pop("A5GEN_TELEMETRY", None)
        else:
            os.environ["A5GEN_TELEMETRY"] = prior
    if emitted["off"] != emitted["instrumented"]:
        raise SystemExit(
            f"--telemetry-ab arms diverged: instrumented emitted "
            f"{emitted['instrumented']}, off {emitted['off']} — the "
            "hatch must never change results"
        )
    if spans["instrumented"] == 0 or spans["off"] != 0:
        raise SystemExit(
            f"--telemetry-ab honesty check failed: instrumented arm "
            f"recorded {spans['instrumented']} fetch spans, off arm "
            f"{spans['off']} (want >0 and 0) — the arms are not "
            "actually A and B"
        )

    def arm_record(name: str) -> dict:
        walls = arms[name]
        mean = sum(walls) / len(walls)
        return {
            "wall_s_mean": mean,
            "wall_s_min": min(walls),
            "runs": len(walls),
            "hashes_per_sec": emitted[name] / max(mean, 1e-9),
            "fetch_spans": spans[name],
        }

    inst, off = arm_record("instrumented"), arm_record("off")
    record = {
        "metric": "telemetry_overhead_ab",
        "unit": "run_crack wall seconds + overhead ratio",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": lanes,
        "blocks": nb,
        "words": args.words,
        "n_emitted": emitted["off"],
        "instrumented": inst,
        "off": off,
        # The §21 acceptance instrument: instrumented-vs-off wall
        # overhead on the production contract; bar ≤ 1%.
        "overhead_ratio": inst["wall_s_mean"] / max(
            off["wall_s_mean"], 1e-9
        ) - 1.0,
        "bar": 0.01,
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


# ----------------------------------------------------------- serve-mode A/B --


def run_serve_ab(args: argparse.Namespace) -> None:
    """A/B the resident engine (PERF.md §20) against N cold
    CLI-equivalent runs on the production crack contract: the same N
    equal small jobs (one wordlist × table × decoy digests each, the
    --stream-ab fixture discipline) swept end-to-end per arm.

    The COLD arm models today's per-invocation cost: before every job
    the process-level compiled-step cache and jax's compilation caches
    are cleared (a fresh CLI process additionally pays imports — this
    arm is conservative), and no schema cache is configured.  The
    ENGINE arm is one resident :class:`Engine`: job 0 pays the one
    program + schema build (its ttfc IS the cold ttfc), jobs 1..N-1 are
    warm — submitted together and interleaved at superstep boundaries —
    with the engine's schema cache on a throwaway directory.  Reports
    per-job ttfc (the shared ``_TtfcProbe`` definition), aggregate wall
    and jobs/s, and each arm's compiled-program count (the step-cache
    miss counter — the compile-once assertion); asserts per-job emitted
    counts identical across arms.  Prints ONE JSON line."""
    import shutil
    import tempfile
    from dataclasses import replace

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
    from hashcat_a5_table_generator_tpu.runtime import sweep as sweep_mod
    from hashcat_a5_table_generator_tpu.runtime.engine import Engine
    from hashcat_a5_table_generator_tpu.runtime.sweep import (
        Sweep,
        SweepConfig,
        step_cache_stats,
    )
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    dev = jax.devices()[0]
    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    if lanes % nb:
        raise SystemExit("--serve-ab needs blocks dividing lanes")
    n_jobs = max(2, int(args.serve_jobs))
    spec = AttackSpec(mode=args.mode, algo=args.algo)
    sub_map = get_layout(args.table).to_substitution_map()
    words = synth_wordlist(args.words)
    host_digest = HOST_DIGEST[spec.algo]
    digests = [host_digest(b"bench-decoy-%d" % i) for i in range(1024)]
    base_cfg = SweepConfig(lanes=lanes, num_blocks=nb)

    def clear_compile_caches() -> None:
        # The cold-CLI simulation: no compiled step survives between
        # jobs (jax.clear_caches drops the executables the step cache's
        # jit objects hold, _STEP_CACHE/_WARMED_STEPS the objects).
        with sweep_mod._STEP_CACHE_LOCK:
            sweep_mod._STEP_CACHE.clear()
        sweep_mod._WARMED_STEPS.clear()
        jax.clear_caches()

    def cold_arm() -> dict:
        jobs = []
        s0 = step_cache_stats()
        t_arm = time.perf_counter()
        for _ in range(n_jobs):
            clear_compile_caches()
            probe = _TtfcProbe()
            cfg = replace(base_cfg, progress=probe)
            t0 = time.perf_counter()
            res = Sweep(spec, sub_map, words, digests,
                        config=cfg).run_crack(resume=False)
            wall = time.perf_counter() - t0
            jobs.append({
                "wall_s": wall,
                "ttfc_s": (
                    probe.first - t0 if probe.first is not None else wall
                ),
                "n_emitted": res.n_emitted,
            })
        arm_wall = time.perf_counter() - t_arm
        s1 = step_cache_stats()
        return {
            "wall_s": arm_wall,
            "jobs_per_sec": n_jobs / max(arm_wall, 1e-9),
            "jobs": jobs,
            "ttfc_mean_s": sum(j["ttfc_s"] for j in jobs) / n_jobs,
            "programs_compiled": s1["misses"] - s0["misses"],
        }

    def engine_arm() -> dict:
        clear_compile_caches()
        cache_dir = tempfile.mkdtemp(prefix="a5-serve-ab-schema-")
        # The bench owns the serve loop (auto=False — the embedder
        # mode): both arms then compile on the same thread, which
        # matters on hosts where XLA compiles slower off the main
        # thread (observed ~1.8x here).
        engine = Engine(replace(base_cfg, schema_cache=cache_dir),
                        auto=False)
        try:
            t_arm = time.perf_counter()
            probes, handles, submits = [], [], []

            def submit_one():
                probe = _TtfcProbe()
                probes.append(probe)
                submits.append(time.perf_counter())
                handles.append(engine.submit(
                    spec, sub_map, words, digests,
                    config=replace(base_cfg, schema_cache=cache_dir,
                                   progress=probe),
                ))

            # Job 0 pays the build (the engine's cold ttfc); the rest
            # arrive together and multiplex warm.
            submit_one()
            engine.run_until_idle()
            for _ in range(n_jobs - 1):
                submit_one()
            engine.run_until_idle()
            results = [h.result(timeout=0) for h in handles]
            arm_wall = time.perf_counter() - t_arm
            # One more warm job on the now-idle engine: the cold arm's
            # jobs ran ALONE, so the like-for-like warm ttfc must too —
            # the batch above measures ttfc under concurrent admission
            # (each job also waits on its peers' interleaved
            # supersteps), reported separately.
            submit_one()
            engine.run_until_idle()
            results.append(handles[-1].result(timeout=0))
            jobs = [
                {
                    "ttfc_s": (
                        probes[i].first - submits[i]
                        if probes[i].first is not None else arm_wall
                    ),
                    "n_emitted": results[i].n_emitted,
                }
                for i in range(len(handles))
            ]
            stats = engine.stats()
            warm_batch = jobs[1:n_jobs]
            return {
                "wall_s": arm_wall,
                "jobs_per_sec": n_jobs / max(arm_wall, 1e-9),
                "jobs": jobs,
                "ttfc_cold_s": jobs[0]["ttfc_s"],
                # Concurrent-admission warm ttfc (includes the wait on
                # peer jobs' interleaved supersteps)...
                "ttfc_warm_batch_mean_s": (
                    sum(j["ttfc_s"] for j in warm_batch)
                    / len(warm_batch)
                ),
                # ...and the solo warm ttfc — the apples-to-apples
                # comparator for the cold arm's solo jobs.
                "ttfc_warm_idle_s": jobs[-1]["ttfc_s"],
                "programs_compiled": stats["programs_compiled"],
                "program_cache_hits": stats["program_cache_hits"],
                "schema_cache": stats["schema_cache"],
            }
        finally:
            engine.close()
            shutil.rmtree(cache_dir, ignore_errors=True)

    cold = cold_arm()
    engine = engine_arm()
    emitted = {j["n_emitted"] for j in cold["jobs"]} | {
        j["n_emitted"] for j in engine["jobs"]
    }
    if len(emitted) != 1:
        raise SystemExit(
            f"--serve-ab arms diverged: per-job emitted counts {emitted} "
            "— refusing to report timings for non-identical work"
        )
    record = {
        "metric": "serve_mode_ab",
        "unit": "seconds (ttfc, wall) + jobs/sec + program builds",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": lanes,
        "blocks": nb,
        "words": args.words,
        "jobs": n_jobs,
        "cold": cold,
        "engine": engine,
        # The §20 acceptance instruments: solo warm ttfc against the
        # cold arm's mean of solo jobs (the <= 0.1x bar), aggregate
        # wall (the >= 2x bar), and the compile-once assertion (engine
        # arm's program builds vs the cold arm's N).
        "warm_ttfc_ratio": (
            engine["ttfc_warm_idle_s"] / max(cold["ttfc_mean_s"], 1e-9)
        ),
        "warm_ttfc_batch_ratio": (
            engine["ttfc_warm_batch_mean_s"]
            / max(cold["ttfc_mean_s"], 1e-9)
        ),
        "wall_ratio": cold["wall_s"] / max(engine["wall_s"], 1e-9),
        "compile_ratio": (
            cold["programs_compiled"]
            / max(engine["programs_compiled"], 1)
        ),
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


def run_fleet_ab(args: argparse.Namespace,
                 elastic: bool = False) -> None:
    """A/B routed vs direct serve on the §20 contract (PERF.md §25):
    arm DIRECT drives N equal small jobs against one freshly spawned
    ``a5gen serve`` engine over its unix socket; arm ROUTED drives the
    identical jobs against an identically spawned engine behind a
    :class:`FleetRouter`.  Both arms pre-warm with one untimed job so
    the measured window is the steady-state hot path (the router adds
    JSON re-framing + a table lookup per event — the §25 acceptance
    bar is within 5% aggregate wall).  Parity-asserts per-job
    emitted/hit counts across arms; prints ONE JSON line.

    ``elastic=True`` (``--elastic-ab``, PERF.md §27) arms the elastic
    tier on the routed arm: admission control ON (capacity + bounded
    pending) and the autoscaler's control loop TICKING, with
    thresholds the toy load never crosses — pinning that the elastic
    machinery costs nothing at steady state (the same ≤5% bar vs the
    direct arm; the record asserts no scale action fired, so the
    measured window really is steady-state).

    Runs NO jax in this process — both arms' device work happens in
    the engine subprocesses, so the bench process never competes with
    them for the backend."""
    import os
    import shutil
    import socket
    import tempfile

    from hashcat_a5_table_generator_tpu.runtime.autoscale import (
        AutoscaleConfig,
        Autoscaler,
    )
    from hashcat_a5_table_generator_tpu.runtime.fleet import (
        FleetRouter,
        spawn_engines,
    )
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout

    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    n_jobs = max(2, int(args.serve_jobs))
    words = synth_wordlist(args.words)
    sub_map = get_layout(args.table).to_substitution_map()
    import hashlib as _hashlib

    digests = [
        _hashlib.new(args.algo, b"bench-decoy-%d" % i).digest()
        for i in range(1024)
    ]
    job_fields = {
        "words": [w.decode() for w in words],
        "table_map": {
            k.decode(): [v.decode() for v in vals]
            for k, vals in sub_map.items()
        },
        "algo": args.algo,
        "mode": args.mode,
        "digest_list": [d.hex() for d in digests],
        "config": {"lanes": lanes, "blocks": nb},
    }
    env = dict(os.environ)
    if args.platform:
        env["JAX_PLATFORMS"] = args.platform

    def spawn_one(tag: str):
        d = tempfile.mkdtemp(prefix=f"a5-fleet-ab-{tag}-")
        specs = spawn_engines(
            1, d,
            engine_args=["--lanes", str(lanes), "--blocks", str(nb),
                         "--schema-cache", os.path.join(d, "cache")],
            engine_id_prefix=tag, env=env,
        )
        return d, specs[0]

    def direct_arm() -> dict:
        d, (sock_path, _eid, proc) = spawn_one("direct")
        conn = None
        try:
            deadline = time.monotonic() + 300
            while True:
                try:
                    conn = socket.socket(socket.AF_UNIX)
                    conn.connect(sock_path)
                    break
                except OSError:
                    conn.close()
                    conn = None
                    if proc.poll() is not None:
                        raise SystemExit(
                            "--fleet-ab: direct-arm engine exited "
                            f"with {proc.returncode}"
                        )
                    if time.monotonic() > deadline:
                        raise SystemExit(
                            "--fleet-ab: direct-arm engine never "
                            "listened"
                        )
                    time.sleep(0.2)
            f = conn.makefile("rw", encoding="utf-8")

            def run_jobs(ids):
                per = {}
                for j in ids:
                    f.write(json.dumps(
                        {**job_fields, "op": "submit", "id": j}
                    ) + "\n")
                f.flush()
                while len(per) < len(ids):
                    ev = json.loads(f.readline())
                    if ev.get("event") == "done":
                        per[ev["id"]] = {
                            "n_emitted": ev["n_emitted"],
                            "n_hits": ev["n_hits"],
                        }
                    elif ev.get("event") in ("failed", "error"):
                        raise SystemExit(
                            f"--fleet-ab direct arm failed: {ev}"
                        )
                return per

            run_jobs(["warm0"])  # untimed: the compile lands here
            t0 = time.perf_counter()
            per = run_jobs([f"d{i}" for i in range(n_jobs)])
            wall = time.perf_counter() - t0
            f.write('{"op":"shutdown"}\n')
            f.flush()
            proc.wait(timeout=60)
            return {
                "wall_s": wall,
                "jobs_per_sec": n_jobs / max(wall, 1e-9),
                "jobs": [per[f"d{i}"] for i in range(n_jobs)],
            }
        finally:
            if conn is not None:
                conn.close()
            if proc.poll() is None:
                proc.kill()
                proc.wait()
            shutil.rmtree(d, ignore_errors=True)

    def routed_arm() -> dict:
        d, (sock_path, eid, proc) = spawn_one("routed")
        if elastic:
            # The §27 arm: admission control armed at bounds the toy
            # load never hits — the cost measured is the capacity
            # check + pending bookkeeping, not queueing.
            router = FleetRouter(place=args.fleet_place, poll_s=1.0,
                                 engine_capacity=64, max_pending=256)
        else:
            router = FleetRouter(place=args.fleet_place, poll_s=1.0)
        scaler = None
        try:
            router.attach(sock_path, eid, proc=proc, timeout=300)
            if elastic:
                # Ticking for real (interval_s), thresholds the toy
                # load cannot cross: the steady state must SCALE
                # nothing while the loop runs — asserted below.
                scaler = Autoscaler(
                    router,
                    lambda: (_ for _ in ()).throw(
                        RuntimeError("steady-state arm must not spawn")
                    ),
                    AutoscaleConfig(
                        min_engines=1, max_engines=2,
                        scale_up_at=1e6, scale_down_at=0.0,
                        up_window=2, down_window=10**6,
                        cooldown_s=5.0, interval_s=0.25,
                    ),
                )
            events: dict = {}

            def submit(j):
                events[j] = []
                router.submit({**job_fields, "op": "submit", "id": j},
                              emit=events[j].append)

            def done_of(j):
                if not router.wait(j, timeout=600):
                    raise SystemExit(
                        f"--fleet-ab routed arm: job {j} never settled"
                    )
                done = [e for e in events[j]
                        if e.get("event") == "done"]
                if not done:
                    raise SystemExit(
                        f"--fleet-ab routed arm: job {j} settled "
                        f"{router.job(j).state} — {events[j][-3:]}"
                    )
                return {"n_emitted": done[0]["n_emitted"],
                        "n_hits": done[0]["n_hits"]}

            submit("warm0")
            done_of("warm0")
            t0 = time.perf_counter()
            for i in range(n_jobs):
                submit(f"r{i}")
            jobs = [done_of(f"r{i}") for i in range(n_jobs)]
            wall = time.perf_counter() - t0
            out = {
                "wall_s": wall,
                "jobs_per_sec": n_jobs / max(wall, 1e-9),
                "jobs": jobs,
            }
            if scaler is not None:
                scale = scaler.describe()
                quarantined = router.stats()["fleet"][
                    "engines_quarantined"
                ]
                if (scale["scale_ups"] or scale["scale_downs"]
                        or scale["spawn_failures"] or quarantined):
                    raise SystemExit(
                        "--elastic-ab: the steady-state arm scaled, "
                        "failed a spawn, or quarantined its engine "
                        f"({scale}, quarantined={quarantined}) — the "
                        "measured window is not steady-state; "
                        "refusing to report"
                    )
                out["autoscale"] = {
                    k: scale[k] for k in
                    ("min", "max", "scale_ups", "scale_downs",
                     "spawn_failures")
                }
            return out
        finally:
            router.close(shutdown_engines=True)
            shutil.rmtree(d, ignore_errors=True)

    direct = direct_arm()
    routed = routed_arm()
    per_arm = [
        tuple((j["n_emitted"], j["n_hits"]) for j in arm["jobs"])
        for arm in (direct, routed)
    ]
    if len(set(per_arm)) != 1 or not all(
        j["n_emitted"] > 0 for j in direct["jobs"]
    ):
        raise SystemExit(
            f"--fleet-ab arms diverged: per-job counts {per_arm} — "
            "refusing to report timings for non-identical work"
        )
    record = {
        "metric": "elastic_ab" if elastic else "fleet_ab",
        "unit": "seconds (aggregate wall) + jobs/sec",
        "platform": args.platform or "default",
        "lanes": lanes,
        "blocks": nb,
        "words": args.words,
        "jobs": n_jobs,
        "place": args.fleet_place,
        "direct": direct,
        "routed": routed,
        # The §25 passthrough instrument (§27 reuses the bar with the
        # elastic tier armed): routed wall over direct wall (1.0 =
        # free; the acceptance bar is <= 1.05 on the §20 contract).
        "wall_ratio": routed["wall_s"] / max(direct["wall_s"], 1e-9),
        "overhead_pct": 100.0 * (
            routed["wall_s"] / max(direct["wall_s"], 1e-9) - 1.0
        ),
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


def run_split_ab(args: argparse.Namespace) -> None:
    """A/B giant-job striping (PERF.md §31) on the fleet contract: ONE
    oversized crack job submitted to a :class:`FleetRouter` backed by
    ``--split-engines`` spawned engines with striping ON — the router
    scatters it as disjoint rank-stride shard ranges and k-way-merges
    the per-shard hit streams back into one (word,rank)-ordered client
    stream — vs the IDENTICAL job on one engine with striping OFF.
    Both arms warm with one untimed identical job so the measured
    window is sweep throughput, not compile.  Parity-asserts the
    merged hit stream against the solo arm's tuple-for-tuple (content
    AND order — the merge's whole contract) plus the done totals, and
    reports per-arm wall, the speedup, and the router-side merge
    overhead as a share of the split arm's wall (the §31 acceptance
    instruments).  Runs NO jax in this process — both arms' device
    work happens in the engine subprocesses."""
    import hashlib as _hashlib
    import os
    import shutil
    import tempfile

    import hashcat_a5_table_generator_tpu.runtime.fleet as fleet_mod
    from hashcat_a5_table_generator_tpu.oracle.engines import (
        iter_candidates,
    )
    from hashcat_a5_table_generator_tpu.runtime.fleet import (
        FleetRouter,
        spawn_engines,
    )
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout

    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    n_engines = max(2, int(args.split_engines))
    words = synth_wordlist(args.words)
    sub_map = get_layout(args.table).to_substitution_map()
    # Plant real hits scattered through the keyspace (the host oracle
    # enumerates reference order) so the merge path actually carries a
    # stream to order, plus decoys for membership pressure.
    planted = set()
    for w in words[:: max(1, len(words) // 37)]:
        cands = list(iter_candidates(w, sub_map, 0, 15))
        planted.add(cands[len(cands) // 2])
    digests = sorted(
        _hashlib.new(args.algo, c).digest() for c in planted
    ) + [
        _hashlib.new(args.algo, b"split-decoy-%d" % i).digest()
        for i in range(512)
    ]
    job_fields = {
        "words": [w.decode() for w in words],
        "table_map": {
            k.decode(): [v.decode() for v in vals]
            for k, vals in sub_map.items()
        },
        "algo": args.algo,
        "mode": args.mode,
        "digest_list": [d.hex() for d in digests],
        "config": {"lanes": lanes, "blocks": nb},
    }
    env = dict(os.environ)
    if args.platform:
        env["JAX_PLATFORMS"] = args.platform

    def arm(tag: str, n: int, split: str) -> dict:
        d = tempfile.mkdtemp(prefix=f"a5-split-ab-{tag}-")
        router = FleetRouter(poll_s=1.0, split=split)
        merge_s = [0.0]
        orig_round = fleet_mod._SplitMerge._merge_round

        def timed_round(self, i, ev, _orig=orig_round):
            t0 = time.perf_counter()
            _orig(self, i, ev)
            merge_s[0] += time.perf_counter() - t0

        fleet_mod._SplitMerge._merge_round = timed_round
        try:
            specs = spawn_engines(
                n, d,
                engine_args=["--lanes", str(lanes), "--blocks", str(nb),
                             "--schema-cache", os.path.join(d, "cache")],
                engine_id_prefix=tag, env=env,
            )
            for sock_path, eid, proc in specs:
                router.attach(sock_path, eid, proc=proc, timeout=300)
            events: dict = {}

            def run_one(j):
                events[j] = []
                router.submit({**job_fields, "op": "submit", "id": j},
                              emit=events[j].append)
                if not router.wait(j, timeout=900):
                    raise SystemExit(
                        f"--split-ab {tag} arm: job {j} never settled"
                    )
                done = [e for e in events[j] if e.get("event") == "done"]
                if not done:
                    raise SystemExit(
                        f"--split-ab {tag} arm: job {j} settled "
                        f"{router.job(j).state} — {events[j][-3:]}"
                    )
                return done[0]

            run_one("warm0")  # untimed: the compiles land here
            merge_s[0] = 0.0
            t0 = time.perf_counter()
            done = run_one("big0")
            wall = time.perf_counter() - t0
            hits = [
                (e["word_index"], int(e["rank"]), e["plain_hex"],
                 e["digest"])
                for e in events["big0"] if e.get("event") == "hit"
            ]
            fleet = router.stats()["fleet"]
            return {
                "wall_s": wall,
                "engines": n,
                "n_emitted": done["n_emitted"],
                "n_hits": done["n_hits"],
                "hits": hits,
                "jobs_split": fleet["jobs_split"],
                "shard_done_events": sum(
                    1 for e in events["big0"]
                    if e.get("event") == "shard_done"
                ),
                "merge_s": merge_s[0],
            }
        finally:
            fleet_mod._SplitMerge._merge_round = orig_round
            router.close(shutdown_engines=True)
            shutil.rmtree(d, ignore_errors=True)

    solo = arm("solo", 1, "off")
    split = arm("split", n_engines, "on")
    if split["jobs_split"] != 2:  # warm job + measured job both scatter
        raise SystemExit(
            "--split-ab: the split arm never scattered "
            f"(jobs_split={split['jobs_split']}) — nothing to measure"
        )
    if (
        split["hits"] != solo["hits"]
        or split["n_hits"] != solo["n_hits"]
        or split["n_emitted"] != solo["n_emitted"]
        or not solo["hits"]
    ):
        raise SystemExit(
            "--split-ab arms diverged: merged stream "
            f"{len(split['hits'])} hits (emitted {split['n_emitted']}) "
            f"vs solo {len(solo['hits'])} (emitted {solo['n_emitted']}) "
            "— refusing to report timings for a non-identical stream"
        )
    for a in (solo, split):
        a["hits"] = len(a.pop("hits"))  # parity held; drop the bulk
    record = {
        "metric": "split_ab",
        "unit": "seconds (wall) + speedup",
        "platform": args.platform or "default",
        "lanes": lanes,
        "blocks": nb,
        "words": args.words,
        "planted_hits": len(planted),
        # The striping win is host-parallelism-gated: N engine
        # processes on < N usable cores timeshare the sweep compute
        # and the wall ratio honestly reads ~1.0.  Recorded so a
        # speedup number is never compared across hosts blind.
        "host_cpus": len(os.sched_getaffinity(0)),
        "solo": solo,
        "split": split,
        # §31 acceptance instruments: fleet-level speedup on ONE job
        # (the striping headroom), and the router's merge cost as a
        # share of the split wall (the merge must stay bookkeeping,
        # not a second pipeline stage).
        "speedup": solo["wall_s"] / max(split["wall_s"], 1e-9),
        "merge_overhead_share": (
            split["merge_s"] / max(split["wall_s"], 1e-9)
        ),
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


def run_pack_ab(args: argparse.Namespace) -> None:
    """A/B the cross-job packed dispatch (PERF.md §22) against the PR 8
    per-job round-robin on the production crack contract: the same N
    compatible small jobs (one synthetic wordlist, per-tenant decoy
    digest sets — the underfilled-superstep regime packing targets)
    swept through a resident Engine per arm, both arms WARM (a
    throwaway batch first, so the measurement is dispatch amortization,
    not compile).  Reports per-arm aggregate wall, the packed arm's
    fill ratio (occupied / total lanes per dispatch), concurrent-
    admission warm ttfc (batch mean over a fresh batch on the warm
    engine — §20's 0.123 s comparator), per-job span fairness (max/min
    host-gap share from the PR 9 timeline), and parity-asserts every
    job's emitted count against its own SOLO run.  One JSON line."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
    from hashcat_a5_table_generator_tpu.runtime.engine import Engine
    from hashcat_a5_table_generator_tpu.runtime.sweep import (
        Sweep,
        SweepConfig,
    )
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    dev = jax.devices()[0]
    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    if lanes % nb:
        raise SystemExit("--pack-ab needs blocks dividing lanes")
    n_jobs = max(2, int(args.pack_jobs))
    if nb % n_jobs:
        raise SystemExit(
            f"--pack-ab needs --blocks ({nb}) divisible by --pack-jobs "
            f"({n_jobs}) so every job owns an equal segment"
        )
    spec = AttackSpec(mode=args.mode, algo=args.algo)
    sub_map = get_layout(args.table).to_substitution_map()
    words = synth_wordlist(args.words)
    host_digest = HOST_DIGEST[spec.algo]
    job_digests = [
        [host_digest(b"bench-decoy-%d-%d" % (j, i)) for i in range(256)]
        for j in range(n_jobs)
    ]
    # superstep=4: the underfilled contract's superstep size.  The auto
    # default (fetch_chunk = 16 launches per superstep) targets long
    # sweeps; for jobs a fraction of ONE superstep long it just pads
    # every dispatch — and ttfc — with masked scan steps, identically
    # in both arms.  4 keeps several supersteps per job (so the span
    # fairness instrument has data) without that padding.
    base_cfg = SweepConfig(lanes=lanes, num_blocks=nb, superstep=4)

    solo = []
    for j in range(n_jobs):
        res = Sweep(spec, sub_map, words, job_digests[j],
                    config=base_cfg).run_crack(resume=False)
        solo.append(res.n_emitted)

    def arm(pack: bool) -> dict:
        engine = Engine(base_cfg, auto=False, pack=pack)
        try:
            def batch(probes=None):
                handles = []
                submits = []
                for j in range(n_jobs):
                    cfg = base_cfg
                    if probes is not None:
                        probe = _TtfcProbe()
                        probes.append(probe)
                        from dataclasses import replace

                        cfg = replace(base_cfg, progress=probe)
                    submits.append(time.perf_counter())
                    handles.append(engine.submit(
                        spec, sub_map, words, job_digests[j], config=cfg
                    ))
                return handles, submits

            def run_batch(probes=None):
                handles, submits = batch(probes)
                engine.run_until_idle()
                return handles, submits

            run_batch()  # warm: programs compiled here (both arms)
            # The measured batch splits admission from serving: the
            # plan builds are identical work in both arms (measured as
            # admit_wall_s); the SERVE wall is the dispatch+consume
            # phase packing exists to amortize — the §22 wall-ratio
            # instrument compares it.
            t0 = time.perf_counter()
            handles, _ = batch()
            engine._admit()  # builds + fuse, no dispatch
            t1 = time.perf_counter()
            engine.run_until_idle()
            wall = time.perf_counter() - t1
            results = [h.result(timeout=0) for h in handles]
            emitted = [r.n_emitted for r in results]
            gaps = [
                h.span_summary.get("host_gap_s", 0.0) for h in handles
            ]
            fairness = (
                max(gaps) / min(gaps) if gaps and min(gaps) > 0 else None
            )
            # Concurrent-admission warm ttfc: a fresh batch on the warm
            # engine, each job's first consumed fetch since ITS submit
            # (admission builds INCLUDED — that is what a tenant waits).
            probes: list = []
            handles, submits = run_batch(probes)
            for h in handles:
                h.result(timeout=0)
            ttfc = [
                probes[i].first - submits[i]
                for i in range(n_jobs)
                if probes[i].first is not None
            ]
            stats = engine.stats()
            return {
                "wall_s": wall,
                "admit_wall_s": t1 - t0,
                "jobs": n_jobs,
                "emitted": emitted,
                "warm_ttfc_batch_mean_s": (
                    sum(ttfc) / len(ttfc) if ttfc else None
                ),
                "span_fairness_maxmin": fairness,
                "packed_dispatches": stats["packed_dispatches"],
                "fill_ratio": stats["packed_fill"],
                # Per-pump fill instruments (PERF.md §28): the
                # aggregate above dilutes post-departure decay across
                # every dispatch since engine start; these carry the
                # LAST observed per-dispatch fill and the running
                # minimum, so churn (and the re-fuse response) is
                # visible in the JSON.
                "fill_last": stats["packed_fill_last"],
                "fill_min": stats["packed_fill_min"],
                "refuse_total": stats["refuse_total"],
                "supersteps_served": stats["supersteps_served"],
            }
        finally:
            engine.close()

    packed = arm(True)
    rr = arm(False)
    for name, a in (("packed", packed), ("round-robin", rr)):
        if a["emitted"] != solo:
            raise SystemExit(
                f"--pack-ab {name} arm diverged from solo runs: "
                f"{a['emitted']} vs {solo} — refusing to report timings "
                "for non-identical work"
            )
    if packed["packed_dispatches"] == 0:
        raise SystemExit(
            "--pack-ab packed arm never fused — the jobs were expected "
            "to be compatible by construction"
        )
    record = {
        "metric": "pack_mode_ab",
        "unit": "seconds (wall, ttfc) + ratios",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": lanes,
        "blocks": nb,
        "words": args.words,
        "jobs": n_jobs,
        "packed": packed,
        "round_robin": rr,
        # §22 acceptance instruments: aggregate SERVE-wall ratio (the
        # >=1.3x bar for underfilled jobs; admission builds are
        # identical work in both arms and reported as admit_wall_s),
        # the packed arm's fill ratio, and the concurrent-admission
        # warm ttfc (vs §20's 0.123 s; builds included).
        "wall_ratio": rr["wall_s"] / max(packed["wall_s"], 1e-9),
        "fill_ratio": packed["fill_ratio"],
        "warm_ttfc_batch_s": packed["warm_ttfc_batch_mean_s"],
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


# ----------------------------------------------------------- pack churn A/B --


def run_pack_churn(args: argparse.Namespace) -> None:
    """A/B dynamic re-fuse (PERF.md §28) under tenant churn: per arm,
    ``--churn-waves`` waves of N compatible jobs are submitted to a
    warm packed resident Engine, half of each wave is CANCELLED after
    two serve rounds (the departure the §28 trigger watches), and the
    wave drains.  The re-fuse arm (``refuse_below=--refuse-below``)
    retraces survivors into tighter groups; the control arm
    (``refuse_below=0``) keeps dispatching the thinned group with
    masked lanes.  Reports per-arm serve wall, the post-departure fill
    minimum, the post-re-fuse recovered fill peak, and the refuse
    count; parity-asserts every SURVIVOR's emitted count against its
    own solo run.  One JSON line."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
    from hashcat_a5_table_generator_tpu.runtime.engine import Engine
    from hashcat_a5_table_generator_tpu.runtime.sweep import (
        Sweep,
        SweepConfig,
    )
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    dev = jax.devices()[0]
    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    if lanes % nb:
        raise SystemExit("--pack-churn needs blocks dividing lanes")
    n_jobs = max(2, int(args.pack_jobs))
    if nb % n_jobs:
        raise SystemExit(
            f"--pack-churn needs --blocks ({nb}) divisible by "
            f"--pack-jobs ({n_jobs}) so every job owns an equal segment"
        )
    waves = max(1, int(args.churn_waves))
    spec = AttackSpec(mode=args.mode, algo=args.algo)
    sub_map = get_layout(args.table).to_substitution_map()
    words = synth_wordlist(args.words)
    host_digest = HOST_DIGEST[spec.algo]
    job_digests = [
        [host_digest(b"churn-decoy-%d-%d" % (j, i)) for i in range(256)]
        for j in range(n_jobs)
    ]
    base_cfg = SweepConfig(lanes=lanes, num_blocks=nb, superstep=4)
    # Half of each wave departs; parity only makes sense for the jobs
    # that run to completion.
    cancelled = set(range(0, n_jobs, 2)) if n_jobs > 2 else {0}
    survivors = [j for j in range(n_jobs) if j not in cancelled]

    solo = {}
    for j in survivors:
        res = Sweep(spec, sub_map, words, job_digests[j],
                    config=base_cfg).run_crack(resume=False)
        solo[j] = res.n_emitted

    def arm(refuse: bool) -> dict:
        engine = Engine(base_cfg, auto=False, pack=True,
                        refuse_below=(args.refuse_below if refuse
                                      else 0))
        try:
            def submit_wave():
                return [
                    engine.submit(spec, sub_map, words, job_digests[j])
                    for j in range(n_jobs)
                ]

            # Warm: compile both the full-width and (on the re-fuse
            # arm) the survivor-width packed programs outside the
            # measured window, so the walls compare dispatch behavior,
            # not compile.
            warm = submit_wave()
            engine._admit()
            for _ in range(2):
                engine._serve_round()
            for j in cancelled:
                warm[j].cancel()
            engine.run_until_idle()

            wall = 0.0
            fill_min = None
            post_refuse_peak = None
            emitted = {j: [] for j in survivors}
            for _wave in range(waves):
                handles = submit_wave()
                engine._admit()  # builds + fuse, outside the wall
                t0 = time.perf_counter()
                for _ in range(2):
                    engine._serve_round()
                for j in cancelled:
                    handles[j].cancel()
                # Drain the wave, sampling the per-pump fill so the
                # post-departure decay AND the post-re-fuse recovery
                # both land in the record.
                while True:
                    engine._serve_round()
                    engine._admit(wait=False)  # collect refuse builds
                    st = engine.stats()
                    if st["packed_fill_last"]:
                        f = st["packed_fill_last"]
                        if fill_min is None or f < fill_min:
                            fill_min = f
                        if st["refuse_total"] and st["fused_groups"]:
                            post_refuse_peak = max(
                                post_refuse_peak or 0.0, f
                            )
                    if not st["jobs_active"]:
                        break
                wall += time.perf_counter() - t0
                for j in survivors:
                    emitted[j].append(handles[j].result(timeout=5)
                                      .n_emitted)
            stats = engine.stats()
            for j in survivors:
                for wave_idx, n in enumerate(emitted[j]):
                    if n != solo[j]:
                        raise SystemExit(
                            f"--pack-churn {'re-fuse' if refuse else 'control'} "
                            f"arm diverged from solo: job {j} wave "
                            f"{wave_idx} emitted {n} vs {solo[j]} — "
                            "refusing to report timings for "
                            "non-identical work"
                        )
            return {
                "wall_s": wall,
                "waves": waves,
                "jobs_per_wave": n_jobs,
                "cancelled_per_wave": len(cancelled),
                "fill_min": fill_min,
                "post_refuse_fill_peak": post_refuse_peak,
                "refuse_total": stats["refuse_total"],
                "packed_dispatches": stats["packed_dispatches"],
                "fill_aggregate": stats["packed_fill"],
                "supersteps_served": stats["supersteps_served"],
            }
        finally:
            engine.close()

    refused = arm(True)
    control = arm(False)
    if refused["refuse_total"] == 0:
        raise SystemExit(
            "--pack-churn re-fuse arm never retraced — half the "
            "tenants cancelling was expected to cross the threshold"
        )
    record = {
        "metric": "pack_churn_ab",
        "unit": "seconds (wall) + fill ratios",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": lanes,
        "blocks": nb,
        "words": args.words,
        "jobs": n_jobs,
        "refuse_below": args.refuse_below,
        "refuse": refused,
        "control": control,
        # §28 acceptance instruments: the control arm's fill minimum
        # shows the decay churn costs without re-fuse; the re-fuse
        # arm's recovered peak must sit back above the threshold, and
        # the serve-wall ratio shows what the retrace bought.
        "wall_ratio": control["wall_s"] / max(refused["wall_s"], 1e-9),
        "fill_recovered": refused["post_refuse_fill_peak"],
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


def run_churn_cross(args: argparse.Namespace) -> None:
    """A/B cross-group vs within-group dynamic re-fuse (PERF.md §31)
    under two-group churn: per arm, TWO sequential admission batches
    of two compatible jobs each form two fused groups on one packed
    resident Engine; after two serve rounds one member of EACH group
    cancels, leaving both groups thin at ~half fill with one survivor
    apiece — exactly the regime within-group re-fuse cannot fix (a
    lone survivor rebuilds SOLO, so packed fill never recovers) and
    the cross scope exists for: the survivors' ``pack_candidate``
    keys match, so the cross harvest merges them into one full group.
    Reports per-arm post-departure fill minimum, post-re-fuse
    recovered fill, and the refuse/refuse_cross counters;
    parity-asserts every survivor's emitted count against its own
    solo run.  One JSON line."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
    from hashcat_a5_table_generator_tpu.runtime.engine import Engine
    from hashcat_a5_table_generator_tpu.runtime.sweep import (
        Sweep,
        SweepConfig,
    )
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    dev = jax.devices()[0]
    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    if lanes % nb or nb % 2:
        raise SystemExit(
            "--churn-cross needs blocks dividing lanes and an even "
            "block count (two jobs per group)"
        )
    n_groups, per_group = 2, 2
    n_jobs = n_groups * per_group
    spec = AttackSpec(mode=args.mode, algo=args.algo)
    sub_map = get_layout(args.table).to_substitution_map()
    words = synth_wordlist(args.words)
    host_digest = HOST_DIGEST[spec.algo]
    job_digests = [
        [host_digest(b"cross-decoy-%d-%d" % (j, i)) for i in range(256)]
        for j in range(n_jobs)
    ]
    base_cfg = SweepConfig(lanes=lanes, num_blocks=nb, superstep=4)
    # One member of each group departs; its groupmate survives.
    cancelled = {0, per_group}
    survivors = [j for j in range(n_jobs) if j not in cancelled]

    solo = {}
    for j in survivors:
        res = Sweep(spec, sub_map, words, job_digests[j],
                    config=base_cfg).run_crack(resume=False)
        solo[j] = res.n_emitted

    def arm(scope: str) -> dict:
        engine = Engine(base_cfg, auto=False, pack=True,
                        refuse_below=args.refuse_below,
                        refuse_scope=scope)
        try:
            def run_pass(measured: bool) -> dict:
                handles = []
                for g in range(n_groups):
                    handles += [
                        engine.submit(spec, sub_map, words,
                                      job_digests[g * per_group + j])
                        for j in range(per_group)
                    ]
                    engine._admit()  # one staged batch = one group
                # Counters are engine-lifetime: gate this pass's
                # post-refuse peak on refuses fired DURING it, or the
                # warm pass's refuse would count the pre-cancel
                # full-fill dispatches as "recovered".
                refuse0 = engine.stats()["refuse_total"]
                t0 = time.perf_counter()
                for _ in range(2):
                    engine._serve_round()
                for j in cancelled:
                    handles[j].cancel()
                fill_min = None
                post_refuse_peak = None
                while True:
                    engine._serve_round()
                    engine._admit(wait=False)  # collect refuse builds
                    st = engine.stats()
                    if st["packed_fill_last"]:
                        f = st["packed_fill_last"]
                        if fill_min is None or f < fill_min:
                            fill_min = f
                        if st["refuse_total"] > refuse0:
                            post_refuse_peak = max(
                                post_refuse_peak or 0.0, f
                            )
                    if not st["jobs_active"]:
                        break
                wall = time.perf_counter() - t0
                for j in survivors:
                    n = handles[j].result(timeout=5).n_emitted
                    if measured and n != solo[j]:
                        raise SystemExit(
                            f"--churn-cross {scope} arm diverged from "
                            f"solo: job {j} emitted {n} vs {solo[j]} — "
                            "refusing to report fills for "
                            "non-identical work"
                        )
                return {
                    "wall_s": wall,
                    "fill_min": fill_min,
                    "post_refuse_fill_peak": post_refuse_peak,
                }
            run_pass(measured=False)  # warm: every program compiles
            out = run_pass(measured=True)
            stats = engine.stats()
            out["refuse_total"] = stats["refuse_total"]
            out["refuse_cross"] = stats["refuse_cross"]
            return out
        finally:
            engine.close()

    cross = arm("cross")
    within = arm("within")
    if cross["refuse_cross"] < 1:
        raise SystemExit(
            "--churn-cross: the cross arm never harvested across "
            f"groups ({cross}) — two thin sibling groups were expected "
            "to merge"
        )
    if within["refuse_cross"] != 0 or within["refuse_total"] == 0:
        raise SystemExit(
            f"--churn-cross: the within arm misbehaved ({within}) — "
            "it must retrace (lone survivors rebuild solo) without "
            "ever crossing groups"
        )
    record = {
        "metric": "churn_cross_ab",
        "unit": "fill ratios",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": lanes,
        "blocks": nb,
        "words": args.words,
        "groups": n_groups,
        "jobs_per_group": per_group,
        "refuse_below": args.refuse_below,
        "cross": cross,
        "within": within,
        # §31 acceptance instruments: the cross harvest merges the two
        # lone survivors back to a full-width packed program; the
        # within scope leaves them solo at the post-departure floor.
        "fill_recovered_cross": cross["post_refuse_fill_peak"],
        "fill_recovered_within": within["post_refuse_fill_peak"],
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


# --------------------------------------------------------- stride/emit A/B --


def run_pair_ab(args: argparse.Namespace) -> None:
    """A/B the pair-lane tier (K=2 candidates per hash lane, PERF.md
    §24) against K=1 on the production superstep crack contract.  Both
    arms run the SAME plan, piece schema, digest set, and launch
    geometry (``--lanes`` lanes × ``--blocks`` blocks × 16 steps)
    through ONE compiled superstep program each; they differ ONLY in
    the candidates-per-lane multiplier — the pair arm's blocks cover
    2× the candidate ranks, so a full sweep takes half the dispatches.
    Parity is enforced: both arms must emit the IDENTICAL candidate
    count per full sweep, or the bench exits nonzero.  The record
    carries per-arm hashes/s, the budget counter's ops/candidate at the
    pinned stride-128 geometry (KERNEL_BUDGETS cross-ref), and the
    fixture's pair-eligibility share."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from hashcat_a5_table_generator_tpu.models.attack import (
        block_arrays,
        digest_arrays,
        make_superstep_step,
        piece_arrays,
        plan_arrays,
        superstep_arrays,
        superstep_buffers,
        table_arrays,
    )
    from hashcat_a5_table_generator_tpu.ops.blocks import (
        make_blocks,
        superstep_index,
    )
    from hashcat_a5_table_generator_tpu.ops.packing import piece_schema_for
    from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
        _G as pallas_g,
        fused_expand_md5,
        fused_expand_suball_md5,
        k_opts_for,
        k_vals_for,
        pair_for_config,
        scalar_units_for,
    )
    from tools.graftaudit.counter import count_traced_kernel

    dev = jax.devices()[0]
    lanes = args.lanes
    nb = args.blocks if args.blocks is not None else 32
    steps = 16
    if lanes % nb:
        raise SystemExit("--pair-ab needs blocks dividing lanes")
    stride = lanes // nb
    hit_cap = 256

    spec, ct, plan, ds = _ab_crack_plan(args)
    pieces = piece_schema_for(plan, ct)
    pair_k = pair_for_config(spec, plan, pieces, block_stride=stride)
    if pair_k is None:
        raise SystemExit(
            "--pair-ab: the fixture plan is not pair-eligible "
            "(schema gate / hash-block count) — nothing to measure"
        )
    radix2 = k_opts_for(plan) == 1
    scalar_units = scalar_units_for(plan)
    p0 = plan_arrays(plan)
    p = dict(p0)
    p.update(piece_arrays(pieces))
    t = table_arrays(ct)
    d = digest_arrays(ds)
    # Device-launched candidate share of the whole variant space — the
    # pair tier covers exactly the device-swept candidates, so this IS
    # the eligibility share of the fixture when the gate passes.
    total_vars = sum(plan.n_variants)
    launched_vars = sum(
        t_ for t_, fb in zip(plan.n_variants, plan.fallback) if not fb
    )
    eligibility_share = launched_vars / max(total_vars, 1)

    def arm(pairk: "int | None") -> dict:
        rank_stride = stride * (pairk or 1)
        idx = superstep_index(plan, rank_stride)
        if idx is None:
            raise SystemExit("--pair-ab: plan not superstep-eligible")
        total_blocks = idx[2]
        sstep = make_superstep_step(
            spec, num_lanes=lanes, num_blocks=nb,
            out_width=plan.out_width, block_stride=stride, steps=steps,
            hit_cap=hit_cap, total_blocks=total_blocks,
            windowed=bool(getattr(plan, "windowed", False)),
            radix2=radix2, pieces=pieces, pair_k=pairk,
        )
        ss = superstep_arrays(plan, rank_stride, idx=idx)
        n_super = max(1, -(-total_blocks // (steps * nb)))
        bufs = superstep_buffers(hit_cap)
        out = sstep(p, t, d, ss, np.int32(0), bufs)  # warm compile
        int(out["n_emitted"])
        bufs = {"hit_word": out["hit_word"], "hit_rank": out["hit_rank"]}
        hashed, launches, sweeps = 0, 0, 0
        per_sweep = None
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < args.seconds or sweeps == 0:
            total = 0
            for si in range(n_super):
                out = sstep(p, t, d, ss, np.int32(si * steps * nb), bufs)
                total += int(out["n_emitted"])  # completion barrier
                bufs = {"hit_word": out["hit_word"],
                        "hit_rank": out["hit_rank"]}
                launches += steps
            hashed += total
            sweeps += 1
            if per_sweep is None:
                per_sweep = total
        wall = time.perf_counter() - t0
        return {
            "hashes_per_sec": hashed / wall,
            "emitted_per_sweep": per_sweep,
            "dispatches_per_sweep": n_super,
            "launches": launches,
            "sweeps": sweeps,
            "wall_s": round(wall, 3),
        }

    def kernel_ops(pairk: "int | None") -> "float | None":
        """ops/candidate at the PINNED budget geometry (stride 128 ×
        16 blocks), interpret-mode trace — device-independent, directly
        comparable to KERNEL_BUDGETS.json."""
        cstride = 128
        cnb = max(pallas_g, 16)
        rank_stride = cstride * (pairk or 1)
        batch, _, _ = make_blocks(
            plan, start_word=0, start_rank=0,
            max_variants=cnb * rank_stride, max_blocks=cnb,
            fixed_stride=rank_stride,
        )
        b = block_arrays(batch, num_blocks=cnb)
        common = dict(
            num_lanes=cnb * cstride, out_width=int(plan.out_width),
            min_substitute=spec.effective_min,
            max_substitute=spec.max_substitute, block_stride=cstride,
            k_opts=k_vals_for(plan), algo=spec.algo, interpret=True,
            scalar_units=scalar_units, pieces=pieces,
            pair=pairk is not None,
        )
        try:
            if spec.mode in ("default", "reverse"):
                fn = lambda: fused_expand_md5(  # noqa: E731
                    p0["tokens"], p0["lengths"], p0["match_pos"],
                    p0["match_len"], p0["match_radix"],
                    p0["match_val_start"], t["val_bytes"], t["val_len"],
                    b["word"], b["base"], b["count"], **common,
                )
            else:
                fn = lambda: fused_expand_suball_md5(  # noqa: E731
                    p0["tokens"], p0["lengths"], p0["pat_radix"],
                    p0["pat_val_start"], p0["seg_orig_start"],
                    p0["seg_orig_len"], p0["seg_pat"],
                    p0.get("cval_bytes", t["val_bytes"]),
                    p0.get("cval_len", t["val_len"]),
                    b["word"], b["base"], b["count"], **common,
                )
            ops, _ = count_traced_kernel(
                fn, pallas_g, cstride * (2 if pairk else 1)
            )
            return round(ops, 1)
        except Exception as e:  # pragma: no cover - config-dependent
            print(f"# [pair-ab] op count failed (pair={pairk}): {e}",
                  file=sys.stderr)
            return None

    solo = arm(None)
    pair = arm(pair_k)
    if solo["emitted_per_sweep"] != pair["emitted_per_sweep"]:
        raise SystemExit(
            f"--pair-ab parity violation: solo swept "
            f"{solo['emitted_per_sweep']} candidates, pair "
            f"{pair['emitted_per_sweep']} — the tiers must emit the "
            "identical stream"
        )
    solo["ops_per_candidate"] = kernel_ops(None)
    pair["ops_per_candidate"] = kernel_ops(pair_k)
    record = {
        "metric": "pair_lane_ab",
        "unit": "hashes/sec + ops/candidate",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "algo": args.algo,
        "mode": args.mode,
        "lanes": lanes,
        "blocks": nb,
        "words": args.words,
        "pair_k": pair_k,
        "eligibility_share": round(eligibility_share, 4),
        "solo": solo,
        "pair": pair,
        "speedup": pair["hashes_per_sec"] / max(solo["hashes_per_sec"],
                                                1e-12),
        "ops_ratio": (
            pair["ops_per_candidate"] / solo["ops_per_candidate"]
            if pair["ops_per_candidate"] and solo["ops_per_candidate"]
            else None
        ),
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


def run_stride_ab(args: argparse.Namespace) -> None:
    """A/B block stride 128 vs 256 x emission scheme perslot vs bytescan
    (PERF.md §7a ranked lever 2 / §17) on the production crack-step
    contract.  Each arm records hashes/s from a timed window AND the
    fused kernel's jaxpr-counted ops/candidate at that (stride, scheme) —
    produced by ``tools.graftaudit.counter``, the same implementation
    that pins ``KERNEL_BUDGETS.json``, so BENCH records and the budget
    gate can never quote different numbers.  One JSON line; the winner is
    the fastest measured arm, with op counts alongside so on-chip runs
    can confirm (or refute) the op model's stride-256 prediction."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import jax.numpy as jnp

    from hashcat_a5_table_generator_tpu.models.attack import (
        block_arrays,
        digest_arrays,
        make_fused_body,
        piece_arrays,
        plan_arrays,
        scalar_units_arrays,
        table_arrays,
    )
    from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
    from hashcat_a5_table_generator_tpu.ops.packing import piece_schema_for
    from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
        _G as pallas_g,
        fused_expand_md5,
        fused_expand_suball_md5,
        k_opts_for,
        k_vals_for,
        opts_for_config,
        scalar_units_for,
    )
    from hashcat_a5_table_generator_tpu.runtime.env import emit_scheme
    from tools.graftaudit.counter import count_traced_kernel

    dev = jax.devices()[0]
    lanes = args.lanes
    spec, ct, plan, ds = _ab_crack_plan(args)
    radix2 = k_opts_for(plan) == 1
    scalar_units = scalar_units_for(plan)
    schema = piece_schema_for(plan, ct)
    p0, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
    if scalar_units:
        p0.update(scalar_units_arrays(plan, ct))
    p1 = dict(p0)
    if schema is not None:
        p1.update(piece_arrays(schema))

    def kernel_ops(stride: int, pieces) -> "float | None":
        """ops/candidate of the fused kernel at (stride, scheme) — the
        KERNEL_BUDGETS counter over an interpret-mode trace (device-
        independent; NB tiny, the count normalizes per candidate)."""
        nb = max(pallas_g, 2048 // stride)
        batch, _, _ = make_blocks(
            plan, start_word=0, start_rank=0, max_variants=nb * stride,
            max_blocks=nb, fixed_stride=stride,
        )
        b = block_arrays(batch, num_blocks=nb)
        k = k_vals_for(plan)
        common = dict(
            num_lanes=nb * stride, out_width=int(plan.out_width),
            min_substitute=spec.effective_min,
            max_substitute=spec.max_substitute, block_stride=stride,
            k_opts=k, algo=spec.algo, interpret=True,
            scalar_units=scalar_units, pieces=pieces,
        )
        try:
            if spec.mode in ("default", "reverse"):
                fn = lambda: fused_expand_md5(  # noqa: E731
                    p0["tokens"], p0["lengths"], p0["match_pos"],
                    p0["match_len"], p0["match_radix"],
                    p0["match_val_start"], t["val_bytes"], t["val_len"],
                    b["word"], b["base"], b["count"], **common,
                )
            else:
                fn = lambda: fused_expand_suball_md5(  # noqa: E731
                    p0["tokens"], p0["lengths"], p0["pat_radix"],
                    p0["pat_val_start"], p0["seg_orig_start"],
                    p0["seg_orig_len"], p0["seg_pat"],
                    p0.get("cval_bytes", t["val_bytes"]),
                    p0.get("cval_len", t["val_len"]),
                    b["word"], b["base"], b["count"],
                    close_next=p0.get("close_next"),
                    close_mul=p0.get("close_mul"), **common,
                )
            ops, _ = count_traced_kernel(fn, pallas_g, stride)
            return round(ops, 1)
        except Exception as e:  # pragma: no cover - config-dependent
            print(f"# [stride-ab] op count failed at stride {stride}: {e}",
                  file=sys.stderr)
            return None

    def time_arm(stride: int, pieces, parr) -> dict:
        """Timed window on the production crack-step contract (hit_bits +
        BOTH counts chained device-side: an emitted-only accumulator lets
        XLA DCE the membership stage — the §15 honesty trap)."""
        if lanes % stride:
            return {"error": f"lanes {lanes} not divisible by {stride}"}
        nb = lanes // stride
        fused = opts_for_config(spec, plan, ct, block_stride=stride,
                                num_blocks=nb)
        body = make_fused_body(
            spec, num_lanes=lanes, out_width=plan.out_width,
            block_stride=stride, fused_expand_opts=fused,
            fused_scalar_units=scalar_units, radix2=radix2, pieces=pieces,
        )
        def _acc(p_, t_, b_, d_, tot):
            out = body(p_, t_, d_, b_)
            return tot + jnp.stack([out["n_emitted"], out["n_hits"]])

        acc_step = jax.jit(_acc)
        batches = []
        w, rank = 0, 0
        for _ in range(args.batches):
            batch, w, rank = make_blocks(
                plan, start_word=w, start_rank=rank, max_variants=lanes,
                max_blocks=nb, fixed_stride=stride,
            )
            if batch.total == 0:
                break
            batches.append(block_arrays(batch, num_blocks=nb))
        if not batches:
            return {"error": "wordlist produced no variant blocks"}
        zero = jnp.zeros((2,), jnp.int32)
        int(acc_step(parr, t, batches[0], d, zero)[0])  # warmup/compile
        hashed, launches = 0, 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < args.seconds:
            tot = zero
            for i in range(8):
                tot = acc_step(parr, t, batches[i % len(batches)], d, tot)
            hashed += int(tot[0])  # completion barrier
            launches += 8
        wall = time.perf_counter() - t0
        return {
            "value": hashed / wall,
            "launches": launches,
            "path": "pallas" if fused is not None else "xla",
        }

    arms = {}
    for stride in (128, 256):
        for scheme, pieces, parr in (
            ("perslot", schema, p1), ("bytescan", None, p0),
        ):
            if scheme == "perslot" and schema is None:
                continue  # plan ineligible (or A5GEN_EMIT=bytescan)
            name = f"stride{stride}-{scheme}"
            print(f"# [stride-ab] arm {name}", file=sys.stderr)
            sub = time_arm(stride, pieces, parr)
            sub["ops_per_candidate"] = kernel_ops(stride, pieces)
            arms[name] = sub

    ok = {k: v for k, v in arms.items() if "error" not in v}
    winner = max(ok, key=lambda k: ok[k]["value"]) if ok else None
    record = {
        "metric": "stride_emit_ab",
        "unit": "hashes/sec + kernel ops/candidate",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": lanes,
        "emit_default": emit_scheme(),
        "arms": arms,
        "winner": winner,
        # The ops numbers come from the SAME counter that pins these
        # budgets — cross-reference for reviewers.
        "budget_file": "KERNEL_BUDGETS.json",
    }
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()


# ----------------------------------------------------------------- worker --


# --------------------------------------------------------------- autotune --


def run_autotune_worker(args: argparse.Namespace, dev) -> None:
    """--autotune measurement body (device already initialized): sweep
    the runtime's tune matrix on the live backend, emitting one JSON
    record per completed arm — the orchestrator's last-record parsing
    then lands the newest finished arm even when the attempt is killed
    mid-matrix, and the --tune-state file lets the retry resume from
    exactly there — then a final winner record.  The winning geometry
    is persisted as this device kind's profile (PERF.md §29) unless
    A5GEN_TUNE_PROFILE=off and no --tune-profile-dir overrides it."""
    from hashcat_a5_table_generator_tpu.runtime.env import (
        tune_profile_setting,
    )
    from hashcat_a5_table_generator_tpu.runtime.tune import (
        TuneProfileCorrupt,
        run_autotune,
    )

    # The full matrix is an accelerator-window workload; CPU (the CI
    # smoke job and the orchestrator's fallback) gets the 2x2.
    smoke = dev.platform == "cpu"
    write = (
        args.tune_profile_dir is not None
        or tune_profile_setting() is not None
    )

    def on_arm(rec: dict) -> None:
        line = {
            "metric": "autotune_arm",
            "value": rec["hashes_per_s"],
            "unit": "hashes/sec",
            "vs_baseline": rec["hashes_per_s"] / NORTH_STAR,
            "platform": dev.platform,
            "device_kind": dev.device_kind,
            "arm": rec["arm"],
            "geometry": dict(rec["geometry"]),
            "emitted_per_sweep": rec["emitted_per_sweep"],
            "sweeps": rec["sweeps"],
            "partial_matrix": True,  # a final winner record follows
        }
        if rec.get("resumed"):
            line["resumed"] = True
        print(json.dumps(stamp_geometry(line, source="autotune")))
        sys.stdout.flush()
        print(f"# [tune:{rec['arm']}] {rec['hashes_per_s']:.3e} hashes/s"
              f"{' (resumed)' if rec.get('resumed') else ''}",
              file=sys.stderr)

    try:
        res = run_autotune(
            seconds=args.seconds,
            smoke=smoke,
            state_path=args.tune_state,
            on_arm=on_arm,
            write=write,
            directory=args.tune_profile_dir,
        )
    except (TuneProfileCorrupt, RuntimeError, ValueError) as e:
        print(json.dumps(stamp_geometry(
            error_record(args.algo, f"autotune: {e}"), source="autotune",
        )))
        sys.stdout.flush()
        raise SystemExit(1)
    record = {
        "metric": "autotune_matrix",
        "value": res["hashes_per_s"],
        "unit": "hashes/sec",
        "vs_baseline": res["hashes_per_s"] / NORTH_STAR,
        "platform": dev.platform,
        "device_kind": res["device_kind"],
        "arm": res["winner"],
        "arms_measured": len(res["arms"]),
        "geometry": dict(res["geometry"]),
        "emitted_per_sweep": res["emitted_per_sweep"],
        "profile_path": res["profile_path"],
        "smoke": smoke,
    }
    print(json.dumps(stamp_geometry(record, source="autotune")))
    sys.stdout.flush()
    if not args.worker and args.compare_last_tpu:
        compare_last_tpu(record["value"])


def run_worker(args: argparse.Namespace) -> None:
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    # Probe device init on a daemon thread; if it does not come up in time,
    # abort — the hung init holds backend locks, so an in-process retry on
    # another platform would deadlock.  The orchestrator handles retries.
    import threading

    init_ok = threading.Event()

    def _probe():
        try:
            jax.devices()
            init_ok.set()
        except Exception as e:  # pragma: no cover - backend-dependent
            print(f"# accelerator init failed: {e}", file=sys.stderr)

    probe = threading.Thread(target=_probe, daemon=True)
    probe.start()
    probe.join(args.init_timeout)
    if not init_ok.is_set():
        print(
            f"# accelerator init did not complete in {args.init_timeout}s",
            file=sys.stderr,
        )
        if not args.worker:
            # Direct (--platform) invocation: no orchestrator above us to
            # emit the record, so keep the one-JSON-line contract here.
            print(json.dumps(stamp_geometry(
                error_record(args.algo, "accelerator init timeout")
            )))
            sys.stdout.flush()
        sys.stderr.flush()
        os._exit(2)

    from hashcat_a5_table_generator_tpu.models.attack import (
        AttackSpec,
        block_arrays,
        build_plan,
        digest_arrays,
        make_fused_body,
        plan_arrays,
        table_arrays,
    )
    from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
    from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
    from hashcat_a5_table_generator_tpu.ops.packing import pack_words
    from hashcat_a5_table_generator_tpu.tables.compile import compile_table
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    if args.lanes > (1 << 30):
        # Two launches must fit the device-side int32 count accumulator.
        raise SystemExit("--lanes above 2^30 would overflow the int32 "
                         "emitted-count accumulator")

    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    if args.autotune:
        run_autotune_worker(args, dev)
        return

    # The kernel bench honors the autotune profile exactly like the
    # production sweep (PERF.md §29): geometry the user left to the
    # defaults is filled from this device kind's profile when one
    # exists (the device kind is only known here, past init).
    if (args.geometry_source or "explicit") != "explicit":
        from hashcat_a5_table_generator_tpu.runtime.tune import load_profile

        geom = (load_profile(dev.device_kind) or {}).get("geometry") or {}
        if geom.get("lanes"):
            args.lanes = int(geom["lanes"])
            if args.blocks is None and geom.get("num_blocks"):
                args.blocks = int(geom["num_blocks"])
            args.geometry_source = "profile"
            print(
                f"# geometry from autotune profile ({dev.device_kind}): "
                f"{args.lanes} lanes x {args.blocks or 'auto'} blocks",
                file=sys.stderr,
            )

    spec = AttackSpec(mode=args.mode, algo=args.algo)
    sub_map = get_layout(args.table).to_substitution_map()
    ct = compile_table(sub_map)
    words = synth_wordlist(args.words)
    packed = pack_words(words)
    plan = build_plan(spec, ct, packed)
    host_digest = HOST_DIGEST[spec.algo]
    targets = [host_digest(b"bench-decoy-%d" % i) for i in range(1024)]
    ds = build_digest_set(targets, spec.algo)

    # Block layout: one rule owned by the sweep runtime (the bench must
    # measure the same layout the real sweep executes): fixed-stride
    # whenever the block count divides lanes evenly (arithmetic
    # lane->block map; faster on every backend — PERF.md §4c), else packed.
    # With --blocks unset, each arm gets its own measured-best geometry
    # (PERF.md §9b/§11: the XLA arm peaks at stride 128; the fused
    # kernel's general path at stride 512 — 256 for suball — while the
    # K=1 scalar-units path peaks back at stride 128, where fill is
    # highest, because §11 removed most of the per-block cost that made
    # big strides pay).  A shared geometry would handicap one arm and
    # misreport the winner.
    from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
        scalar_units_for,
    )
    from hashcat_a5_table_generator_tpu.runtime.sweep import SweepConfig

    def arm_geometry(arm_name: str) -> "tuple[int, int | None]":
        """(num_blocks, stride | None=packed) for one arm."""
        if args.blocks is not None:
            nb = args.blocks
        elif args.block_layout == "packed":
            nb = max(1, args.lanes // 128)
        elif arm_name == "pallas":
            if scalar_units_for(plan):
                pref = 128
            else:
                pref = 256 if args.mode.startswith("suball") else 512
            if args.lanes % pref == 0:
                nb = args.lanes // pref
            else:
                nb = max(1, args.lanes // 128)
        else:
            nb = max(1, args.lanes // 128) if args.lanes % 128 == 0 else 1024
        stride = SweepConfig(
            lanes=args.lanes,
            num_blocks=nb,
            packed_blocks={"auto": None, "packed": True, "stride": False}[
                args.block_layout
            ],
        ).resolve_block_stride()
        return nb, stride

    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
    # The pallas arm's kernel takes the scalar-units fast path (PERF.md
    # §11) exactly as the production sweep would.
    scalar_units = scalar_units_for(plan)
    if scalar_units:
        # Word-level scalar-unit fields, precomputed once (as the sweep
        # does): the pallas arm's per-launch prep becomes row gathers.
        from hashcat_a5_table_generator_tpu.models.attack import (
            scalar_units_arrays,
        )

        p.update(scalar_units_arrays(plan, ct))

    # Pre-cut real blocks from the sweep's head (host cost excluded: the
    # sweep runtime overlaps cutting with device execution), cached per
    # geometry — both arms share a cut when their geometries agree.
    _batch_cache: dict = {}

    def batches_for(nb: int, stride: "int | None") -> list:
        key = (nb, stride)
        if key not in _batch_cache:
            batches = []
            w, rank = 0, 0
            for _ in range(args.batches):
                batch, w, rank = make_blocks(
                    plan, start_word=w, start_rank=rank,
                    max_variants=args.lanes, max_blocks=nb,
                    fixed_stride=stride,
                )
                if batch.total == 0:
                    break
                batches.append(block_arrays(batch, num_blocks=nb))
            if not batches:
                raise SystemExit("wordlist produced no variant blocks")
            _batch_cache[key] = batches
        return _batch_cache[key]

    # Every sync below is a device->host SCALAR fetch (``int(...)`` on the
    # emitted count): on the axon TPU tunnel ``jax.block_until_ready`` can
    # return before the computation retires, which is how r3's timed loop
    # dispatched unboundedly and blew the orchestrator deadline (VERDICT r3
    # weak #2). A scalar fetch is an honest completion barrier everywhere.
    #
    # `n_emitted` excludes min-window misses (e.g. default mode's rank-0
    # no-substitution variant) and overlap-clash lanes — only emitted lanes
    # are hashed candidates, so only they count.
    #
    # The fetch itself costs a full tunnel round trip (~65 ms measured —
    # ~5x the device time of a 2^19-lane launch), so the timed loop chains
    # per-launch emitted counts into a DEVICE-side int32 accumulator and
    # fetches it once per chunk: in-flight work is bounded by the chunk
    # length (the chunk fetch is a completion barrier over its whole
    # chain), while the round trip amortizes across the chunk.
    import jax.numpy as jnp

    from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
        k_opts_for,
        opts_for_config,
    )

    # K=1 tables: the XLA arm's decode collapses to bit extraction.
    radix2 = k_opts_for(plan) == 1
    zero = jnp.zeros((), jnp.int32)

    def time_arm(arm_name: str, fused_opts, nb: int,
                 stride: "int | None") -> dict:
        """Warm up, size chunks, and run the timed window for one arm
        (fused_opts=None -> XLA expand+hash pair; K -> Pallas kernel)."""
        print(f"# [{arm_name}] geometry: {args.lanes} lanes x {nb} blocks "
              f"({'packed' if stride is None else f'stride {stride}'})",
              file=sys.stderr)
        batches = batches_for(nb, stride)
        body = make_fused_body(spec, num_lanes=args.lanes,
                               out_width=plan.out_width, block_stride=stride,
                               fused_expand_opts=fused_opts,
                               fused_scalar_units=scalar_units,
                               radix2=radix2)
        acc_step = jax.jit(
            lambda p_, t_, b_, d_, tot:
                tot + body(p_, t_, d_, b_)["n_emitted"]
        )

        t0 = time.perf_counter()
        int(acc_step(p, t, batches[0], d, zero))
        print(f"# [{arm_name}] warmup (incl. compile): "
              f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)

        # One steady-state launch (fetch included) sizes the chunk so each
        # chunk retires in ~2 s of wall clock; per-launch time inside a
        # chunk is lower than this estimate (no per-launch round trip), so
        # chunks only ever finish faster than sized. int32 safety: the
        # device accumulator counts <= lanes per launch, so the cap scales
        # with the geometry (256 at 2^22 lanes; far higher for the small
        # CPU-fallback launches, whose fetch overhead otherwise dominates).
        t0 = time.perf_counter()
        int(acc_step(p, t, batches[1 % len(batches)], d, zero))
        per_launch = time.perf_counter() - t0
        # 1024 absolute ceiling: the hard guard below only fires at chunk
        # boundaries, so a chunk mis-sized by a fast sizing launch must
        # stay within the guard's patience even at a ~100x steady-state
        # slowdown (the r3 failure mode).
        int32_cap = ((1 << 31) - 1) // max(args.lanes, 1)
        chunk = max(2, min(int32_cap, 1024,
                           int(2.0 / max(per_launch, 1e-4))))
        print(f"# [{arm_name}] sized chunks: {per_launch:.3f}s/launch -> "
              f"{chunk}/chunk", file=sys.stderr)

        from contextlib import nullcontext

        trace_ctx = nullcontext()
        if args.profile_dir:
            from jax import profiler as _profiler

            trace_ctx = _profiler.trace(
                os.path.join(args.profile_dir, arm_name)
            )

        hashed = 0
        launches = 0
        with trace_ctx:
            start = time.perf_counter()
            # Hard guard: if chunks run slower than the sizing launch
            # suggested, stop at a chunk boundary and report a partial
            # window rather than dying on the orchestrator's knife (r3's
            # failure mode). Only fetched chunks are counted.
            guard = start + max(3 * args.seconds, args.seconds + 30.0)
            i = 0
            guard_tripped = False
            while True:
                total = zero
                for _ in range(chunk):
                    total = acc_step(
                        p, t, batches[i % len(batches)], d, total
                    )
                    i += 1
                hashed += int(total)  # completion barrier for the chain
                launches += chunk
                now = time.perf_counter()
                guard_tripped = now > guard
                if now - start >= args.seconds or guard_tripped:
                    break
            elapsed = time.perf_counter() - start

        value = hashed / elapsed
        print(f"# [{arm_name}] {launches} launches, {hashed:.3e} hashes, "
              f"{elapsed:.2f}s -> {value:.3e} hashes/s", file=sys.stderr)
        sub = {
            "value": value,
            "launches": launches,
            "per_launch_s": round(elapsed / max(launches, 1), 4),
            "blocks": nb,
        }
        if fused_opts is not None:
            # Which kernel tier actually ran (PERF.md §11): the scalar
            # fast path engages for K=1 plans, full-enumeration and
            # count-windowed alike.
            sub["kernel"] = (
                "scalar-single" if scalar_units == "single"
                else "scalar-bitmask" if scalar_units
                else "general"
            )
        if guard_tripped:
            sub["partial"] = True  # chunks ran far slower than sized
        return sub

    # Arm selection: time both the XLA pair and the fused Pallas kernel
    # when the config is kernel-eligible on this device (VERDICT r4 #2 —
    # the bench must measure the kernel built to beat the XLA path, not
    # just the path the env default selects), and record the winner —
    # each arm at its own geometry (arm_geometry).
    def pallas_entry():
        """('pallas', opts, nb, stride) at the arm's preferred geometry.
        Only AUTO geometry may fall back to stride 128 when the preferred
        stride is ineligible — an explicit --blocks/--block-layout request
        is timed as pinned or not at all (the arms must not silently run
        at geometries the user did not ask for)."""
        nb, stride = arm_geometry("pallas")
        geoms = [(nb, stride)]
        if args.blocks is None and args.block_layout != "packed":
            geoms.append((max(1, args.lanes // 128), 128))
        for nb_try, stride_try in geoms:
            if stride_try is None or args.lanes % max(stride_try, 1):
                continue
            opts = opts_for_config(spec, plan, ct, block_stride=stride_try,
                                   num_blocks=nb_try)
            if opts is not None:
                return ("pallas", opts, nb_try, stride_try)
        return None

    xla_nb, xla_stride = arm_geometry("xla")
    xla_entry = ("xla", None, xla_nb, xla_stride)
    pallas = pallas_entry()
    if args.arm == "xla":
        arm_plan = [xla_entry]
    elif args.arm == "pallas":
        if pallas is None:
            raise SystemExit(
                "--arm pallas: config is not kernel-eligible on this device"
            )
        arm_plan = [pallas]
    elif pallas is None:
        arm_plan = [xla_entry]
    else:
        arm_plan = [xla_entry, pallas]

    def winner_record(results: dict, partial_arms: bool) -> "dict | None":
        ok = {k: v for k, v in results.items() if "error" not in v}
        if not ok:
            return None
        winner = max(ok, key=lambda k: ok[k]["value"])
        record = {
            "metric": metric_name(args.algo),
            "value": results[winner]["value"],
            "unit": "hashes/sec",
            "vs_baseline": results[winner]["value"] / NORTH_STAR,
            "platform": dev.platform,
            "device_kind": dev.device_kind,
            "lanes": args.lanes,
            "blocks": results[winner].get("blocks", args.blocks),
            "launches": results[winner].get("launches", 0),
            "per_launch_s": results[winner].get("per_launch_s", 0.0),
            "arm": winner,
        }
        stamp_geometry(record, source=args.geometry_source)
        if results[winner].get("kernel"):
            record["kernel"] = results[winner]["kernel"]
        if args.mode != "default" or args.table != "qwerty-cyrillic":
            record["mode"] = args.mode
            record["table"] = args.table
        if results[winner].get("partial"):
            record["partial"] = True
        if len(results) > 1 or partial_arms:
            record["arms"] = results
        if partial_arms:
            record["partial_arms"] = True  # not every planned arm ran
        return record

    results: dict[str, dict] = {}
    for i, (arm_name, fused_opts, nb, arm_stride) in enumerate(arm_plan):
        try:
            results[arm_name] = time_arm(arm_name, fused_opts, nb, arm_stride)
        except Exception as e:  # pragma: no cover - backend-dependent
            # A losing arm must not sink the bench: record the failure and
            # let the other arm carry the number (the Pallas kernel's
            # first hardware runs happen *here*).
            print(f"# [{arm_name}] FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr)
            results[arm_name] = {"value": 0.0, "error": f"{e}"[:500]}
        if i + 1 < len(arm_plan):
            # Checkpoint the winner-so-far: if the orchestrator kills us
            # mid-next-arm, this line still lands a number (it parses the
            # LAST record on stdout).
            interim = winner_record(results, partial_arms=True)
            if interim is not None:
                print(json.dumps(interim))
                sys.stdout.flush()

    record = winner_record(results, partial_arms=False)
    if record is None:
        raise SystemExit("all arms failed")
    print(json.dumps(stamp_geometry(record)))
    sys.stdout.flush()
    if not args.worker and args.compare_last_tpu:
        # Verdict BEFORE the save refreshes the record it compares to.
        compare_last_tpu(record["value"])
    if not args.worker and dev.platform != "cpu":
        # Direct (--platform) accelerator run, no orchestrator above us:
        # persist the last-good on-chip record here.
        save_tpu_last(record)


# ----------------------------------------------------------- orchestrator --


#: Stderr signatures of a device-init-class transient that fired AFTER
#: the backend handshake (the ``device.init`` fault seam, a tunnel drop
#: during Sweep construction): the orchestrator treats these as
#: retryable attempts inside ``--init-retry-budget``, exactly like a
#: pre-init wedge (PERF.md §23).
_DEVICE_INIT_RE = re.compile(
    r"device\.init|Unable to initialize backend|"
    r"failed to connect to.*tpu|DEADLINE_EXCEEDED.*initialize",
    re.IGNORECASE,
)


def _attempt(argv: list[str], env: dict, init_grace: float, run_grace: float,
             max_total: float):
    """Run one worker subprocess under a dynamic deadline.

    The worker prints ``# device:`` to stderr once backend init succeeds;
    until then the deadline is ``init_grace`` (a wedged init is killed
    fast), after which it extends by ``run_grace`` (compile + timed window
    deserve their time) — capped at ``max_total`` from attempt start.
    Returns (record|None, stderr_tail, rc).  A killed/failed worker can
    still yield a record: the worker prints a full record line after EACH
    completed arm, so the last non-error record on stdout survives a kill
    during a later arm (it carries ``partial_arms: true``).
    """
    import tempfile

    # The child gets its own file objects; the parent polls via separate
    # opens of the same paths — a dup'd descriptor would share one file
    # offset with the child, and seeking it mid-write corrupts the stream.
    with tempfile.TemporaryDirectory() as td:
        out_path = os.path.join(td, "out")
        err_path = os.path.join(td, "err")
        with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
            proc = subprocess.Popen(argv, env=env, stdout=out_f, stderr=err_f)
            t0 = time.monotonic()
            deadline = t0 + init_grace
            extended = False
            killed = ""
            rc = None
            while True:
                rc = proc.poll()
                if rc is not None:
                    break
                if not extended:
                    with open(err_path) as f:
                        if "# device:" in f.read():
                            deadline = min(
                                time.monotonic() + run_grace,
                                t0 + max_total,
                            )
                            extended = True
                if time.monotonic() > deadline:
                    proc.kill()
                    proc.wait()
                    rc = -9
                    killed = (
                        f"\n# orchestrator: hard kill after "
                        f"{time.monotonic() - t0:.0f}s "
                        f"({'run' if extended else 'init'} deadline)"
                    )
                    break
                time.sleep(1.0)
        with open(out_path) as f:
            stdout = f.read()
        with open(err_path) as f:
            stderr = f.read() + killed
    tail = stderr[-2000:]
    if tail:
        print(tail, file=sys.stderr)
    # Take the LAST parseable non-error record — even when the worker was
    # killed or failed: the worker prints a full record after each
    # completed arm, so a kill during arm 2 must not discard arm 1's
    # finished measurement.
    record = None
    for line in reversed(stdout.strip().splitlines()):
        try:
            cand = json.loads(line)
        except (ValueError, TypeError):
            continue
        if isinstance(cand, dict) and "value" in cand \
                and "error" not in cand:
            record = cand
            break
    if record is not None and rc != 0:
        record["worker_rc"] = rc
    return record, tail, rc, extended or "# device:" in stderr, \
        time.monotonic() - t0


def run_orchestrator(args: argparse.Namespace) -> None:
    me = os.path.abspath(__file__)

    if args.autotune and not args.tune_state:
        # The partial-matrix resume seam (PERF.md §29): every retry
        # attempt — init flake or a mid-matrix kill — is a fresh
        # subprocess that picks up from the last completed arm.
        import tempfile

        args.tune_state = os.path.join(
            tempfile.gettempdir(), f"a5gen-tune-state-{os.getpid()}.json"
        )

    def worker_args(init_timeout: float, platform: str | None = None,
                    arm: str | None = None,
                    geometry_source: str | None = None, **overrides):
        vals = {
            "lanes": args.lanes, "blocks": args.blocks, "words": args.words,
            "seconds": args.seconds, "batches": args.batches,
        }
        vals.update(overrides)
        out = [
            "--lanes", str(vals["lanes"]),
            "--words", str(vals["words"]),
            "--seconds", str(vals["seconds"]),
            "--batches", str(vals["batches"]), "--algo", args.algo,
            "--mode", args.mode, "--table", args.table,
            "--init-timeout", str(init_timeout),
            "--block-layout", args.block_layout, "--arm", arm or args.arm,
        ]
        if vals["blocks"] is not None:  # None = per-arm auto geometry
            out += ["--blocks", str(vals["blocks"])]
        if platform:
            out += ["--platform", platform]
        if args.profile_dir:
            out += ["--profile-dir", args.profile_dir]
        src = geometry_source or args.geometry_source
        if src:
            out += ["--geometry-source", src]
        if args.autotune:
            out += ["--autotune"]
            if args.tune_state:
                out += ["--tune-state", args.tune_state]
            if args.tune_profile_dir:
                out += ["--tune-profile-dir", args.tune_profile_dir]
        return out

    # CPU fallback gets host-sized shapes: the full accelerator geometry
    # (2^22 lanes × 32768 blocks) takes minutes per launch on a host core.
    cpu_args = worker_args(
        60, platform="cpu", geometry_source="explicit",
        lanes=min(args.lanes, 2048),
        blocks=32 if args.blocks is None else min(args.blocks, 32),
        words=min(args.words, 4000),
        seconds=min(args.seconds, 8.0),
        batches=min(args.batches, 4),
    )

    # Budget: the whole orchestration must land a number inside the
    # driver's patience (--wall-budget, default 540s).  Per attempt,
    # init_grace is the time the backend gets to come up; only once init
    # *succeeds* (the worker prints '# device:') does the deadline extend
    # for compile + the timed window — and a successful init may spend the
    # CPU reserve too (the fallback is moot once a device is up).
    #
    # The axon tunnel is a known *transient* wedge (it ate the r3 window
    # and the r4 snapshot): one try is not a diagnosis.  So accelerator
    # attempts RETRY with backoff — fresh subprocess each time — for as
    # long as the budget allows, reserving only the tail the CPU fallback
    # needs; every attempt's stderr tail is recorded (VERDICT r4 #1).
    # Two compiles + two warmups + two timed windows when both arms run.
    run_grace = 420.0 + 2 * args.seconds
    cpu_need = 90 + 60 + 30  # cpu init grace + compile/run + slack
    # A post-init accelerator attempt may run long — but never into the
    # CPU fallback's guaranteed tail (a failing post-init run must still
    # leave enough budget to land SOME number).
    cpu_tail = float(cpu_need)
    total_deadline = time.monotonic() + args.wall_budget

    def try_one(name, extra, init_grace, max_total):
        """One capped attempt; returns the record (NOT printed — the
        caller may still merge in a completion attempt) or logs the
        failure and returns None."""
        env = dict(os.environ)
        argv = [sys.executable, me, "--worker"] + extra
        print(f"# attempt[{name}]: {' '.join(argv[2:])}", file=sys.stderr)
        record, tail, rc, init_ok, wall_s = _attempt(
            argv, env, init_grace, run_grace, max_total=max_total,
        )
        attempts[0] += 1
        # A ``device.init``-class failure AFTER backend init (the
        # PERF.md §23 seam: Sweep construction flakes, tunnel drops
        # mid-handshake) is the same transient as a pre-init wedge —
        # it counts toward the SAME init-retry budget and the loop
        # retries it as an attempt, never emits it as a dead record.
        init_flake = not init_ok or (
            record is None and _DEVICE_INIT_RE.search(tail) is not None
        )
        if init_flake:
            init_wait[0] += wall_s
            # The r01-r05 init-flake pattern as a queryable registry
            # signal (PERF.md §23), not just buried failed_attempts
            # JSON: every attempt that never initialized counts, with
            # its burnt wall.
            from hashcat_a5_table_generator_tpu.runtime import telemetry

            telemetry.counter("bench.init_retries").add(1)
            telemetry.counter("bench.init_wall_s").add(wall_s)
        if record is not None:
            record["attempt"] = name
            return record
        failures.append({"attempt": name, "rc": rc, "init_ok": init_ok,
                         "init_flake": bool(init_flake),
                         "wall_s": round(wall_s, 1),
                         "stderr_tail": tail[-600:]})
        return None

    def arm_entry(rec):
        """One record's winner as an `arms`-style sub-record."""
        return {
            "value": rec["value"],
            "launches": rec.get("launches", 0),
            "per_launch_s": rec.get("per_launch_s", 0.0),
        }

    def emit(record):
        # Registry-derived init-flake summary on the emitted record:
        # the counters are the queryable signal, these fields make the
        # artifact self-describing (PERF.md §23).  ``attempts`` makes a
        # flaky session diagnosable from the record alone: how many
        # subprocesses it took to land this number.
        from hashcat_a5_table_generator_tpu.runtime import telemetry

        record["attempts"] = attempts[0]
        retries = int(telemetry.counter("bench.init_retries").value)
        if retries:
            record["init_retries"] = retries
            record["init_wall_s"] = round(
                float(telemetry.counter("bench.init_wall_s").value), 1
            )
        if args.compare_last_tpu:
            # Verdict BEFORE the save refreshes the record it compares
            # to (stderr; the JSON record line stays the only stdout).
            compare_last_tpu(record.get("value"))
        if record.get("platform") and record["platform"] != "cpu":
            # A live accelerator measurement: refresh the committed
            # last-good record — unless it is an autotune-matrix or
            # partial record, whose metric is a different contract
            # (full-sweep rate / one arm) than the committed
            # kernel-arm number.
            if not record.get("partial_matrix") \
                    and record.get("metric") != "autotune_matrix":
                save_tpu_last(record)
        else:
            # CPU fallback carried the number: embed the last on-chip
            # measurement so the artifact keeps TPU evidence.
            attach_tpu_evidence(record)
        if failures:
            record["failed_attempts"] = failures
        print(json.dumps(stamp_geometry(record)))

    def complete_arms(record):
        """A kill mid-pallas-arm leaves a partial_arms record (xla only).
        When budget remains, run a pallas-ONLY attempt — the persistent
        compilation cache makes the retry's compile cheap — and merge, so
        the fused kernel still gets measured (VERDICT r4 #2)."""
        if not record.get("partial_arms") or args.arm != "auto":
            return record
        remaining = total_deadline - time.monotonic()
        if remaining - cpu_tail < 120:
            return record
        print("# orchestrator: completing unmeasured pallas arm",
              file=sys.stderr)
        rec2 = try_one(
            "accelerator-pallas",
            worker_args(args.init_timeout, arm="pallas"),
            min(args.init_timeout + 30, remaining - cpu_tail),
            total_deadline - time.monotonic() - 60,
        )
        if rec2 is None:
            return record
        arms = dict(record.get("arms") or {record["arm"]: arm_entry(record)})
        arms.update(rec2.get("arms")
                    or {rec2["arm"]: arm_entry(rec2)})
        ok = {k: v for k, v in arms.items() if "error" not in v}
        winner = max(ok, key=lambda k: ok[k]["value"])
        merged = dict(record)
        merged.update({
            "value": arms[winner]["value"],
            "vs_baseline": arms[winner]["value"] / NORTH_STAR,
            "launches": arms[winner].get("launches", 0),
            "per_launch_s": arms[winner].get("per_launch_s", 0.0),
            "arm": winner,
            "arms": arms,
        })
        merged.pop("partial_arms", None)
        merged["arms_completed_by_retry"] = True
        return merged

    def complete_matrix(record):
        """--autotune: a kill mid-matrix lands the newest finished arm
        (partial_matrix).  While budget remains, retry — the worker
        resumes from the --tune-state file, skipping every completed
        arm — so the full matrix lands unattended inside the same
        retry budget (PERF.md §29)."""
        while record.get("partial_matrix"):
            remaining = total_deadline - time.monotonic()
            if remaining - cpu_tail < 120:
                record["matrix_incomplete"] = True
                break
            print("# orchestrator: resuming autotune matrix from "
                  f"{args.tune_state}", file=sys.stderr)
            rec2 = try_one(
                "accelerator-tune-resume",
                worker_args(args.init_timeout),
                min(args.init_timeout + 30, remaining - cpu_tail),
                total_deadline - time.monotonic() - cpu_tail,
            )
            if rec2 is None:
                record["matrix_incomplete"] = True
                break
            record = rec2
        return record

    def complete(record):
        return (complete_matrix(record) if args.autotune
                else complete_arms(record))

    failures = []
    attempts = [0]  # total subprocess attempts (emitted per record)
    init_wait = [0.0]  # cumulative wall burnt on attempts that never init'd
    tried_tpu_plugin = False
    backoff = 10.0
    while True:
        remaining = total_deadline - time.monotonic()
        spendable = remaining - cpu_need
        if spendable < 75:
            break
        if init_wait[0] >= args.init_retry_budget:
            # The backend never even initialized across this much wall:
            # stop feeding the wedge and leave the rest of the budget to
            # the CPU fallback (BENCH_r05 burned ~6 min here).
            print(f"# orchestrator: init-retry budget exhausted "
                  f"({init_wait[0]:.0f}s >= {args.init_retry_budget:.0f}s); "
                  "taking the CPU fallback", file=sys.stderr)
            break
        # Default platform resolution (the axon TPU tunnel, when present).
        # A wedged init is killed at init_grace; a successful init may run
        # up to the CPU fallback's guaranteed tail.
        init_grace = min(args.init_timeout + 30, spendable)
        rec = try_one("accelerator",
                      worker_args(min(args.init_timeout, init_grace - 15)),
                      init_grace,
                      total_deadline - time.monotonic() - cpu_tail)
        if rec is not None:
            emit(complete(rec))
            return
        # Explicit tpu plugin: if axon is wedged but a local libtpu chip
        # exists this comes up fast; if neither exists it errors fast —
        # so one try settles it for the whole run.
        if not tried_tpu_plugin:
            tried_tpu_plugin = True
            if total_deadline - time.monotonic() - cpu_need >= 75:
                rec = try_one("tpu", worker_args(45, platform="tpu"), 75,
                              total_deadline - time.monotonic() - cpu_tail)
                if rec is not None:
                    emit(complete(rec))
                    return
        # Tunnel down: back off briefly, then retry a fresh subprocess.
        sleep_s = min(backoff,
                      max(0.0, total_deadline - time.monotonic() - cpu_need))
        if sleep_s > 0:
            print(f"# orchestrator: accelerator down, retrying in "
                  f"{sleep_s:.0f}s", file=sys.stderr)
            time.sleep(sleep_s)
        backoff = min(backoff * 2, 60.0)

    rec = try_one("cpu-fallback", cpu_args, 90,
                  max(60.0, total_deadline - time.monotonic() - 5))
    if rec is not None:
        emit(rec)
        return

    print(json.dumps(stamp_geometry(attach_tpu_evidence(error_record(
        args.algo, "all platform attempts failed", failed_attempts=failures,
    )))))
    sys.exit(2)


def main() -> None:
    global GEOMETRY_SOURCE

    args = _build_bench_parser().parse_args()
    ab_mode = (args.superstep_ab or args.stride_ab or args.pipeline_ab
               or args.stream_ab or args.serve_ab or args.telemetry_ab
               or args.pack_ab or args.pack_churn or args.pair_ab
               or args.fleet_ab or args.elastic_ab or args.split_ab
               or args.churn_cross)
    if args.compare_last_tpu and not (
        ab_mode or args.autotune or args.worker or args.platform
    ):
        # Standalone verdict: report the committed record vs the north
        # star and exit — no measurement.
        compare_last_tpu()
        return
    if args.geometry_source is None:
        # Unset-vs-explicit is the geometry-provenance seam (PERF.md
        # §29): workers fill "default" geometry from the device kind's
        # autotune profile once init reveals the device.
        args.geometry_source = (
            "explicit" if args.lanes is not None else "default"
        )
    GEOMETRY_SOURCE = args.geometry_source
    if args.seconds is None:
        # --autotune's window is PER ARM; the matrix has dozens.
        args.seconds = 2.0 if args.autotune else 10.0
    if args.lanes is None:
        # Unset vs explicit matters: the focused A/B modes target small
        # geometries, the kernel bench the big accelerator launch; an
        # explicit --lanes is honored by all.
        args.lanes = 2048 if ab_mode else (1 << 22)
    if args.words is None:
        # --serve-ab's contract is N equal SMALL jobs (compile-dominant
        # — the regime the resident engine amortizes); --pack-ab's is N
        # UNDERFILLED jobs (dispatch-dominant — the regime packing
        # amortizes); everything else keeps the historical default.
        # --pack-ab wants UNDERFILLED jobs: each job's whole block range
        # is a fraction of one superstep's lane capacity at the §4c
        # geometry — the regime cross-job packing amortizes (PERF.md
        # §22).
        # --pack-churn needs jobs LONG enough that work remains after
        # the mid-flight cancels (several supersteps per tenant), so
        # its default is larger than --pack-ab's underfilled 24.
        # --split-ab's contract is ONE OVERSIZED job (the striping
        # regime — per-shard sweep work must dwarf scatter + merge);
        # --churn-cross reuses --pack-churn's long-tenant sizing.
        args.words = (
            1000 if (args.serve_ab or args.fleet_ab or args.elastic_ab)
            else 24 if args.pack_ab
            else 2000 if (args.pack_churn or args.churn_cross)
            else 20000 if args.split_ab else 50000
        )
    if args.fleet_ab or args.elastic_ab:
        # Routed-vs-direct serve A/B (PERF.md §25), with the elastic
        # tier armed on the routed arm under --elastic-ab (PERF.md
        # §27); spawns engine subprocesses — no jax in this process.
        run_fleet_ab(args, elastic=args.elastic_ab)
    elif args.split_ab:
        # Giant-job striping A/B (PERF.md §31); spawns engine
        # subprocesses — no jax in this process.
        run_split_ab(args)
    elif args.churn_cross:
        # Cross-group vs within-group re-fuse A/B (PERF.md §31); runs
        # on the pinned (or default) platform in-process.
        run_churn_cross(args)
    elif args.pair_ab:
        # Pair-lane tier A/B (PERF.md §24); runs on the pinned (or
        # default) platform in-process.
        run_pair_ab(args)
    elif args.pack_ab:
        # Cross-job packing A/B (PERF.md §22); runs on the pinned (or
        # default) platform in-process.
        run_pack_ab(args)
    elif args.pack_churn:
        # Dynamic re-fuse churn A/B (PERF.md §28); runs on the pinned
        # (or default) platform in-process.
        run_pack_churn(args)
    elif args.telemetry_ab:
        # Telemetry-overhead A/B (PERF.md §21); runs on the pinned (or
        # default) platform in-process.
        run_telemetry_ab(args)
    elif args.serve_ab:
        # Resident-engine service-mode A/B (PERF.md §20); runs on the
        # pinned (or default) platform in-process.
        run_serve_ab(args)
    elif args.stream_ab:
        # Streaming-ingestion A/B (PERF.md §19); runs on the pinned (or
        # default) platform in-process.
        run_stream_ab(args)
    elif args.pipeline_ab:
        run_pipeline_ab(args)
    elif args.stride_ab:
        # Focused stride/emission A/B (PERF.md §7a lever 2 / §17); runs
        # on the pinned (or default) platform in-process.
        run_stride_ab(args)
    elif args.superstep_ab:
        # Focused loop-level A/B (PERF.md §15); runs on the pinned (or
        # default) platform in-process, no orchestrator.
        run_superstep_ab(args)
    elif args.worker or args.platform:
        # --worker: orchestrator subprocess.  --platform: the user pinned a
        # backend — run in-process at the requested geometry with no kill
        # deadline (the init-timeout abort still guards a wedged init).
        run_worker(args)
    else:
        run_orchestrator(args)


if __name__ == "__main__":
    main()

"""Benchmark: fused expand→MD5→membership throughput on one chip.

The headline config from ``BASELINE.json`` configs[2]: a rockyou-class
wordlist × qwerty-cyrillic, default mode, MD5 — candidates expanded, hashed
and membership-tested entirely on device. The reference publishes no numbers
(``BASELINE.md``); the target is the north star ≥1e10 candidate-hashes/sec
per chip, so ``vs_baseline`` is value / 1e10.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "hashes/sec", "vs_baseline": N}

Two-level structure (the accelerator backend in this environment — the axon
TPU tunnel — can wedge *forever* inside backend init, and a wedged init
thread cannot be killed in-process):

- **Orchestrator** (default entry): runs the measurement as a *subprocess*
  per platform attempt — default resolution (the axon tunnel), then the
  explicit ``tpu`` plugin, then a CPU fallback sized for host execution —
  each under a hard kill-timeout, all under one total wall-clock budget.
  Emits exactly one JSON line: the first successful attempt's record,
  augmented with the platform used and the stderr tails of failed attempts
  (so a wedge is diagnosable, not a bare timeout).  Exits 2 if every
  attempt failed (the error record is still printed).
- **Worker** (``--worker``): the actual timed loop.  Probes device init on a
  daemon thread with its own timeout and aborts with rc=2 if init never
  completes (``os._exit`` — the wedged thread holds backend locks).

Steady-state methodology: pre-cut real variant blocks for the sweep's head,
warm up (compile), then cycle the pre-cut batches for a fixed wall-clock
window, counting device-reported emitted candidates (each emitted candidate
is exactly one MD5). Host block-cutting is excluded from the timed loop —
in the sweep runtime it overlaps device execution (double-buffered feeds).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

NORTH_STAR = 1e10  # hashes/sec/chip target, BASELINE.json / BASELINE.md


def metric_name(algo: str) -> str:
    return f"{algo}_candidate_hashes_per_sec_per_chip"


def error_record(algo: str, error: str, **extra) -> dict:
    rec = {
        "metric": metric_name(algo),
        "value": 0.0,
        "unit": "hashes/sec",
        "vs_baseline": 0.0,
        "error": error,
    }
    rec.update(extra)
    return rec


def synth_wordlist(n: int, seed: int = 0):
    """Deterministic rockyou-like wordlist: lowercase stems + digit tails."""
    import numpy as np

    rng = np.random.default_rng(seed)
    stems = rng.integers(ord("a"), ord("z") + 1, size=(n, 10), dtype=np.uint8)
    lens = rng.integers(6, 11, size=n)
    digits = rng.integers(0, 3, size=n)  # 0-2 trailing digits
    words = []
    for i in range(n):
        w = bytes(stems[i, : lens[i]])
        if digits[i]:
            w = w[: -digits[i]] + b"123"[: digits[i]]
        words.append(w)
    return words


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--lanes", type=int, default=1 << 22,
                    help="variant lanes per launch")
    ap.add_argument("--blocks", type=int, default=32768,
                    help="static block count per launch")
    ap.add_argument("--words", type=int, default=50000,
                    help="synthetic wordlist size")
    ap.add_argument("--seconds", type=float, default=10.0,
                    help="timed-window length")
    ap.add_argument("--batches", type=int, default=8,
                    help="distinct pre-cut batches to cycle")
    ap.add_argument("--algo", default="md5", help="hash algorithm")
    ap.add_argument("--block-layout", choices=("auto", "packed", "stride"),
                    default="auto",
                    help="variant-block layout (same semantics as the CLI; "
                         "auto = stride whenever blocks divides lanes evenly)")
    ap.add_argument("--mode", default="default", help="attack mode")
    ap.add_argument("--init-timeout", type=float, default=150.0,
                    help="seconds the worker waits for accelerator init")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) before init")
    ap.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler trace of the timed window here")
    ap.add_argument("--worker", action="store_true",
                    help="run the measurement in this process (internal)")
    return ap


# ----------------------------------------------------------------- worker --


def run_worker(args: argparse.Namespace) -> None:
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    # Probe device init on a daemon thread; if it does not come up in time,
    # abort — the hung init holds backend locks, so an in-process retry on
    # another platform would deadlock.  The orchestrator handles retries.
    import threading

    init_ok = threading.Event()

    def _probe():
        try:
            jax.devices()
            init_ok.set()
        except Exception as e:  # pragma: no cover - backend-dependent
            print(f"# accelerator init failed: {e}", file=sys.stderr)

    probe = threading.Thread(target=_probe, daemon=True)
    probe.start()
    probe.join(args.init_timeout)
    if not init_ok.is_set():
        print(
            f"# accelerator init did not complete in {args.init_timeout}s",
            file=sys.stderr,
        )
        if not args.worker:
            # Direct (--platform) invocation: no orchestrator above us to
            # emit the record, so keep the one-JSON-line contract here.
            print(json.dumps(
                error_record(args.algo, "accelerator init timeout")
            ))
            sys.stdout.flush()
        sys.stderr.flush()
        os._exit(2)

    from hashcat_a5_table_generator_tpu.models.attack import (
        AttackSpec,
        block_arrays,
        build_plan,
        digest_arrays,
        make_fused_body,
        plan_arrays,
        table_arrays,
    )
    from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
    from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
    from hashcat_a5_table_generator_tpu.ops.packing import pack_words
    from hashcat_a5_table_generator_tpu.tables.compile import compile_table
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
    from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    spec = AttackSpec(mode=args.mode, algo=args.algo)
    sub_map = get_layout("qwerty-cyrillic").to_substitution_map()
    ct = compile_table(sub_map)
    words = synth_wordlist(args.words)
    packed = pack_words(words)
    plan = build_plan(spec, ct, packed)
    host_digest = HOST_DIGEST[spec.algo]
    targets = [host_digest(b"bench-decoy-%d" % i) for i in range(1024)]
    ds = build_digest_set(targets, spec.algo)

    # Block layout: one rule owned by the sweep runtime (the bench must
    # measure the same layout the real sweep executes): fixed-stride
    # whenever the block count divides lanes evenly (arithmetic
    # lane->block map; faster on every backend — PERF.md §4c), else packed.
    from hashcat_a5_table_generator_tpu.runtime.sweep import SweepConfig

    stride = SweepConfig(
        lanes=args.lanes,
        num_blocks=args.blocks,
        packed_blocks={"auto": None, "packed": True, "stride": False}[
            args.block_layout
        ],
    ).resolve_block_stride()
    print(f"# block layout: {'packed' if stride is None else f'stride {stride}'}",
          file=sys.stderr)
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)

    # Pre-cut real blocks from the sweep's head (host cost excluded: the
    # sweep runtime overlaps cutting with device execution).
    batches = []
    w, rank = 0, 0
    for _ in range(args.batches):
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank,
            max_variants=args.lanes, max_blocks=args.blocks,
            fixed_stride=stride,
        )
        if batch.total == 0:
            break
        batches.append(block_arrays(batch, num_blocks=args.blocks))
    if not batches:
        raise SystemExit("wordlist produced no variant blocks")

    # Every sync below is a device->host SCALAR fetch (``int(...)`` on the
    # emitted count): on the axon TPU tunnel ``jax.block_until_ready`` can
    # return before the computation retires, which is how r3's timed loop
    # dispatched unboundedly and blew the orchestrator deadline (VERDICT r3
    # weak #2). A scalar fetch is an honest completion barrier everywhere.
    #
    # `n_emitted` excludes min-window misses (e.g. default mode's rank-0
    # no-substitution variant) and overlap-clash lanes — only emitted lanes
    # are hashed candidates, so only they count.
    #
    # The fetch itself costs a full tunnel round trip (~65 ms measured —
    # ~5x the device time of a 2^19-lane launch), so the timed loop chains
    # per-launch emitted counts into a DEVICE-side int32 accumulator and
    # fetches it once per chunk: in-flight work is bounded by the chunk
    # length (the chunk fetch is a completion barrier over its whole
    # chain), while the round trip amortizes across the chunk.
    import jax.numpy as jnp

    from hashcat_a5_table_generator_tpu.ops.pallas_expand import opts_for

    fused_opts = opts_for(spec, plan, ct, block_stride=stride,
                          num_blocks=args.blocks)
    if fused_opts is not None:
        print("# fused Pallas expand+MD5 kernel enabled", file=sys.stderr)
    body = make_fused_body(spec, num_lanes=args.lanes,
                           out_width=plan.out_width, block_stride=stride,
                           fused_expand_opts=fused_opts)
    acc_step = jax.jit(
        lambda p_, t_, b_, d_, tot: tot + body(p_, t_, d_, b_)["n_emitted"]
    )
    zero = jnp.zeros((), jnp.int32)

    t0 = time.perf_counter()
    int(acc_step(p, t, batches[0], d, zero))
    print(f"# warmup (incl. compile): {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    # One steady-state launch (fetch included) sizes the chunk so each
    # chunk retires in ~2 s of wall clock; per-launch time inside a chunk
    # is lower than this estimate (no per-launch round trip), so chunks
    # only ever finish faster than sized. int32 safety: 256 launches of
    # 2^22 lanes stays under 2^31 counts.
    t0 = time.perf_counter()
    int(acc_step(p, t, batches[1 % len(batches)], d, zero))
    per_launch = time.perf_counter() - t0
    chunk = max(2, min(256, int(2.0 / max(per_launch, 1e-4))))
    print(f"# sized chunks: {per_launch:.3f}s/launch -> {chunk}/chunk",
          file=sys.stderr)

    from contextlib import nullcontext

    trace_ctx = nullcontext()
    if args.profile_dir:
        import jax.profiler

        trace_ctx = jax.profiler.trace(args.profile_dir)

    hashed = 0
    launches = 0
    with trace_ctx:
        start = time.perf_counter()
        # Hard guard: if chunks run slower than the sizing launch
        # suggested, stop at a chunk boundary and report a partial window
        # rather than dying on the orchestrator's knife (r3's failure
        # mode). Only fetched chunks are counted.
        guard = start + max(3 * args.seconds, args.seconds + 30.0)
        i = 0
        guard_tripped = False
        while True:
            total = zero
            for _ in range(chunk):
                total = acc_step(p, t, batches[i % len(batches)], d, total)
                i += 1
            hashed += int(total)  # completion barrier for the whole chain
            launches += chunk
            now = time.perf_counter()
            guard_tripped = now > guard
            if now - start >= args.seconds or guard_tripped:
                break
        elapsed = time.perf_counter() - start

    value = hashed / elapsed
    print(f"# {launches} launches, {hashed:.3e} hashes, {elapsed:.2f}s",
          file=sys.stderr)
    record = {
        "metric": metric_name(args.algo),
        "value": value,
        "unit": "hashes/sec",
        "vs_baseline": value / NORTH_STAR,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "lanes": args.lanes,
        "blocks": args.blocks,
        "launches": launches,
        "per_launch_s": round(elapsed / max(launches, 1), 4),
    }
    if guard_tripped:
        record["partial"] = True  # chunks ran far slower than sized
    print(json.dumps(record))
    sys.stdout.flush()


# ----------------------------------------------------------- orchestrator --


def _attempt(argv: list[str], env: dict, init_grace: float, run_grace: float,
             max_total: float):
    """Run one worker subprocess under a dynamic deadline.

    The worker prints ``# device:`` to stderr once backend init succeeds;
    until then the deadline is ``init_grace`` (a wedged init is killed
    fast), after which it extends by ``run_grace`` (compile + timed window
    deserve their time) — capped at ``max_total`` from attempt start, the
    attempt's share of the orchestrator's overall budget.
    Returns (record|None, stderr_tail, rc).
    """
    import tempfile

    # The child gets its own file objects; the parent polls via separate
    # opens of the same paths — a dup'd descriptor would share one file
    # offset with the child, and seeking it mid-write corrupts the stream.
    with tempfile.TemporaryDirectory() as td:
        out_path = os.path.join(td, "out")
        err_path = os.path.join(td, "err")
        with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
            proc = subprocess.Popen(argv, env=env, stdout=out_f, stderr=err_f)
            t0 = time.monotonic()
            deadline = t0 + init_grace
            extended = False
            killed = ""
            rc = None
            while True:
                rc = proc.poll()
                if rc is not None:
                    break
                if not extended:
                    with open(err_path) as f:
                        if "# device:" in f.read():
                            deadline = min(
                                time.monotonic() + run_grace,
                                t0 + max_total,
                            )
                            extended = True
                if time.monotonic() > deadline:
                    proc.kill()
                    proc.wait()
                    rc = -9
                    killed = (
                        f"\n# orchestrator: hard kill after "
                        f"{time.monotonic() - t0:.0f}s "
                        f"({'run' if extended else 'init'} deadline)"
                    )
                    break
                time.sleep(1.0)
        with open(out_path) as f:
            stdout = f.read()
        with open(err_path) as f:
            stderr = f.read() + killed
    tail = stderr[-2000:]
    if tail:
        print(tail, file=sys.stderr)
    record = None
    if rc == 0:
        for line in reversed(stdout.strip().splitlines()):
            try:
                cand = json.loads(line)
            except (ValueError, TypeError):
                continue
            if isinstance(cand, dict) and "value" in cand:
                record = cand
                break
    return record, tail, rc


def run_orchestrator(args: argparse.Namespace) -> None:
    me = os.path.abspath(__file__)

    def worker_args(init_timeout: float, platform: str | None = None,
                    **overrides):
        vals = {
            "lanes": args.lanes, "blocks": args.blocks, "words": args.words,
            "seconds": args.seconds, "batches": args.batches,
        }
        vals.update(overrides)
        out = [
            "--lanes", str(vals["lanes"]), "--blocks", str(vals["blocks"]),
            "--words", str(vals["words"]),
            "--seconds", str(vals["seconds"]),
            "--batches", str(vals["batches"]), "--algo", args.algo,
            "--mode", args.mode, "--init-timeout", str(init_timeout),
            "--block-layout", args.block_layout,
        ]
        if platform:
            out += ["--platform", platform]
        if args.profile_dir:
            out += ["--profile-dir", args.profile_dir]
        return out

    # CPU fallback gets host-sized shapes: the full accelerator geometry
    # (2^22 lanes × 32768 blocks) takes minutes per launch on a host core.
    cpu_args = worker_args(
        60, platform="cpu",
        lanes=min(args.lanes, 2048),
        blocks=min(args.blocks, 32),
        words=min(args.words, 4000),
        seconds=min(args.seconds, 8.0),
        batches=min(args.batches, 4),
    )

    # Budget: the whole orchestration must land a number well inside the
    # driver's patience (~10 min).  Per attempt, init_grace is the time the
    # backend gets to come up; only once init *succeeds* (the worker prints
    # '# device:') does the deadline extend for compile + the timed window.
    # One shared wall-clock budget bounds the sum of attempts, always
    # reserving enough tail for the CPU fallback to complete.
    run_grace = 240.0 + args.seconds  # first TPU compile can take minutes
    cpu_need = 90 + 60 + 30  # cpu init grace + compile/run + slack
    total_deadline = time.monotonic() + 540.0
    attempts = [
        # Default platform resolution (the axon TPU tunnel, when present).
        ("accelerator", worker_args(args.init_timeout),
         args.init_timeout + 30, True),
        # Explicit tpu plugin: if axon is wedged but a local libtpu chip
        # exists this comes up fast; if neither exists it errors fast.
        ("tpu", worker_args(45, platform="tpu"), 45 + 30, True),
        ("cpu-fallback", cpu_args, 90, False),
    ]

    failures = []
    for name, extra, init_grace, reserve_cpu in attempts:
        remaining = total_deadline - time.monotonic()
        spendable = remaining - (cpu_need if reserve_cpu else 0)
        if spendable < init_grace:
            failures.append({
                "attempt": name, "rc": None,
                "stderr_tail": "# orchestrator: skipped (budget exhausted)",
            })
            continue
        env = dict(os.environ)
        argv = [sys.executable, me, "--worker"] + extra
        print(f"# attempt[{name}]: {' '.join(argv[2:])}", file=sys.stderr)
        record, tail, rc = _attempt(
            argv, env, init_grace, run_grace, max_total=spendable
        )
        if record is not None:
            record["attempt"] = name
            if failures:
                record["failed_attempts"] = failures
            print(json.dumps(record))
            return
        failures.append({"attempt": name, "rc": rc, "stderr_tail": tail})

    print(json.dumps(error_record(
        args.algo, "all platform attempts failed", failed_attempts=failures,
    )))
    sys.exit(2)


def main() -> None:
    args = build_parser().parse_args()
    if args.worker or args.platform:
        # --worker: orchestrator subprocess.  --platform: the user pinned a
        # backend — run in-process at the requested geometry with no kill
        # deadline (the init-timeout abort still guards a wedged init).
        run_worker(args)
    else:
        run_orchestrator(args)


if __name__ == "__main__":
    main()

"""L5: mesh construction, shard_map pipelines, collectives, multi-host."""

from .mesh import (  # noqa: F401
    make_device_blocks,
    make_mesh,
    make_sharded_candidates_step,
    make_sharded_crack_step,
    replicate,
    shard_leading,
    stack_blocks,
)
from .multihost import (  # noqa: F401
    allgather_max,
    allgather_sum,
    gather_hits,
    host_stripe,
    initialize,
    run_candidates_multihost,
    run_crack_multihost,
    stripe_packed,
)

"""L5: mesh construction, shard_map pipelines, collectives."""

from .mesh import (  # noqa: F401
    make_device_blocks,
    make_mesh,
    make_sharded_crack_step,
    replicate,
    shard_leading,
    stack_blocks,
)

"""Sharding runtime: the TPU-native replacement for the reference's goroutine
scheduler (L5, ``main.go:70-99``).

The reference parallelizes per dictionary word (one goroutine per word behind
a counting semaphore) and serializes every candidate through one channel. The
TPU design instead shards **variant blocks** over a 1-D device mesh:

* the host block scheduler (``ops.blocks.make_blocks``) cuts each device an
  equal lane budget — per-word skew disappears because a single word's huge
  variant space splits into as many blocks as needed (the product-space
  analog of sequence/context parallelism, SURVEY.md §2.3/§5);
* plans, tables and the digest set are **replicated** (they are small and
  read-only); block descriptors and lane outputs are **sharded** on the
  leading axis;
* the only cross-device traffic is the hit/emit reduction — a `psum` over
  ICI inside ``shard_map``; hits travel as a packed per-lane bitmask
  (``models.attack.pack_bits``) fetched lazily (hits are rare);
* multi-host runs initialize ``jax.distributed`` and give each host its own
  wordlist shard (DCN never carries candidate traffic — SURVEY.md §5).

Everything here works identically on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``) — that is how the test suite
and the driver's dry-run exercise multi-chip semantics without hardware.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..audit import audited_entry
from ..models.attack import (
    AttackSpec,
    make_candidates_body,
    make_fused_body,
    make_superstep_body,
)
from ..ops.blocks import BlockBatch, make_blocks, pad_batch


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across JAX versions: promoted to the top-level
    namespace (with ``check_vma``) in newer JAX; older releases ship it as
    ``jax.experimental.shard_map`` with the equivalent ``check_rep``
    knob."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as esm

    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def make_mesh(n_devices: int | None = None, *, axis_name: str = "data") -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices (all, if None)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def make_device_blocks(
    plan,
    *,
    n_devices: int,
    lanes_per_device: int,
    start_word: int = 0,
    start_rank: int = 0,
    max_blocks: int | None = None,
    fixed_stride: int | None = None,
) -> Tuple[List[BlockBatch], int, int]:
    """Cut one launch's work: ``n_devices`` equal-budget block batches.

    Returns (batches, next_word, next_rank) — the cursor after the LAST
    device's range, so consecutive launches sweep the space contiguously.
    Devices later in the list may receive empty batches near the end of the
    sweep; those lanes are masked out by ``emit``. ``max_blocks`` caps each
    device's block count (pair with ``stack_blocks(..., num_blocks=...)`` for
    launch-to-launch jit shape stability). ``fixed_stride`` selects the
    fixed-lanes-per-block layout (``ops.blocks.make_blocks``).
    """
    batches = []
    w, rank = start_word, start_rank
    for _ in range(n_devices):
        batch, w, rank = make_blocks(
            plan,
            start_word=w,
            start_rank=rank,
            max_variants=lanes_per_device,
            max_blocks=max_blocks,
            fixed_stride=fixed_stride,
        )
        batches.append(batch)
    return batches, w, rank


def stack_blocks(
    batches: List[BlockBatch], *, num_blocks: int | None = None
) -> Dict[str, np.ndarray]:
    """Stack per-device block batches into shard_map-ready arrays.

    Batches are padded to a common block count with zero-count blocks whose
    ``offset`` continues past the end — their lanes fail ``rank < count`` and
    are masked. Returns arrays with leading axis ``n_devices * nb``.
    ``batches`` must be non-empty (one entry per mesh device).
    ``num_blocks`` forces the per-device block count (static jit shapes
    across launches); by default the largest batch sets it.
    """
    if not batches:
        raise ValueError("batches must have one entry per mesh device")
    n_slots = max(b.base_digits.shape[1] for b in batches)
    nb = num_blocks or max(1, max(len(b.count) for b in batches))
    padded = []
    for b in batches:
        b = BlockBatch(
            word=b.word,
            base_digits=np.pad(
                b.base_digits, ((0, 0), (0, n_slots - b.base_digits.shape[1]))
            ),
            count=b.count,
            offset=b.offset,
        )
        padded.append(pad_batch(b, nb))
    return {
        "word": np.concatenate([b.word for b in padded]).astype(np.int32),
        "base": np.concatenate([b.base_digits for b in padded]).astype(np.int32),
        "count": np.concatenate([b.count for b in padded]).astype(np.int32),
        "offset": np.concatenate([b.offset for b in padded]).astype(np.int32),
    }


@audited_entry(
    "parallel.make_sharded_crack_step",
    kind="sharded_body",
    stages=("expand", "hash", "membership"),
)
def make_sharded_crack_step(
    spec: AttackSpec,
    mesh: Mesh,
    *,
    lanes_per_device: int,
    out_width: int,
    axis_name: str = "data",
    block_stride: int | None = None,
    fused_expand_opts: int | None = None,
    fused_scalar_units: bool = False,
    radix2: bool = False,
    pieces=None,
    pair_k: int | None = None,
):
    """The fused crack step, shard_map'd over a 1-D mesh.

    Input pytrees: ``plan``/``table``/``digests`` replicated, ``blocks``
    sharded on the leading axis (from :func:`stack_blocks`). Returns the
    packed per-lane hit bitmask ``hit_bits`` sharded over the mesh (device
    ``d``'s lanes occupy bit-words ``[d*lanes/32, (d+1)*lanes/32)``) plus
    globally-psum'd scalar counts (replicated).
    """
    if lanes_per_device % 32:
        # Each device packs its own lanes into whole uint32 bit-words; a
        # non-multiple would misalign the concatenated global bitmask.
        raise ValueError(
            f"lanes_per_device must be a multiple of 32 (packed hit "
            f"bitmask words), got {lanes_per_device}"
        )
    body = make_fused_body(
        spec, num_lanes=lanes_per_device, out_width=out_width,
        block_stride=block_stride, fused_expand_opts=fused_expand_opts,
        fused_scalar_units=fused_scalar_units, radix2=radix2,
        pieces=pieces, pair_k=pair_k,
    )

    def local_step(plan, table, digests, blocks):
        out = body(plan, table, digests, blocks)
        # The fused body's counts are device-local; reduce them over ICI so
        # every host sees global totals without touching the per-lane masks.
        out["n_emitted"] = jax.lax.psum(out["n_emitted"], axis_name)
        out["n_hits"] = jax.lax.psum(out["n_hits"], axis_name)
        return out

    rep = P()
    shard = P(axis_name)
    mapped = _shard_map(
        local_step,
        mesh=mesh,
        in_specs=(rep, rep, rep, shard),
        out_specs={
            "hit_bits": shard,
            "n_emitted": rep,
            "n_hits": rep,
        },
        # Out specs are explicit, so the static vma checker adds nothing
        # here — and it rejects pallas_call bodies whose block specs mix
        # replicated plan/table refs with sharded block refs (JAX's own
        # error message recommends exactly this switch).
        check_vma=False,
    )
    return jax.jit(mapped)


@audited_entry(
    "parallel.make_sharded_superstep_step",
    kind="sharded_body",
    stages=("expand", "hash", "membership"),
)
def make_sharded_superstep_step(
    spec: AttackSpec,
    mesh: Mesh,
    *,
    lanes_per_device: int,
    axis_name: str = "data",
    num_blocks: int,
    step_advance: "int | None" = None,
    **kwargs,
):
    """The superstep executor, shard_map'd over a 1-D mesh.

    Each device runs the SAME ``lax.scan`` superstep body
    (``models.attack.make_superstep_body``) over its own block-cursor
    stripe: device ``d`` of ``D`` starts at ``b0 + d * num_blocks`` and
    every scan step advances all devices by ``D * num_blocks`` — exactly
    the contiguous per-launch ranges ``make_device_blocks`` cuts, so the
    sharded superstep sweeps the identical (word, rank) stream.  An
    explicit ``step_advance`` overrides that default when this mesh's
    stripes are a subset of a larger lattice (the pod giant-job mode
    passes ``num_blocks * total_stripes``; PERF.md §29).

    Input pytrees: ``plan``/``table``/``digests``/``ss`` replicated;
    ``b0`` an int32 [D] of per-device start block indices, sharded;
    ``bufs`` the per-device hit-buffer sets — int32
    ``[D * (hit_cap + 1)]`` sharded on the leading axis, donated off-CPU
    exactly like the single-device step (the pipelined driver cycles two
    sets; PERF.md §18).
    Outputs: ``counters`` (= psum'd ``[n_emitted, n_hits]``, the
    driver's single per-superstep fetch) and the scalar counts
    replicated; ``dev_hits`` int32 [D] and the per-device hit buffers
    ``hit_word``/``hit_rank`` int32 [D * (hit_cap + 1)] sharded on the
    leading axis (device ``d``'s slots at
    ``[d * (hit_cap + 1), (d+1) * (hit_cap + 1))``, slot ``hit_cap`` the
    trash slot).  The host merges per-device slices and sorts by
    (word, rank) — cursor order, identical to the single-device stream.

    Cross-job packed dispatch (``n_seg`` in ``kwargs``, PERF.md §22):
    ``b0`` becomes int32 [D, n_seg] — device ``d``'s per-segment start
    rows, each ``b0[j] + d * (num_blocks // n_seg)`` — and the SAME
    single stacked collective now carries the segmented counter rows
    (``counters`` int32 [2, n_seg] psum'd elementwise), so per-job
    counts survive sharding without any extra psum.
    """
    from ..models.attack import _buffer_donation

    n_devices = int(np.prod(mesh.devices.shape))
    # step_advance default: this mesh's stripes tile the keyspace alone.
    # The pod giant-job mode widens it to num_blocks * total_stripes so
    # every process's mesh advances past ALL pod stripes (PERF.md §29).
    if step_advance is None:
        step_advance = num_blocks * n_devices
    body = make_superstep_body(
        spec, num_lanes=lanes_per_device, num_blocks=num_blocks,
        step_advance=step_advance, **kwargs,
    )

    def local_step(plan, table, digests, ss, b0, bufs):
        out = body(plan, table, digests, ss, b0[0], bufs)
        # ONE collective per superstep: counters stacks
        # [n_emitted, n_hits] (per-segment COLUMNS under the packed
        # dispatch), so the replicated scalars are its rows (or their
        # segment sums).
        out["counters"] = jax.lax.psum(out["counters"], axis_name)
        if out["counters"].ndim == 1:
            out["n_emitted"] = out["counters"][0]
            out["n_hits"] = out["counters"][1]
        else:
            out["n_emitted"] = jnp.sum(out["counters"][0])
            out["n_hits"] = jnp.sum(out["counters"][1])
        return out

    rep = P()
    shard = P(axis_name)
    mapped = _shard_map(
        local_step,
        mesh=mesh,
        in_specs=(rep, rep, rep, rep, shard, shard),
        out_specs={
            "counters": rep,
            "n_emitted": rep,
            "n_hits": rep,
            "dev_hits": shard,
            "hit_word": shard,
            "hit_rank": shard,
        },
        check_vma=False,  # see make_sharded_crack_step
    )
    return jax.jit(mapped, donate_argnums=_buffer_donation())


def make_sharded_candidates_step(
    spec: AttackSpec,
    mesh: Mesh,
    *,
    lanes_per_device: int,
    out_width: int,
    axis_name: str = "data",
    block_stride: int | None = None,
    radix2: bool = False,
    pieces=None,
):
    """The expand-only step, shard_map'd over a 1-D mesh.

    For the reference-compatible stdout surface at mesh scale: each device
    expands its own block shard; the host fetches the (sharded) candidate
    buffer and streams it in device order — device d's lanes occupy rows
    ``[d * lanes_per_device, (d+1) * lanes_per_device)``, which is cursor
    order because :func:`make_device_blocks` cuts device ranges contiguously.

    Returns ``step(plan, table, blocks) -> (cand, cand_len, word_row, emit)``
    with every output sharded on its leading axis.
    """
    local_step = make_candidates_body(
        spec, num_lanes=lanes_per_device, out_width=out_width,
        block_stride=block_stride, radix2=radix2, pieces=pieces,
    )

    rep = P()
    shard = P(axis_name)
    mapped = _shard_map(
        local_step,
        mesh=mesh,
        in_specs=(rep, rep, shard),
        out_specs=(shard, shard, shard, shard),
        check_vma=False,  # see make_sharded_crack_step
    )
    return jax.jit(mapped)


def replicate(mesh: Mesh, tree):
    """Put a pytree on every device of the mesh (replicated sharding)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def shard_leading(mesh: Mesh, tree, *, axis_name: str = "data"):
    """Shard a pytree's arrays over their leading axis."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def delete_tree(tree) -> None:
    """Explicitly free a pytree's device buffers (replicated or sharded:
    ``Array.delete`` drops every addressable shard).  The streaming
    chunk ring (PERF.md §19) calls this on each consumed chunk's plan /
    superstep arrays so resident device memory tracks the ring, not the
    dictionary — waiting for the GC would let freed chunks pile up
    behind Python reference cycles.  Host numpy leaves and
    already-deleted arrays are ignored."""
    for arr in jax.tree_util.tree_leaves(tree):
        delete = getattr(arr, "delete", None)
        if delete is None:
            continue
        try:
            delete()
        except RuntimeError:  # pragma: no cover - already freed
            pass

"""Multi-host runtime: ``jax.distributed`` + per-host wordlist stripes.

The reference's only "communication backend" is in-process Go channels
(``main.go:58-98``); its scheduler cannot leave one machine.  The TPU-native
equivalent (SURVEY.md §2.3/§5) is two-level:

* **within a host**: the sharded sweep over the local 1-D device mesh
  (``parallel.mesh`` via ``SweepConfig.devices``) — candidate traffic and
  hit reductions ride ICI;
* **across hosts**: the dictionary is cut into contiguous *stripes*, one per
  process; each host sweeps only its stripe with its local devices, and only
  tiny serialized **hit records** cross the host network (DCN) at the end —
  candidates never do.

This maps the problem's structure onto the hardware: candidate generation is
embarrassingly parallel over words (the reference itself parallelizes
per-word, ``main.go:70-94``), so host-level data parallelism with a final
hit gather is the whole story — no parameter synchronization, no pipeline.

Hit collection uses ``jax.experimental.multihost_utils.process_allgather``
over the distributed backend: JSON-serialized hit records padded to the
max per-host payload (hits are rare; the payload is bytes, not candidates).
Every process returns the same combined result; process 0 is the
conventional reporter.

Works as an N-process CPU job for CI (see tests/test_multihost.py: two
processes, one virtual CPU device each, coordinator on localhost).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.packing import PackedWords
from ..runtime.env import env_warn_once, read_env

__all__ = [
    "PeerLossError",
    "pod_local_done_exit",
    "initialize",
    "host_stripe",
    "stripe_packed",
    "gather_hits",
    "allgather_sum",
    "allgather_max",
    "allgather_metrics",
    "run_crack_multihost",
    "run_candidates_multihost",
]

#: Seconds without any sign of life from a peer before a survivor blocked
#: in a collective gives up (``A5GEN_DCN_TIMEOUT`` overrides; ``0``
#: disables).  With the coordination-service heartbeat (the normal case)
#: "sign of life" is a heartbeat update, so a STRAGGLER still sweeping its
#: stripe never trips it — only a process that stopped beating does, and
#: this value is pure detection latency.  Without a KV client (fallback)
#: it degrades to a plain collective timeout, where the default must also
#: cover straggler skew.
_DEFAULT_DCN_TIMEOUT = 600.0

#: Seconds between heartbeat publications (see :func:`_start_heartbeat`).
_HB_INTERVAL = 5.0

_HB_PREFIX = "a5gen/hb/"

_hb_thread: Optional[threading.Thread] = None


class PeerLossError(RuntimeError):
    """A peer process died or stalled while this one waited in a collective.

    ``jax.distributed`` collectives have no liveness detection — a host
    that dies mid-sweep leaves the survivors blocked in the final
    hit all-gather forever (VERDICT r4 weak #6).  Detection is a
    heartbeat: every process publishes a counter to the pod's
    coordination KV store every ``_HB_INTERVAL`` seconds for its whole
    lifetime (daemon thread, started by :func:`initialize`), and a
    survivor blocked in a collective polls its peers' counters — a
    counter frozen longer than ``A5GEN_DCN_TIMEOUT`` means the peer is
    gone, and the survivor aborts loudly instead of hanging.  Change
    detection (not timestamps) keeps it clock-skew-free, and a peer
    still *sweeping* keeps beating, so slow stripes never false-abort.
    Recovery is a relaunch: each host checkpoints its own stripe cursor
    independently, so rerunning the same command on every host resumes
    every stripe and dedupes already-reported hits
    (``runtime.checkpoint``, ``cli --retries``).
    """


def _kv_client():
    """The distributed coordination KV client, or None (no distributed
    runtime / internal API moved)."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax-internal API
        return None


def _start_heartbeat() -> None:
    """Publish this process's liveness counter forever (daemon thread).

    The thread dies with the process — which is exactly the signal: a
    frozen counter IS a dead process.  Idempotent; no-op when the
    distributed runtime (and hence the KV store) is absent."""
    global _hb_thread
    if _hb_thread is not None and _hb_thread.is_alive():
        return
    client = _kv_client()
    if client is None:
        return
    import jax

    key = f"{_HB_PREFIX}{jax.process_index()}"

    def _beat():
        n = 0
        while True:
            try:
                client.key_value_set(key, str(n), allow_overwrite=True)
            except Exception:
                return  # client torn down: process is exiting
            n += 1
            time.sleep(_HB_INTERVAL)

    _hb_thread = threading.Thread(
        target=_beat, daemon=True, name="a5gen-heartbeat"
    )
    _hb_thread.start()


def pod_local_done_exit() -> None:
    """Elastic-mode (``--pod-hits local``) exit protocol.

    ``jax.distributed``'s atexit hook runs a cooperative Shutdown barrier
    — which blocks (or errors) exactly when a peer died, breaking local
    mode's promise that a dead peer never blocks a survivor.  But process
    0 also HOSTS the coordination service: if it just ``os._exit``-ed on
    finishing its stripe, still-working peers would be killed by "leader
    died" errors.  So: every process marks itself done in the KV store
    (a write, not a barrier — works with dead peers), non-coordinator
    processes exit immediately, and process 0 lingers as service host
    until every peer is done or dead (stale heartbeat), then exits.
    All exits are ``os._exit(0)`` — the shutdown barrier never runs.
    """
    import sys

    import jax

    pid, nprocs = jax.process_index(), jax.process_count()
    client = _kv_client()
    if nprocs > 1 and client is None:
        # No KV store (internal API moved): an early os._exit cannot be
        # coordinated safely — keep the normal exit path (cooperative
        # shutdown barrier) rather than risk killing working peers.
        return
    if client is not None:
        try:
            client.key_value_set(f"a5gen/done/{pid}", "1",
                                 allow_overwrite=True)
        except Exception:  # pragma: no cover - service already torn down
            pass
    if pid == 0 and nprocs > 1:
        # A5GEN_DCN_TIMEOUT=0 disables DEATH detection only: the
        # coordinator still waits on done-marks (plain KV reads), it just
        # never declares a silent peer dead.
        threshold = _dcn_timeout()
        seen: dict = {}
        pending = set(range(1, nprocs))
        notified = False
        while pending:
            for p in list(pending):
                try:
                    done = client.key_value_try_get(f"a5gen/done/{p}")
                except Exception:
                    done = None
                if done is not None:
                    pending.discard(p)
            if not pending:
                break
            if threshold > 0:
                dead = _stale_peer(client, seen, nprocs, pid, threshold,
                                   only=pending)
                if dead is not None:
                    pending.discard(dead)
                    print(
                        f"a5gen: process 0: peer {dead} died mid-sweep; "
                        "its stripe needs a relaunch (resumes from its "
                        "own --checkpoint)",
                        file=sys.stderr,
                    )
                    continue
            if not notified:
                notified = True
                print(
                    f"a5gen: process 0: stripe done; staying up as "
                    f"coordination host for {len(pending)} working "
                    "peer(s)",
                    file=sys.stderr,
                )
            time.sleep(1.0)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


def _stale_peer(client, seen: dict, nprocs: int, self_pid: int,
                threshold: float,
                only: "Optional[set]" = None) -> Optional[int]:
    """Return a peer id whose heartbeat has not CHANGED in ``threshold``
    seconds (None if all alive).  ``seen`` carries (value, last-change
    monotonic time) across polls; comparing values instead of clocks
    makes cross-host skew irrelevant.  A peer whose key never appears is
    stale from the first poll — a process that died before its first
    beat is exactly as dead.  ``only`` restricts the scan (the local-mode
    linger loop passes its pending set: peers that finished and exited
    have frozen heartbeats but are not dead)."""
    now = time.monotonic()
    for p in (sorted(only) if only is not None else range(nprocs)):
        if p == self_pid:
            continue
        try:
            v = client.key_value_try_get(f"{_HB_PREFIX}{p}")
        except Exception:
            v = None
        rec = seen.get(p)
        if rec is None or rec[0] != v:
            seen[p] = (v, now)
        elif now - rec[1] > threshold:
            return p
    return None


def _runtime_already_up() -> bool:
    """Whether ``jax.distributed`` is already initialized in this process.

    Probed via ``jax.distributed.is_initialized()`` (falling back to the
    internal global state on older JAX), NOT via ``jax.process_count()`` —
    the latter spins up the XLA backend as a side effect, after which
    ``jax.distributed.initialize`` can never succeed (advisor r2, medium).
    """
    import jax

    try:
        return bool(jax.distributed.is_initialized())
    except AttributeError:
        pass
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Tuple[int, int]:
    """Bring up (or join) the JAX distributed runtime.

    Explicit arguments for manual topologies (CI, bare clusters); all-None
    attempts JAX's cluster auto-detection (cloud TPU pods, SLURM...) and
    falls back to single-process when no cluster environment is found.
    Safe to call when the runtime is already up (returns the live
    topology).  Must run before any other JAX call that would initialize
    the XLA backend.  Returns ``(process_id, num_processes)``.
    """
    import jax

    _dcn_timeout()  # validate the env knob at startup, not at first gather
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    if explicit and coordinator_address is None and (num_processes or 1) <= 1:
        # Explicit single-process topology (e.g. --num-processes 1 with no
        # coordinator): nothing to bring up.
        return 0, 1
    if not _runtime_already_up():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        except RuntimeError as e:
            # A racing/duplicate init is fine (JAX 0.9: "distributed.
            # initialize should only be called once."); anything else —
            # including "must be called before any JAX computations"
            # (backend up) and coordinator bind failures like "address
            # already in use" — is a real operator error, re-raised.
            msg = str(e).lower()
            if "called once" not in msg and "already initialized" not in msg:
                raise
        except ValueError:
            if explicit:
                raise
            # All-None auto-detection found no cluster environment:
            # single-process run.
            return 0, 1
    # Only query the topology AFTER distributed init (these calls create
    # the backend and cache its view of the world).
    pid, nprocs = jax.process_index(), jax.process_count()
    if nprocs > 1:
        # Liveness heartbeat for the pod failure detector (PeerLossError):
        # beats for the process's whole lifetime, including the sweep, so
        # a slow stripe is distinguishable from a dead host.
        _start_heartbeat()
    return pid, nprocs


def host_stripe(n_words: int, num_processes: int, process_id: int
                ) -> Tuple[int, int]:
    """Contiguous balanced stripe ``[lo, hi)`` of ``n_words`` for one host.

    The first ``n_words % num_processes`` hosts get one extra word; stripes
    are contiguous so each host's sweep keeps the linear (word, rank)
    cursor and dictionary-order semantics within its slice.
    """
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"process_id {process_id} out of range for {num_processes}"
        )
    base, rem = divmod(n_words, num_processes)
    lo = process_id * base + min(process_id, rem)
    hi = lo + base + (1 if process_id < rem else 0)
    return lo, hi


def stripe_packed(packed: PackedWords, lo: int, hi: int) -> PackedWords:
    """One host's slice of a packed batch; global dictionary positions are
    preserved in ``index`` so hits report against the full wordlist."""
    return PackedWords(
        tokens=packed.tokens[lo:hi],
        lengths=packed.lengths[lo:hi],
        index=packed.index[lo:hi],
    )


def _dcn_timeout() -> float:
    """``A5GEN_DCN_TIMEOUT`` as seconds, defaulting (with a stderr
    warning) on malformed values — a typo must not crash the pod at the
    END of a sweep, which is when the first collective runs.
    :func:`initialize` calls this too, so the warning fires at startup."""
    raw = read_env("A5GEN_DCN_TIMEOUT")
    if raw is None or raw == "":
        return _DEFAULT_DCN_TIMEOUT
    try:
        return float(raw)
    except ValueError:
        env_warn_once(
            "A5GEN_DCN_TIMEOUT", raw,
            f"invalid A5GEN_DCN_TIMEOUT={raw!r} "
            f"(want seconds); using {_DEFAULT_DCN_TIMEOUT:.0f}",
        )
        return _DEFAULT_DCN_TIMEOUT


def _allgather(x: np.ndarray, timeout: Optional[float] = None) -> np.ndarray:
    """Process-allgather with a leading process axis, under a liveness
    timeout.

    The gather runs on a daemon thread so the caller stays in control of
    the wait.  While blocked, the caller polls its peers' heartbeats
    (:func:`_stale_peer`): a peer whose counter froze for longer than
    ``timeout`` seconds (``A5GEN_DCN_TIMEOUT``, default
    ``_DEFAULT_DCN_TIMEOUT``; ``<=0`` disables the whole guard) raises
    :class:`PeerLossError` with resume instructions, while live-but-slow
    peers keep the wait open indefinitely.  Without a KV client the guard
    degrades to a plain collective timeout.  The stuck gather thread
    cannot be cancelled — callers that intend to exit must use
    ``os._exit`` after reporting (the CLI does)."""
    from jax.experimental import multihost_utils

    if timeout is None:
        timeout = _dcn_timeout()
    if timeout <= 0:
        return np.asarray(multihost_utils.process_allgather(x))

    result: list = []
    error: list = []

    def _run():
        try:
            result.append(np.asarray(multihost_utils.process_allgather(x)))
        except Exception as e:  # pragma: no cover - backend-dependent
            error.append(e)

    th = threading.Thread(target=_run, daemon=True, name="a5gen-allgather")
    th.start()

    import jax

    client = _kv_client()
    nprocs, self_pid = jax.process_count(), jax.process_index()
    seen: dict = {}
    start = time.monotonic()
    recovery = (
        "This host's stripe cursor is checkpointed independently "
        "(--checkpoint PATH.p<id>); relaunch the pod with the same flags "
        "to resume all stripes from their last checkpoints — "
        "already-reported hits are deduped on resume. A5GEN_DCN_TIMEOUT "
        "adjusts the detection threshold (0 disables)."
    )
    while True:
        th.join(min(_HB_INTERVAL, timeout))
        if not th.is_alive():
            break
        if client is not None:
            dead = _stale_peer(client, seen, nprocs, self_pid, timeout)
            if dead is not None:
                raise PeerLossError(
                    f"peer process {dead} has not heartbeat for "
                    f"{timeout:.0f}s while process {self_pid} of {nprocs} "
                    f"waits in a cross-host all-gather: the peer has died "
                    f"or stalled mid-sweep. " + recovery
                )
        elif time.monotonic() - start > timeout:
            raise PeerLossError(
                f"cross-host all-gather did not complete within "
                f"{timeout:.0f}s (process {self_pid} of {nprocs}, no "
                f"coordination KV store for heartbeats): a peer process "
                f"has likely died or stalled mid-sweep. " + recovery
            )
    if error:
        raise error[0]
    return result[0]


def allgather_sum(value: int) -> int:
    """Sum a host-local Python int across processes (DCN scalar reduce)."""
    return int(_allgather(np.asarray([value], dtype=np.int64)).sum())


def allgather_max(value: float) -> float:
    """Max of a host-local float across processes (DCN scalar reduce)."""
    return float(_allgather(np.asarray([value], dtype=np.float64)).max())


def gather_hits(hits: Sequence) -> List:
    """All-gather host-local hit records; returns the combined list sorted
    by (word_index, variant_rank), identical on every process.

    Records are JSON on the wire (variant ranks are host bigints — they can
    exceed int64 for huge variant spaces, so no fixed-width array encoding).
    Payloads are padded to the max per-host length; hits are rare, so the
    padding waste is noise.
    """
    from ..runtime.sinks import HitRecord

    payload = json.dumps([
        {
            "w": int(h.word_index),
            "r": int(h.variant_rank),
            "c": h.candidate.hex(),
            "d": h.digest_hex,
        }
        for h in hits
    ]).encode()
    n = len(payload)
    lens = _allgather(np.asarray([n], dtype=np.int64))[:, 0]
    width = max(1, int(lens.max()))
    buf = np.zeros(width, dtype=np.uint8)
    buf[:n] = np.frombuffer(payload, dtype=np.uint8)
    bufs = _allgather(buf)
    combined = []
    for p in range(bufs.shape[0]):
        raw = bytes(bufs[p, : int(lens[p])])
        for rec in json.loads(raw) if raw else []:
            combined.append(
                HitRecord(
                    word_index=rec["w"],
                    variant_rank=rec["r"],
                    candidate=bytes.fromhex(rec["c"]),
                    digest_hex=rec["d"],
                )
            )
    combined.sort(key=lambda h: (h.word_index, h.variant_rank))
    return combined


def _reduce_superstep(stats: Dict[str, int]) -> Dict[str, int]:
    """Pod-wide superstep stats: counters sum, the launches-per-fetch
    ratio and the pipelined flag max (hosts share one config; stripes
    differ only via the int32 step cap).  Returns {} when no stripe ran
    the executor.

    The key semantics ride ``runtime.telemetry.SUPERSTEP_MERGE`` — the
    same spec the bucketed merge uses — walked in the spec's FIXED
    order: every process must run the identical collective sequence
    even when its own stripe ran the per-launch path (empty stats);
    key-set-dependent gathers would wedge the pod."""
    from ..runtime.telemetry import SUPERSTEP_MERGE

    out = {
        k: allgather_sum(int(stats.get(k, 0)))
        for k in SUPERSTEP_MERGE.sum_keys
    }
    for k in SUPERSTEP_MERGE.max_keys:
        out[k] = int(allgather_max(float(stats.get(k, 0))))
    return out if any(out.values()) else {}


def allgather_metrics(snap: "Optional[Dict]" = None) -> Dict:
    """Pod-wide telemetry: all-gather each host's registry snapshot
    (JSON on the wire, padded like :func:`gather_hits`) and merge via
    the registry's own fixed-order merge (``runtime.telemetry.merge``)
    — counters/histogram buckets sum, gauges follow their declared
    aggregation.  Every process returns the identical merged snapshot.
    ONE collective regardless of key sets (the payload is opaque
    bytes), so ragged per-host metric sets cannot wedge the pod."""
    import jax

    from ..runtime import telemetry

    if snap is None:
        snap = telemetry.snapshot()
    if jax.process_count() == 1:
        # Degenerate pod: no collective to run (and process_allgather
        # drops the leading axis at size 1) — the merge of one.
        return telemetry.merge([snap])
    payload = json.dumps(snap).encode()
    n = len(payload)
    lens = _allgather(np.asarray([n], dtype=np.int64))[:, 0]
    width = max(1, int(lens.max()))
    buf = np.zeros(width, dtype=np.uint8)
    buf[:n] = np.frombuffer(payload, dtype=np.uint8)
    bufs = _allgather(buf)
    snaps = []
    for p in range(bufs.shape[0]):
        raw = bytes(bufs[p, : int(lens[p])])
        snaps.append(json.loads(raw) if raw else {})
    return telemetry.merge(snaps)


def _host_config(config, process_id: int):
    """Per-host copy of a SweepConfig: checkpoint paths get a process
    suffix (each host checkpoints its own stripe cursor independently)."""
    if config is None or config.checkpoint_path is None:
        return config
    return replace(
        config, checkpoint_path=f"{config.checkpoint_path}.p{process_id}"
    )


def stripe_n_words(packed, num_processes: int, process_id: int) -> int:
    """Word count of one host's stripe — the same striping policy
    :func:`_local_sweep` executes (per-bucket independent stripes for
    bucketed input), so progress totals always match the words actually
    swept."""
    if isinstance(packed, dict):
        return sum(
            stripe_n_words(p, num_processes, process_id)
            for p in packed.values()
        )
    lo, hi = host_stripe(packed.batch, num_processes, process_id)
    return hi - lo


def _local_sweep(spec, sub_map, packed, digests, config, pid: int,
                 nprocs: int):
    """This host's sweep over its stripe.  ``packed`` is a flat
    :class:`PackedWords` batch or a ``{width: PackedWords}`` bucket dict
    (the CLI's native fast path) — bucketed input stripes each bucket
    independently, which balances per-bucket work across hosts and keeps
    every stripe's linear (word, rank) cursor."""
    cfg = _host_config(config, pid)
    if isinstance(packed, dict):
        from ..runtime.bucketed import BucketedSweep

        local = {
            width: stripe_packed(p, *host_stripe(p.batch, nprocs, pid))
            for width, p in packed.items()
        }
        return BucketedSweep(spec, sub_map, local, digests, config=cfg)
    from ..runtime.sweep import Sweep

    lo, hi = host_stripe(packed.batch, nprocs, pid)
    return Sweep(spec, sub_map, stripe_packed(packed, lo, hi), digests,
                 config=cfg)


def run_crack_multihost(
    spec,
    sub_map: Dict[bytes, List[bytes]],
    packed: PackedWords,
    digests: Sequence[bytes],
    config=None,
    *,
    recorder=None,
    resume: bool = True,
    gather: bool = True,
):
    """The fused crack sweep at pod scale.

    Every process calls this with the SAME full wordlist — a flat
    :class:`PackedWords` batch or a ``{width: PackedWords}`` bucket dict —
    and sweeps its own stripe on its local devices.

    ``gather=True`` (default): all processes then exchange hit records
    and return the same combined SweepResult; the recorder
    (process-local; typically only given on process 0) receives the
    combined, globally-sorted hit stream.

    ``gather=False`` (elastic mode, CLI ``--pod-hits local``): each
    process streams ITS OWN stripe's hits to its recorder as they are
    found and returns its host-local result — **no collective runs at
    all**, so a dead peer cannot block survivors (they finish their
    stripes and exit cleanly; only the dead host's stripe needs a
    relaunch, which resumes from its own checkpoint).  The union of the
    per-host hit streams equals gathered mode's combined stream.
    """
    import jax

    from ..runtime.sweep import SweepResult

    pid, nprocs = jax.process_index(), jax.process_count()
    sweep = _local_sweep(spec, sub_map, packed, digests, config, pid, nprocs)
    if not gather:
        return sweep.run_crack(recorder, resume=resume)
    res = sweep.run_crack(resume=resume)
    all_hits = gather_hits(res.hits)
    if recorder is not None:
        for h in all_hits:
            recorder.emit(h)
    # resumed/wall_s are globally reduced too (any/max), so every process
    # really does return the same combined SweepResult (advisor r2).
    return SweepResult(
        n_emitted=allgather_sum(res.n_emitted),
        n_hits=len(all_hits),
        hits=all_hits,
        words_done=allgather_sum(res.words_done),
        resumed=allgather_sum(int(res.resumed)) > 0,
        wall_s=allgather_max(res.wall_s),
        routing={k: allgather_sum(int(v)) for k, v in
                 sorted(res.routing.items())},
        superstep=_reduce_superstep(res.superstep),
        # Streaming stats stay HOST-LOCAL (no collectives): chunk
        # sizing, compile overlap, and resident bounds describe this
        # host's own stripe ring — a pod-wide sum would mean nothing,
        # and a key-set-dependent gather could wedge the pod.
        stream=dict(res.stream),
    )


def run_crack_giant(
    spec,
    sub_map: Dict[bytes, List[bytes]],
    packed: PackedWords,
    digests: Sequence[bytes],
    config=None,
    *,
    recorder=None,
    resume: bool = True,
    gather: bool = True,
):
    """ONE oversized keyspace job split across the pod's chips — the
    giant-job twin of :func:`run_crack_multihost` (PERF.md §29).

    Where multihost mode stripes the WORDLIST (each host plans and
    sweeps its own word slice), giant mode hands every process the SAME
    full wordlist and splits the superstep BLOCK lattice instead:
    ``SweepConfig.pod=(pid, nprocs)`` makes global device ``p*D + d``
    own blocks ``b0 + (p*D + d) * num_blocks`` of every superstep, all
    stripes advancing in lockstep, so the union of the shards' hit
    streams is exactly the single-device stream.  The cursor stays the
    global linear (word, rank) cursor — a shard checkpoint (written at
    ``PATH.p<pid>``) resumes under the single-device path and vice
    versa: the giant job is ONE resumable job.  Requires the superstep
    executor (an ineligible plan raises rather than duplicating work
    through the per-launch path).

    ``gather=True`` (default): processes exchange hit records (each hit
    is found by exactly ONE stripe, so the gather is a disjoint union)
    and every process returns the same combined SweepResult; the
    recorder — typically only on process 0 — receives the combined
    (word, rank)-sorted stream.  ``words_done``/``routing``/``geometry``
    describe the FULL dictionary identically on every shard, so they
    merge by max/passthrough, not sum.

    ``gather=False`` (elastic): each process streams its own stripe's
    hits to its recorder and returns its host-local result — no
    collective runs at all, so a dead peer cannot block survivors; only
    the dead shard's stripe needs a relaunch, resuming from its own
    checkpoint.
    """
    import jax

    from ..runtime.sweep import Sweep, SweepConfig, SweepResult

    pid, nprocs = jax.process_index(), jax.process_count()
    cfg = _host_config(config, pid)
    cfg = replace(cfg if cfg is not None else SweepConfig(),
                  pod=(pid, nprocs))
    if isinstance(packed, dict):
        from ..runtime.bucketed import BucketedSweep

        sweep = BucketedSweep(spec, sub_map, packed, digests, config=cfg)
    else:
        sweep = Sweep(spec, sub_map, packed, digests, config=cfg)
    if not gather:
        return sweep.run_crack(recorder, resume=resume)
    res = sweep.run_crack(resume=resume)
    all_hits = gather_hits(res.hits)
    if recorder is not None:
        for h in all_hits:
            recorder.emit(h)
    return SweepResult(
        n_emitted=allgather_sum(res.n_emitted),
        n_hits=len(all_hits),
        hits=all_hits,
        # Every shard sweeps the same dictionary to the same boundary —
        # max (not sum) keeps the global count a global count.
        words_done=int(allgather_max(float(res.words_done))),
        resumed=allgather_sum(int(res.resumed)) > 0,
        wall_s=allgather_max(res.wall_s),
        # Routing counts describe planning the FULL dictionary and are
        # identical on every shard; summing would multiply them by P.
        routing=dict(res.routing),
        superstep=_reduce_superstep(res.superstep),
        stream=dict(res.stream),  # host-local (see run_crack_multihost)
        geometry=dict(res.geometry),
        geometry_source=res.geometry_source,
    )


def run_candidates_multihost(
    spec,
    sub_map: Dict[bytes, List[bytes]],
    packed: PackedWords,
    writer,
    config=None,
    *,
    resume: bool = True,
    gather: bool = True,
):
    """Candidates mode at pod scale: each host streams ITS OWN stripe to its
    local writer (stripe-local dictionary order).  Candidate streams never
    cross DCN — for flat (unbucketed) input, concatenating the per-host
    outputs in process order yields exactly the single-host stream.  For
    bucketed input each host's stream is bucket-major over its own stripe,
    so the concatenation is a per-word-multiset-preserving permutation of
    the single-host bucket-major stream (word order holds within each
    host×bucket run).  Returns this host's SweepResult with global
    emitted/words counts.
    """
    import jax

    from ..runtime.sweep import SweepResult

    pid, nprocs = jax.process_index(), jax.process_count()
    sweep = _local_sweep(spec, sub_map, packed, (), config, pid, nprocs)
    res = sweep.run_candidates(writer, resume=resume)
    if not gather:
        # Elastic mode: host-local counts, no collectives (see
        # :func:`run_crack_multihost`).
        return res
    return SweepResult(
        n_emitted=allgather_sum(res.n_emitted),
        n_hits=0,
        hits=[],
        words_done=allgather_sum(res.words_done),
        resumed=allgather_sum(int(res.resumed)) > 0,
        wall_s=allgather_max(res.wall_s),
        routing={k: allgather_sum(int(v)) for k, v in
                 sorted(res.routing.items())},
        stream=dict(res.stream),  # host-local (see run_crack_multihost)
    )

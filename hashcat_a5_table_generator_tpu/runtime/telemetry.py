"""Process-wide telemetry: ONE metrics registry + the superstep span
timeline (PERF.md §21).

The engine grew one ad-hoc counter surface per subsystem —
``schema_cache_stats()`` in ops/packing, ``_STEP_CACHE_STATS`` in
runtime/sweep, routing/superstep/stream dicts on ``SweepResult``, each
with its own bespoke merge in runtime/bucketed and parallel/multihost —
and no way to observe a *running* engine at all.  This module is the
one place operational signals live:

* :class:`MetricsRegistry` — thread-safe counters, gauges, and
  fixed-bucket histograms with plain-dict ``snapshot()`` /
  :func:`delta` / :func:`merge` semantics.  The scattered counters are
  now derived views of registry snapshots (``schema_cache_stats``,
  ``step_cache_stats`` keep their shapes), and the bucketed/multihost
  stat merges ride the shared :class:`MergeSpec` key semantics instead
  of re-encoding sum-vs-max per call site.
* :class:`SpanTimeline` — a bounded per-sweep ring of superstep span
  records, appended ONLY at already-host-side fetch boundaries (the
  drive loop's lagged counters barrier), so the pipeline overlap
  invariant (PERF.md §18) is untouched.  graftaudit's
  ``audit_telemetry`` statically pins that: no registry/timeline call
  may sit inside a jitted body, a scan body, or the in-flight window of
  the drive loop.
* :func:`profiler_span` — ``jax.profiler.TraceAnnotation`` behind a
  guard, a no-op wherever the profiler is unavailable.

``A5GEN_TELEMETRY=off`` (``runtime/env.telemetry_enabled``) disables
the hot-path instrumentation — span appends, per-fetch registry
updates, progress enrichment — which is what ``bench.py
--telemetry-ab`` measures (bar: ≤1% wall overhead on the production
crack contract).  Counters that back existing RESULT surfaces
(schema-cache and step-cache stats) always record: the hatch must
never change what a sweep reports, only what it instruments.

Deliberately dependency-free (stdlib only), like ``runtime/env.py``:
``ops/`` modules import this at module top level and the eager
``runtime`` imports stay jax-free.  GL013 enforces the flip side: the
registry owns timing, so ``runtime/`` code outside this module must
not grow new ``time.monotonic()`` accumulation patterns.
"""

from __future__ import annotations

import json
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import (Any, Callable, ContextManager, Dict, Iterable, List,
                    Optional, Sequence, Tuple, Type, TypeVar)


def enabled() -> bool:
    """Whether hot-path telemetry records (``A5GEN_TELEMETRY`` hatch).

    Re-read per call — the bench A/B flips the environment between
    arms — but only ever consulted at host-side fetch/compile
    boundaries, never per candidate."""
    from .env import telemetry_enabled

    return telemetry_enabled()


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

#: Default histogram bucket edges for wall-clock seconds: fetch gaps
#: span ~1e-5 s (CPU §4c pipeline) to whole-superstep stalls.
DEFAULT_TIME_EDGES: Tuple[float, ...] = (
    1e-5, 2.5e-5, 1e-4, 2.5e-4, 1e-3, 2.5e-3, 1e-2, 2.5e-2,
    0.1, 0.25, 1.0, 2.5, 10.0,
)


class Counter:
    """Monotonic counter (int or float adds).  Always records — result
    surfaces (schema/step cache stats) are derived from counters, and
    the ``A5GEN_TELEMETRY`` hatch must not change results."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value: float = 0
        self._lock = threading.Lock()

    def add(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _snap(self) -> dict:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Point-in-time value with a declared merge aggregation
    (``max``/``min``/``sum``/``last``) — snapshots carry the policy so
    :func:`merge` needs no out-of-band table."""

    __slots__ = ("name", "agg", "_value", "_lock")

    def __init__(self, name: str, agg: str = "last") -> None:
        if agg not in ("max", "min", "sum", "last"):
            raise ValueError(
                f"gauge agg must be max|min|sum|last, got {agg!r}"
            )
        self.name = name
        self.agg = agg
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _snap(self) -> dict:
        return {"type": "gauge", "value": self.value, "agg": self.agg}


class Histogram:
    """Fixed-bucket histogram.  ``edges`` are upper bounds (Prometheus
    ``le`` semantics: bucket ``i`` counts observations ``<= edges[i]``);
    one implicit overflow bucket past the last edge.  Edges are part of
    the snapshot, so merge can refuse mismatched layouts loudly."""

    __slots__ = ("name", "edges", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str,
                 edges: Sequence[float] = DEFAULT_TIME_EDGES) -> None:
        edges = tuple(float(e) for e in edges)
        if not edges or any(a >= b for a, b in zip(edges, edges[1:])):
            raise ValueError(
                f"histogram edges must be strictly ascending, got {edges}"
            )
        self.name = name
        self.edges = edges
        self._counts = [0] * (len(edges) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        # bisect_left on the upper bounds: the first edge >= v is v's
        # ``le`` bucket; past the last edge lands in the overflow slot.
        i = bisect_left(self.edges, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def _snap(self) -> dict:
        with self._lock:
            return {
                "type": "histogram",
                "edges": list(self.edges),
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }


_M = TypeVar("_M")


class MetricsRegistry:
    """Name → metric, with get-or-create accessors (call sites never
    coordinate creation) and a plain-dict snapshot."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls: Type[_M], *args: Any, **kw: Any) -> _M:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args, **kw)
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} is a {type(m).__name__}, "
                f"not a {cls.__name__}"
            )
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str, agg: str = "last") -> Gauge:
        return self._get(name, Gauge, agg)

    def histogram(self, name: str,
                  edges: Sequence[float] = DEFAULT_TIME_EDGES) -> Histogram:
        return self._get(name, Histogram, edges)

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able ``{name: {"type", "value"/...}}`` in sorted name
        order — deterministic, so multihost exchanges and test
        comparisons never depend on creation order."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        return {name: m._snap() for name, m in metrics}

    def reset(self) -> None:
        """Drop every metric (tests only — production counters are
        process-lifetime; deltas, not resets, scope them to a run)."""
        with self._lock:
            self._metrics.clear()


#: The process-wide registry every subsystem publishes into.
REGISTRY = MetricsRegistry()

#: Engine identity label (PERF.md §25): set by ``a5gen serve
#: --engine-id`` (default ``pid@host`` in serve mode), None outside
#: service mode — standalone runs keep unlabeled series, so nothing
#: downstream changes until a fleet actually exists.
_ENGINE_ID: Optional[str] = None


def set_engine_id(engine_id: Optional[str]) -> None:
    """Label every subsequent :func:`snapshot` with this engine's
    identity, so the fleet router's merged scrape distinguishes
    members instead of silently blending same-named series.  ``None``
    clears the label (tests)."""
    global _ENGINE_ID
    _ENGINE_ID = engine_id


def default_engine_id() -> str:
    """``pid@host`` — the ``--engine-id`` default: unique per process
    on one host, stable for the process lifetime."""
    import os
    import socket as socket_mod

    return f"{os.getpid()}@{socket_mod.gethostname()}"


def engine_id() -> Optional[str]:
    return _ENGINE_ID


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str, agg: str = "last") -> Gauge:
    return REGISTRY.gauge(name, agg)


def histogram(name: str,
              edges: Sequence[float] = DEFAULT_TIME_EDGES) -> Histogram:
    return REGISTRY.histogram(name, edges)


class Stopwatch:
    """Context manager timing one section into a registry histogram —
    the sanctioned home for elapsed-time arithmetic (GL013 bans the
    ``t0 = monotonic(); acc += monotonic() - t0`` idiom in ``runtime/``
    outside this module).  ``elapsed_s`` is readable after exit, so
    callers can apply thresholds (the fleet's slow-scrape strain
    signal, PERF.md §27) without re-deriving the arithmetic; recording
    honors the ``A5GEN_TELEMETRY`` hatch, the reading does not."""

    __slots__ = ("elapsed_s", "_hist", "_t0")

    def __init__(self, hist: Optional[Histogram]) -> None:
        self.elapsed_s = 0.0
        self._hist = hist
        self._t0 = 0.0

    def __enter__(self) -> "Stopwatch":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc: object) -> None:
        self.elapsed_s = time.monotonic() - self._t0
        if self._hist is not None and enabled():
            self._hist.observe(self.elapsed_s)


def stopwatch(name: str,
              edges: Sequence[float] = DEFAULT_TIME_EDGES
              ) -> Stopwatch:
    """Time a ``with`` block into ``histogram(name, edges)``."""
    return Stopwatch(REGISTRY.histogram(name, edges))


def snapshot() -> Dict[str, dict]:
    snap = REGISTRY.snapshot()
    if _ENGINE_ID is not None:
        for entry in snap.values():
            entry["engine"] = _ENGINE_ID
    return snap


# ---------------------------------------------------------------------------
# Snapshot algebra: delta / merge / exposition
# ---------------------------------------------------------------------------


def delta(before: Dict[str, dict], after: Dict[str, dict]
          ) -> Dict[str, dict]:
    """One run's share of the process counters: counters and histograms
    subtract (metrics absent from ``before`` count from zero); gauges
    pass through ``after`` (a point-in-time value has no delta).  Only
    nonzero entries survive — a delta is a report, not a registry
    dump."""
    out: Dict[str, dict] = {}
    for name, snap in after.items():
        prev = before.get(name)
        label = (
            {"engine": snap["engine"]} if "engine" in snap else {}
        )
        if snap["type"] == "counter":
            base = prev["value"] if prev else 0
            d = snap["value"] - base
            if d:
                out[name] = {"type": "counter", "value": d, **label}
        elif snap["type"] == "histogram":
            if prev and prev.get("edges") != snap["edges"]:
                prev = None  # re-created with new edges: delta from zero
            counts = [
                c - (prev["counts"][i] if prev else 0)
                for i, c in enumerate(snap["counts"])
            ]
            count = snap["count"] - (prev["count"] if prev else 0)
            if count:
                out[name] = {
                    "type": "histogram", "edges": list(snap["edges"]),
                    "counts": counts,
                    "sum": snap["sum"] - (prev["sum"] if prev else 0.0),
                    "count": count, **label,
                }
        else:
            # Gauges are point-in-time: the "delta" is the current
            # value, reported only when it moved (or is new) so an
            # unchanged registry yields an empty report.
            if prev is None or snap["value"] != prev["value"]:
                out[name] = dict(snap)
    return out


def _series_key(name: str, engine_id: Optional[str]) -> str:
    """Merged-output key of a per-engine-kept series — the Prometheus
    label spelling, so the merged dict reads like the exposition."""
    return f'{name}{{engine="{engine_id or ""}"}}'


def merge(snapshots: Iterable[Dict[str, dict]]) -> Dict[str, dict]:
    """Combine snapshots from many sources (buckets, hosts, engines):
    counters and histogram buckets sum (histogram edge layouts must
    match — mismatched edges fail loudly instead of blending apples;
    a cross-engine sum drops the now-meaningless ``engine`` label),
    gauges follow their declared ``agg`` — but ONLY among entries of
    one engine: gauges carrying conflicting ``engine`` labels (a
    fleet router's merged scrape, PERF.md §25) are kept as per-engine
    series under :func:`_series_key` keys instead of silently
    aggregating point-in-time values across members.  Keys are
    processed in sorted order, so every participant of a multihost
    exchange reduces the identical sequence (the fixed-order rule
    collectives require)."""
    out: Dict[str, dict] = {}
    split: set = set()  # gauge names gone per-engine
    for snap in snapshots:
        for name in sorted(snap):
            entry = snap[name]
            key = name
            if entry["type"] == "gauge":
                if name in split:
                    key = _series_key(name, entry.get("engine"))
                else:
                    cur = out.get(name)
                    if (
                        cur is not None
                        and cur.get("engine") != entry.get("engine")
                    ):
                        # First conflict: re-key the resident series
                        # and route this (and every later) entry to
                        # its own engine's series.
                        out[_series_key(name, cur.get("engine"))] = \
                            out.pop(name)
                        split.add(name)
                        key = _series_key(name, entry.get("engine"))
            cur = out.get(key)
            if cur is None:
                out[key] = json.loads(json.dumps(entry))  # deep copy
                continue
            if cur["type"] != entry["type"]:
                raise ValueError(
                    f"metric {name!r} merges a {cur['type']} with a "
                    f"{entry['type']}"
                )
            if cur.get("engine") != entry.get("engine"):
                # Summed across engines: the per-member label no
                # longer describes the value.
                cur.pop("engine", None)
            if entry["type"] == "counter":
                cur["value"] += entry["value"]
            elif entry["type"] == "histogram":
                if cur["edges"] != entry["edges"]:
                    raise ValueError(
                        f"histogram {name!r} edge layouts differ: "
                        f"{cur['edges']} vs {entry['edges']}"
                    )
                cur["counts"] = [
                    a + b for a, b in zip(cur["counts"], entry["counts"])
                ]
                cur["sum"] += entry["sum"]
                cur["count"] += entry["count"]
            else:
                agg = cur.get("agg", "last")
                if agg == "sum":
                    cur["value"] += entry["value"]
                elif agg == "max":
                    cur["value"] = max(cur["value"], entry["value"])
                elif agg == "min":
                    cur["value"] = min(cur["value"], entry["value"])
                else:
                    cur["value"] = entry["value"]
    return out


def _prom_name(name: str, prefix: str) -> str:
    out = "".join(
        c if c.isalnum() or c == "_" else "_" for c in name
    )
    return f"{prefix}_{out}"


def _prom_labels(entry: dict, extra: str = "") -> str:
    """Label block for one series: the optional ``le`` bucket label
    plus the ``engine`` identity label when the snapshot carries one
    (PERF.md §25 — a fleet's merged scrape must distinguish
    members)."""
    parts = [extra] if extra else []
    if "engine" in entry:
        parts.append(f'engine="{entry["engine"]}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def to_prometheus(snap: Dict[str, dict], prefix: str = "a5gen") -> str:
    """Prometheus text exposition (v0.0.4) of a snapshot: counters,
    gauges, and cumulative ``le``-bucketed histograms with ``+Inf``,
    ``_sum`` and ``_count`` series.  Entries labeled with an engine
    identity render it as an ``engine="..."`` label; per-engine-kept
    series from :func:`merge` (their dict keys already spell the
    label) render under their base name with the label from the
    entry."""
    lines: List[str] = []
    typed: set = set()  # one # TYPE line per metric name (the
    # exposition format rejects duplicates — per-engine split series
    # of one gauge share a single TYPE header)
    for name in sorted(snap):
        entry = snap[name]
        # A merge()-split series key carries its label in the name;
        # the entry's "engine" field is the authoritative rendering.
        base = name.split("{", 1)[0]
        pname = _prom_name(base, prefix)
        label = _prom_labels(entry)
        if entry["type"] == "histogram":
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} histogram")
            cum = 0
            for edge, c in zip(entry["edges"], entry["counts"]):
                cum += c
                le = 'le="%g"' % edge
                lines.append(
                    f"{pname}_bucket{_prom_labels(entry, le)} {cum}"
                )
            inf = 'le="+Inf"'
            lines.append(
                f'{pname}_bucket{_prom_labels(entry, inf)} '
                f'{entry["count"]}'
            )
            lines.append(f"{pname}_sum{label} {entry['sum']:g}")
            lines.append(f"{pname}_count{label} {entry['count']}")
        else:
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} {entry['type']}")
            v = entry["value"]
            lines.append(f"{pname}{label} {v:g}" if isinstance(v, float)
                         else f"{pname}{label} {v}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Shared stat-dict merge semantics (bucketed + multihost ride these)
# ---------------------------------------------------------------------------


class MergeSpec:
    """Key semantics of one scattered-stat dict: which keys sum (the
    default for anything undeclared), which take the max, which belong
    to the FIRST contributor only (sweep-local scalars like ttfc), and
    which are derived ratios the merger recomputes (never blended).

    ``runtime/bucketed.py`` merges through :meth:`merge`; the multihost
    reducers walk :attr:`sum_keys` / :attr:`max_keys` in fixed order so
    every process runs the identical collective sequence — ONE place
    now says what each key means."""

    def __init__(self, *, sum_keys: Sequence[str] = (),
                 max_keys: Sequence[str] = (),
                 first_keys: Sequence[str] = (),
                 derived_keys: Sequence[str] = ()) -> None:
        self.sum_keys = tuple(sum_keys)
        self.max_keys = tuple(max_keys)
        self.first_keys = tuple(first_keys)
        self.derived_keys = tuple(derived_keys)

    def merge(self, dicts: Sequence[Dict]) -> Dict:
        out: Dict = {}
        for i, d in enumerate(dicts):
            for k, v in d.items():
                if k in self.derived_keys:
                    continue
                if k in self.max_keys:
                    out[k] = max(out.get(k, 0), v)
                elif k in self.first_keys:
                    if i == 0:
                        out[k] = v
                else:
                    out[k] = out.get(k, 0) + v
        return out


#: ``SweepResult.superstep`` (PERF.md §15/§18): counters sum; the
#: steps-per-fetch ratio and the pipelined/pair flags describe one
#: shared config, so they max.
SUPERSTEP_MERGE = MergeSpec(
    sum_keys=("supersteps", "launches", "replays", "retries"),
    max_keys=("launches_per_fetch", "pipelined", "pair"),
)

#: ``SweepResult.stream`` (PERF.md §19): walls/counters sum,
#: peaks/bounds max, sweep-local scalars belong to the first streaming
#: contributor, overlap ratios are derived from the summed terms.
STREAM_MERGE = MergeSpec(
    sum_keys=("chunks", "chunks_swept", "compile_wall_s",
              "compile_overlap_s"),
    max_keys=("peak_resident_plan_bytes", "chunk_bytes_max",
              "chunk_words", "prefetch", "ring"),
    first_keys=("ttfc_s", "resumed_chunk", "first_chunk_compile_s"),
    derived_keys=("overlap_ratio", "steady_overlap_ratio"),
)

#: ``SweepResult.routing`` / ``SweepResult.schema_cache``: plain
#: counter sums.
ROUTING_MERGE = MergeSpec()
SCHEMA_CACHE_MERGE = MergeSpec()


# ---------------------------------------------------------------------------
# Superstep span timeline
# ---------------------------------------------------------------------------


class SpanTimeline:
    """Bounded per-sweep ring of fetch-boundary span records.

    One record per CONSUMED fetch (superstep counters barrier or
    per-launch chunk drain), appended by the drive loop at the already-
    host-side boundary — the timeline never adds a device round trip,
    and its ring bound (``capacity``, default 512) keeps a
    billion-superstep sweep's memory flat.  Each record carries the
    fetch wall-clock, the host gap since the previous consumed fetch,
    the in-flight depth at the fetch (0 = the gap was dead device
    time), hit-buffer occupancy, overflow-replay and chunk markers.

    The timeline also publishes the aggregate registry metrics
    (``sweep.fetch_gap_s`` histogram, ``sweep.host_gap_s`` /
    ``sweep.dead_host_s`` / per-kind fetch counters) — the registry
    owns timing (GL013): drive loops call :meth:`record_fetch` and
    never accumulate ``time.monotonic()`` themselves."""

    def __init__(self, capacity: int = 512,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._clock = clock
        self._lock = threading.Lock()
        self._n = 0
        self._last_fetch: Optional[float] = None
        self._gap_s = 0.0
        self._dead_s = 0.0
        self._max_inflight = 0

    def record_fetch(self, *, kind: str = "superstep", index: int = 0,
                     dispatched_at: Optional[float] = None,
                     inflight: int = 0, launches: int = 0,
                     emitted: int = 0, hits: int = 0,
                     hit_occupancy: float = 0.0, replayed: bool = False,
                     chunk: Optional[int] = None) -> None:
        """Append one span at a consumed fetch boundary and publish the
        aggregates.  No-op under ``A5GEN_TELEMETRY=off``."""
        if not enabled():
            return
        now = self._clock()
        rec = {
            "t": now, "kind": kind, "index": int(index),
            "inflight": int(inflight), "emitted": int(emitted),
            "hits": int(hits),
        }
        if dispatched_at is not None:
            rec["queued_s"] = now - dispatched_at
        if hit_occupancy:
            rec["hit_occupancy"] = float(hit_occupancy)
        if replayed:
            rec["replayed"] = True
        if chunk is not None:
            rec["chunk"] = int(chunk)
        gap = None
        with self._lock:
            if self._last_fetch is not None:
                gap = now - self._last_fetch
                rec["gap_s"] = gap
                self._gap_s += gap
                if inflight == 0:
                    self._dead_s += gap
            self._last_fetch = now
            self._n += 1
            self._max_inflight = max(self._max_inflight, int(inflight))
            self._ring.append(rec)
        counter(f"sweep.fetches.{kind}").add(1)
        if launches:
            counter("sweep.launches").add(int(launches))
        if emitted:
            counter("sweep.candidates").add(int(emitted))
        if hits:
            counter("sweep.hits").add(int(hits))
        if replayed:
            counter("sweep.overflow_replays").add(1)
        if gap is not None:
            histogram("sweep.fetch_gap_s").observe(gap)
            counter("sweep.host_gap_s").add(gap)
            if inflight == 0:
                counter("sweep.dead_host_s").add(gap)

    def spans(self) -> List[dict]:
        """The retained span records, oldest first."""
        with self._lock:
            return list(self._ring)

    def summary(self) -> dict:
        """Per-sweep span digest for ``done``/``paused`` events and
        ``--metrics-json``: span/drop counts, host-gap totals, the
        dead (no superstep in flight) share of the gap, and the peak
        in-flight depth.  Empty dict when nothing recorded."""
        with self._lock:
            n = self._n
            if not n:
                return {}
            retained = len(self._ring)
            gap_s, dead_s = self._gap_s, self._dead_s
            max_inflight = self._max_inflight
            last = self._ring[-1]
        out = {
            "spans": n,
            "dropped": n - retained,
            "host_gap_s": round(gap_s, 6),
            "dead_host_s": round(dead_s, 6),
            "max_inflight": max_inflight,
            "last_kind": last["kind"],
        }
        if gap_s > 0:
            out["dead_share"] = round(dead_s / gap_s, 4)
        return out


# ---------------------------------------------------------------------------
# Progress enrichment + profiler hooks
# ---------------------------------------------------------------------------


def progress_fields() -> dict:
    """Registry-derived fields for the progress JSON line (PERF.md §21;
    keys documented in README): pipeline dead-time share, chunk-ring
    occupancy, and cache hit rates.  Only fields with signal appear;
    {} when telemetry is off or nothing has recorded yet."""
    if not enabled():
        return {}
    out: dict = {}
    gap = counter("sweep.host_gap_s").value
    if gap > 0:
        out["dead_share"] = round(
            counter("sweep.dead_host_s").value / gap, 4
        )
    ring = gauge("stream.ring_occupancy").value
    if ring:
        out["ring_occupancy"] = int(ring)
    for label, prefix in (("schema_cache_hit_rate", "schema_cache"),
                          ("step_cache_hit_rate", "step_cache")):
        hits = counter(f"{prefix}.hits").value
        misses = counter(f"{prefix}.misses").value
        if hits + misses:
            out[label] = round(hits / (hits + misses), 4)
    return out


def profiler_span(name: str) -> ContextManager[Any]:
    """A ``jax.profiler.TraceAnnotation`` span, or a null context when
    the profiler (or that API) is unavailable on this jax version — the
    drive loops annotate phases unconditionally and the guard keeps
    them importable everywhere."""
    try:
        import jax.profiler as _prof

        ta = getattr(_prof, "TraceAnnotation", None)
        if ta is not None:
            return ta(name)
    except Exception:  # pragma: no cover - jax-less / broken profiler
        pass
    import contextlib

    return contextlib.nullcontext()


def profiler_trace(path: Optional[str]) -> ContextManager[Any]:
    """``jax.profiler.trace(path)`` behind the same guard; a null
    context when ``path`` is falsy or the profiler is unavailable
    (``--profile-dir`` must degrade to a no-op, not a crash)."""
    import contextlib

    if not path:
        return contextlib.nullcontext()
    try:
        import jax.profiler as _prof

        tracer = getattr(_prof, "trace", None)
        if tracer is not None:
            return tracer(path)
    except Exception:  # pragma: no cover - jax-less / broken profiler
        pass
    return contextlib.nullcontext()

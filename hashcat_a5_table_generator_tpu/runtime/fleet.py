"""Fleet tier: a front-end router over a pool of engine processes
(PERF.md §25, ROADMAP item 1).

One resident ``a5gen serve`` process (PERF.md §20/§22) multiplexes many
tenants but caps out at one host's worth; the fleet tier scales the
SAME protocol across N engines.  :class:`FleetRouter` owns a pool of
engine endpoints — spawned locally (:func:`spawn_engines`) or attached
by unix-socket path — and speaks the serve protocol upstream, so
existing clients work unmodified: ``submit``/``pause``/``resume``/
``cancel``/``stats``/``metrics``/``shutdown`` pass through, and the
router adds ``drain`` and ``migrate`` for operators.

Everything the router does rides seams the engine tier already ships:

* **Placement** is static-trace-config affinity
  (``runtime.fuse.affinity_token``): a submit document's doc-level
  static config hashes to the same token the engine computes for its
  resident slots (reported through the ``stats`` op's
  ``resident_groups``), so jobs that COULD share a compiled program or
  fuse into one packed dispatch land on the engine already running
  their kind; ties break on load score from the scraped placement
  signals (runnable/staged/building counts).  ``place='round-robin'``
  is the control arm ``bench.py --fleet-ab`` compares against.
* **Rebalance** (drain/migrate) is pause → checkpoint over the wire →
  resubmit with the checkpoint on the target engine — checkpoints are
  a fingerprint-checked JSON wire format, so migration IS
  resubmission (``wire_version``-gated across builds:
  ``checkpoint.check_wire_version``).  ``drain`` empties an engine for
  shutdown; a draining engine takes no new placements.
* **Crash-replay**: an engine death (torn socket, watchdog-detected
  wedge, reaped process) requeues every routed job from its last
  router-held checkpoint onto the survivors.  Redelivery is
  at-least-once at the engine (a resumed machine replays its
  checkpointed hits), made EXACT by the existing muted-replay
  discipline: the router forwards ``replay_mute`` = hits already
  delivered downstream, and the engine's ``_JobRecorder(mute=)``
  suppresses exactly that deterministic prefix — per-job hit streams
  stay byte-identical to solo runs across engine deaths.
* **Compile-once fleet-wide**: engines share one ``--schema-cache``
  directory as the fleet artifact store; entries are written through
  ``checkpoint.atomic_write_bytes`` (tmp + fsync + rename), so N
  concurrent writers never tear an entry and each plan×table schema
  compiles once across the fleet.

Candidates jobs migrate/crash-replay by RESTART (cancel + fresh
resubmission, output truncated) rather than checkpoint resume: their
output file is engine-local and append-resume across processes would
duplicate the tail.  Crack jobs — the service workload — get the exact
checkpoint path.

The router holds no device state and runs no jax: it is JSON, sockets
and tables, so one router fronts many engine processes without
competing for the accelerator.

Giant-job striping (PERF.md §31, ROADMAP item 4): one OVERSIZED job
can also split ACROSS engines.  The router rewrites the submit
document N ways with disjoint ``config.pod = [i, N]`` rank-stride
stripes — the same cursor arithmetic ``SweepConfig.pod`` already
generalizes in-process — and dispatches each stripe to a different
engine; a k-way merge (:class:`_SplitMerge`) releases the per-shard
(word,rank)-ordered hit streams back to the client as ONE globally
(word,rank)-ordered, exactly-once stream.  Every shard rides the
existing checkpoint wire format, so a shard's checkpoint stays
interchangeable with a solo resume, and a dead engine's stripe
reassigns through the ordinary crash-replay path (checkpoint +
``replay_mute``), never replaying a hit into the client.  ``split``
picks the mode (``auto`` scatters oversized fresh submits;
``on``/``off`` force it) and the explicit ``split`` op scatters a
RUNNING job mid-flight (pause → checkpoint → N shard resubmits).

The elastic half (PERF.md §27) makes the fleet overload-safe and
self-managing:

* **Admission control + backpressure**: placements are gated by
  ``engine_capacity`` (routed jobs per engine); jobs that cannot place
  ride a BOUNDED router-side pending queue (``max_pending``) and
  dispatch as capacity frees.  Past the bound, ``submit`` fails with
  the typed overload rejection (:class:`FleetOverloaded` — the JSONL
  front-end renders ``{"error": "overloaded", "retry_after_s": ...}``)
  instead of queueing silently; ``shed_policy`` picks the degradation
  mode (``reject`` new arrivals, shed the ``oldest`` pending job, or
  ``queue`` unboundedly — the legacy escape hatch).  Jobs carrying a
  ``deadline_s`` are shed first (an expired deadline is already a
  failed contract), and ``per_tenant`` caps one tenant's unsettled
  jobs so a single client cannot monopolize the fleet.
* **Health ladder + circuit breaking**: each engine walks ``healthy →
  degraded → quarantined`` on scrape strain — slow scrapes, rising
  ``group_demotions``/``job_restarts`` deltas (the §23 recovery ladder
  leaking through an engine's stats), failed scrapes, and repeated
  checkpoint-bearing job failures (quarantine resubmissions).  A
  degraded engine places last; a QUARANTINED engine takes no
  placements at all and is drained + replaced by the autoscaler —
  the per-engine recovery ladder lifted to the fleet.
* **Autoscaling** (``runtime/autoscale.py``): a router-owned control
  loop spawns engines when sustained load crosses ``scale_up_at`` and
  drains + reaps idle ones below ``scale_down_at``, with hysteresis
  windows and a cooldown so churn cannot flap — spawn rides
  :func:`spawn_engines`, drain rides the PR 13 drain path, so
  placement, affinity and crash-replay are untouched.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import socket
import subprocess
import sys
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, \
    TextIO, Tuple

from . import faults as faults_mod
from . import protocol
from . import telemetry
from .checkpoint import validate_checkpoint_doc
from .fuse import static_affinity_token

#: Module path engines are spawned from (``python -m <this>``).
_PACKAGE = __name__.rsplit(".", 2)[0]


class FleetError(RuntimeError):
    """A fleet-level operation failed (no live engine, an engine
    rejected a routed document, an ack timed out)."""


class FleetOverloaded(FleetError):
    """The typed overload rejection (PERF.md §27): the router's bounded
    admission surface is full (pending queue at ``max_pending``, or a
    tenant over its in-flight cap).  Carries ``retry_after_s`` — the
    router's backoff estimate — so clients back off instead of
    hammering; the JSONL front-end renders it as
    ``{"event": "error", "error": "overloaded", "retry_after_s": ...}``."""

    def __init__(self, reason: str, retry_after_s: float) -> None:
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = float(retry_after_s)

    def event(self, jid: "Optional[str]" = None) -> dict:
        return protocol.ev_error_overloaded(
            self.reason, self.retry_after_s, jid=jid
        )


#: The health ladder's states (PERF.md §27), in degradation order.
HEALTH_STATES = ("healthy", "degraded", "quarantined")


class _NoCapacity(FleetError):
    """Internal: every placeable engine is at ``engine_capacity`` —
    the caller queues (admission control) instead of failing loudly."""


def scraped_load(scrape: dict) -> int:
    """An engine's internal load from its ``stats`` scrape — the ONE
    definition placement (:meth:`FleetRouter._load_score`) and the
    autoscaler's backlog signal share, so they can never disagree
    about what "loaded" means."""
    return (
        scrape.get("jobs_runnable", scrape.get("jobs_active", 0))
        + scrape.get("jobs_staged", 0)
        + scrape.get("jobs_building", 0)
        + scrape.get("jobs_queued", 0)
    )


# ---------------------------------------------------------------------------
# Engine endpoints
# ---------------------------------------------------------------------------


class EngineLink:
    """Router-side handle of ONE engine: the JSONL socket, a reader
    thread demuxing its event stream, and the routing bookkeeping the
    placement reads.

    Event demux: events carrying a job ``id`` flow to the router's
    job-event handler — EXCEPT the ``accepted`` ack a pending
    :meth:`request` is waiting for.  Id-less control replies
    (``stats``/``metrics``/``bye``/``error``) answer the pending
    request; the engine session handles ops sequentially per
    connection, so one in-flight request per link (serialized by
    ``_ctl_lock``) correlates exactly."""

    def __init__(self, sock: socket.socket, endpoint: str,
                 engine_id: str, *,
                 proc: "Optional[subprocess.Popen]" = None,
                 index: int = 0,
                 on_event: Optional[Callable] = None,
                 on_death: Optional[Callable] = None) -> None:
        self.endpoint = endpoint
        self.engine_id = engine_id
        self.proc = proc
        self.index = index
        self.alive = True
        self.draining = False
        #: last scraped ``stats`` event (placement signals).
        self.scrape: dict = {}
        #: consecutive failed health scrapes (watchdog input).
        self.misses = 0
        #: router-level job ids currently routed here.
        self.routed: set = set()
        #: health-ladder state (PERF.md §27): ``healthy`` places
        #: normally, ``degraded`` places last, ``quarantined`` never
        #: places (the autoscaler drains + replaces it).  All ladder
        #: fields are written by the ROUTER (scrape/event paths), never
        #: by the link's own threads.
        self.health = "healthy"
        #: consecutive strained scrapes (slow/failed scrape, rising
        #: recovery-ladder deltas) — degrade/quarantine input.
        self.strikes = 0
        #: consecutive clean scrapes while degraded — recovery input.
        self.clean = 0
        #: checkpoint-bearing job failures off this engine (quarantine
        #: resubmissions) — the repeated-crash-replay ladder input.
        self.replay_fails = 0
        #: last scrape's recovery-ladder counters (delta base).
        self.ladder_prev: dict = {}
        #: next scheduled poll tick (monotonic; per-engine jitter so N
        #: engines never stampede one scrape tick).
        self.next_poll = 0.0
        self._sock = sock
        self._fin = sock.makefile("r", encoding="utf-8")
        self._fout = sock.makefile("w", encoding="utf-8")
        self._wlock = threading.Lock()
        self._ctl_lock = threading.Lock()
        self._waiter: "Optional[Tuple[Optional[str], queue.Queue]]" = None
        #: id-less replies to drop: a timed-out stats/metrics/shutdown
        #: request leaves its reply in flight, and the engine answers
        #: per-connection in order — the NEXT id-less event is the
        #: stale reply, not the new request's (see :meth:`request`).
        self._skip_replies = 0
        self._skip_lock = threading.Lock()
        #: lazily-opened SECOND connection for health scrapes: the
        #: engine serves one session per connection, so stats replies
        #: here can never queue behind a blocking op (a pause parking
        #: at a superstep boundary) on the main op stream — a healthy
        #: engine mid-drain must not look dead to the watchdog, and a
        #: scrape timeout must not desync the main link's reply
        #: correlation.
        self._health_sock = None
        self._health_file = None
        self._health_lock = threading.Lock()
        self._closing = False
        self._on_event = on_event
        self._on_death = on_death
        self._reader_thread = threading.Thread(
            target=self._reader, name=f"a5-fleet-link-{engine_id}",
            daemon=True,
        )
        self._reader_thread.start()

    @classmethod
    def connect(cls, endpoint: str, engine_id: Optional[str] = None,
                *, timeout: float = 180.0, **kw: Any) -> "EngineLink":
        """Connect to an engine's unix socket, retrying until it is
        listening (a freshly spawned engine binds only after its jax
        import)."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.connect(endpoint)
                break
            except OSError:
                s.close()
                proc = kw.get("proc")
                if proc is not None and proc.poll() is not None:
                    raise FleetError(
                        f"engine process for {endpoint!r} exited with "
                        f"{proc.returncode} before listening"
                    )
                if time.monotonic() > deadline:
                    raise FleetError(
                        f"engine at {endpoint!r} not listening after "
                        f"{timeout:g}s"
                    )
                time.sleep(0.1)
        return cls(s, endpoint, engine_id or endpoint, **kw)

    # -- wire ----------------------------------------------------------

    def send(self, doc: dict) -> None:
        # The torn-engine-connection seam (PERF.md §27): an injected
        # error here fails the op exactly like a mid-write socket tear —
        # typed FleetError to the caller; the scrape path's failures
        # additionally feed the health ladder.
        if faults_mod.ACTIVE is not None:
            faults_mod.ACTIVE.fire("link.send")
        with self._wlock:
            self._fout.write(json.dumps(doc) + "\n")
            self._fout.flush()

    def request(self, doc: dict, *, timeout: float = 120.0) -> dict:
        """Send one op and wait for its correlated reply; raises
        :class:`FleetError` on an ``error`` reply, a timeout, or a
        connection lost mid-wait.

        Correlation survives timeouts: the engine answers ops in order
        per connection, so when an op expecting an ID-LESS reply
        (stats/metrics/shutdown) times out, the late reply is still
        ahead of any later request's — the reader skips exactly that
        many id-less events before answering the next waiter.  A
        timed-out SUBMIT needs no skip: its late ``accepted`` carries
        the job id and falls through to the event plane, which ignores
        it."""
        q: "queue.Queue" = queue.Queue()
        with self._ctl_lock:
            self._waiter = (doc.get("id"), q)
            try:
                self.send(doc)
                ev = q.get(timeout=timeout)
            except (OSError, ValueError, faults_mod.FaultError) as exc:
                raise FleetError(
                    f"engine {self.engine_id}: send failed ({exc})"
                ) from exc
            except queue.Empty:
                if protocol.doc_op(doc) in (
                    "stats", "metrics", "shutdown"
                ):
                    with self._skip_lock:
                        self._skip_replies += 1
                raise FleetError(
                    f"engine {self.engine_id}: no reply to "
                    f"{protocol.doc_op(doc)!r} in {timeout:g}s"
                ) from None
            finally:
                self._waiter = None
        if protocol.doc_event(ev) == "error":
            raise FleetError(
                f"engine {self.engine_id}: {ev.get('error')}"
            )
        return ev

    def health_request(self, doc: dict, *, timeout: float) -> dict:
        """One synchronous op on the dedicated health connection
        (opened lazily, re-opened after any failure — a timeout could
        leave a partial reply in flight, so the connection is never
        reused past an error)."""
        with self._health_lock:
            try:
                # The same torn-connection seam as :meth:`send`, on the
                # dedicated health stream: an injected failure here is a
                # failed scrape — retried once in-poll, then a watchdog
                # miss plus a health-ladder strike (PERF.md §27).
                if faults_mod.ACTIVE is not None:
                    faults_mod.ACTIVE.fire("link.send")
                if self._health_file is None:
                    s = socket.socket(socket.AF_UNIX,
                                      socket.SOCK_STREAM)
                    s.settimeout(timeout)
                    s.connect(self.endpoint)
                    self._health_sock = s
                    self._health_file = s.makefile(
                        "rw", encoding="utf-8"
                    )
                self._health_sock.settimeout(timeout)
                self._health_file.write(json.dumps(doc) + "\n")
                self._health_file.flush()
                line = self._health_file.readline()
                if not line:
                    raise OSError("health connection EOF")
                return json.loads(line)
            except (OSError, ValueError, faults_mod.FaultError) as exc:
                self._drop_health()
                raise FleetError(
                    f"engine {self.engine_id}: health scrape failed "
                    f"({exc})"
                ) from exc

    def _drop_health(self) -> None:
        if self._health_sock is not None:
            try:
                self._health_sock.close()
            except OSError:
                pass
        self._health_sock = None
        self._health_file = None

    # -- lifecycle -----------------------------------------------------

    def kill_socket(self) -> None:
        """Tear the connection (watchdog path): the reader unwinds
        through EOF and the router's death handler requeues the routed
        jobs."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._health_lock:
            self._drop_health()

    def close(self) -> None:
        """Intentional close (router shutdown): no death handling."""
        self._closing = True
        self.kill_socket()

    # -- reader --------------------------------------------------------

    def _reader(self) -> None:
        try:
            for line in self._fin:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # torn line mid-death: the EOF follows
                jid = ev.get("id")
                waiter = self._waiter
                if jid is not None and not (
                    waiter is not None
                    and protocol.doc_event(ev) in ("accepted", "error")
                    and jid == waiter[0]
                ):
                    if self._on_event is not None:
                        self._on_event(self, ev)
                    continue
                if jid is None:
                    # A timed-out id-less request's late reply is
                    # still ahead of the current request's in the
                    # per-connection order — drop it, don't answer
                    # the wrong waiter with it.
                    with self._skip_lock:
                        if self._skip_replies > 0:
                            self._skip_replies -= 1
                            continue
                if waiter is not None:
                    waiter[1].put(ev)
                # else: unsolicited control event (dropped)
        except (OSError, ValueError):
            pass  # torn connection: fall through to death handling
        finally:
            self.alive = False
            waiter = self._waiter
            if waiter is not None:
                waiter[1].put(
                    protocol.ev_error("engine connection lost")
                )
            if not self._closing and self._on_death is not None:
                self._on_death(self)


# ---------------------------------------------------------------------------
# Routed jobs
# ---------------------------------------------------------------------------


class RoutedJob:
    """Router-held state of one client job: the sanitized submit
    document (re-submittable verbatim), the affinity token, the engine
    currently running it, the count of hits already forwarded
    downstream (the exactly-once mute), and the last router-held
    checkpoint (the crash-replay origin)."""

    def __init__(self, job_id: str, kind: str, doc: dict, token: str,
                 emit: Optional[Callable]) -> None:
        self.id = job_id
        self.kind = kind  # 'crack' | 'candidates'
        self.doc = doc
        self.token = token
        self.emit = emit
        self.link: Optional[EngineLink] = None
        self.n_forwarded = 0
        #: admission-control identity (PERF.md §27): the submit doc's
        #: ``tenant`` field; jobs without one share the anonymous
        #: tenant and are exempt from the per-tenant cap.
        self.tenant: Optional[str] = None
        #: absolute shed deadline (monotonic) from the doc's
        #: ``deadline_s`` — deadline-carrying jobs are shed FIRST under
        #: overload, and an expired pending job sheds at the next pump.
        self.deadline: Optional[float] = None
        #: last router-held checkpoint DOC (submit-time migrate-in,
        #: pause events, quarantine events) — the crash-replay origin.
        self.checkpoint: Optional[dict] = None
        self.state = "queued"  # routed|paused|done|failed|cancelled
        self.replays = 0
        #: a drain/migrate is in flight: the next paused (crack) or
        #: cancelled (candidates) event re-places instead of
        #: forwarding downstream.
        self.migrating = False
        #: deferred telemetry counter name for a requeued job that
        #: parked on the pending queue before re-placing.
        self.requeue_counter: Optional[str] = None
        #: popped from the pending queue by the requeue worker, its
        #: dispatch in flight: cancel/resume must neither settle nor
        #: re-admit a job in this window (set/cleared under the
        #: router's lock).
        self.claimed = False
        self.target: Optional[str] = None
        #: the CURRENT placement's submit request has been acked by
        #: the engine.  False while a dispatch is in flight — that
        #: dispatching thread owns any failure, so the death handler
        #: must not also requeue the job (double ownership would run
        #: a ghost sweep under a table entry the dispatcher deletes).
        self.acked = False
        self.settled = threading.Event()
        #: split scatter (PERF.md §31): the k-way merge when this job
        #: IS scattered across engines (its hits arrive through the
        #: shards; ``link`` stays None).
        self.split: "Optional[_SplitMerge]" = None
        #: ``(index, count)`` when this job IS one scattered stripe of
        #: ``parent`` — its events route into the parent's merge, and
        #: crash-replay of its range counts as a reassignment.
        self.shard: Optional[Tuple[int, int]] = None
        self.parent: "Optional[RoutedJob]" = None
        #: the explicit ``split`` op's park handshake: set while the
        #: op waits for the running job's pause→checkpoint round trip
        #: (the paused event signals it instead of reaching the
        #: client).
        self.splitting: Optional[threading.Event] = None

    @property
    def unsettled(self) -> bool:
        return self.state in ("queued", "routed", "paused")


# ---------------------------------------------------------------------------
# Split-job hit-stream merging (PERF.md §31)
# ---------------------------------------------------------------------------


class _SplitMerge:
    """Router-held merge state of ONE split job: N shards stream
    (word,rank)-ordered hits off disjoint rank-stride pod stripes;
    this k-way merge releases them downstream as one globally
    (word,rank)-ordered, exactly-once client stream.

    Release discipline: the global minimum across the shard buffers
    releases only while no LIVE shard with an empty buffer could still
    produce an earlier key — each shard's stream is (word,rank)-
    monotone (the pod lattice walks blocks in global order), so its
    last seen key (``_marks``) is a safe lower bound on everything it
    will produce next.  A shard that ended stops gating.  Nothing
    releases before :meth:`arm` — a scatter that fails mid-way must
    leave the client stream untouched for the solo fallback — and
    every release happens under the merge lock so the client sees one
    serialized ordered stream.

    Exactly-once across reassignment comes free from the §20/§26
    crash-replay discipline: a dead shard resubmits from its last
    router-held checkpoint with ``replay_mute`` = hits it already fed
    THIS merge, so the replacement engine withholds exactly the
    deterministic prefix the buffers already hold."""

    def __init__(self, router: "FleetRouter", job: RoutedJob,
                 n: int) -> None:
        self.router = router
        self.job = job
        self.n = n
        self.shards: List[RoutedJob] = []
        self._bufs: List[deque] = [deque() for _ in range(n)]
        #: last merge key seen per shard (None = nothing yet).
        self._marks: List[Optional[Tuple[int, int]]] = [None] * n
        #: terminal state per shard (None = still streaming).
        self._ended: List[Optional[str]] = [None] * n
        #: the done event per shard (the parent's totals source).
        self._stats: List[Optional[dict]] = [None] * n
        self._armed = False
        self._finished = False
        self._failure: Optional[dict] = None
        self._lock = threading.Lock()
        ck = job.checkpoint or {}
        #: the scattered checkpoint's emitted counter: every shard
        #: resumes from the SAME doc, so each shard's done counters
        #: include this prefix once — the parent's total subtracts the
        #: duplicate n-1 copies.
        self._ck_emitted = int(ck.get("n_emitted", 0) or 0)
        self._resumed = job.checkpoint is not None

    def shard_emit(self, i: int) -> Callable:
        """The shard's ``RoutedJob.emit``: the router's ordinary event
        plane forwards shard events here instead of to a client."""
        def emit(ev: dict, _i: int = i) -> None:
            self.on_event(_i, ev)
        return emit

    def arm(self) -> None:
        """Open the client valve — called once, after every shard
        dispatched.  Hits that streamed during the scatter drain now;
        terminals that landed early finish now."""
        with self._lock:
            self._armed = True
            self._release_locked(self._drain_locked())
        self._maybe_finish()

    # -- event plane (shard emit callbacks, reader threads) ------------

    def on_event(self, i: int, ev: dict) -> None:
        event = protocol.doc_event(ev)
        if event == "hit":
            self._merge_round(i, ev)
            return
        if event == "done":
            shard = self.shards[i]
            engine = shard.link.engine_id if shard.link else None
            with self._lock:
                self._ended[i] = "done"
                self._stats[i] = ev
                self._release_locked(self._drain_locked())
                armed = self._armed
            if armed:
                self.router._forward(self.job, protocol.ev_shard_done(
                    self.job.id, shard=i, shards=self.n,
                    engine=engine, n_hits=ev.get("n_hits"),
                ))
        elif event == "failed":
            first = False
            with self._lock:
                self._ended[i] = "failed"
                if self._failure is None:
                    self._failure = ev
                    first = True
                armed = self._armed
            if first and armed:
                # One stripe is unrecoverable (replay budget spent):
                # the whole job fails — stop the siblings burning
                # device time on ranges nobody will merge.
                self._cancel_live(exclude=i)
        elif event == "cancelled":
            with self._lock:
                self._ended[i] = "cancelled"
        else:
            # Informational per-job events (refused, ...) pass through
            # re-labeled with the parent id.
            with self._lock:
                armed = self._armed
            if armed:
                ev2 = dict(ev)
                ev2["id"] = self.job.id
                self.router._forward(self.job, ev2)
            return
        self._maybe_finish()

    def _merge_round(self, i: int, ev: dict) -> None:
        """One shard hit through the merge (``audit_merge_loop`` pins
        this shape): the ONE unconditional host decode — the rank
        string parses exactly once, here, never per-shard in the drain
        bookkeeping — then lock-held bounded buffering: the shard's
        buffer takes the hit and the drain pops every releasable head
        before the lock drops, so a stalled sibling bounds the buffer
        at its stripe lag, never at the whole keyspace."""
        key = (ev["word_index"], int(ev["rank"]))
        with self._lock:
            self._bufs[i].append((key, ev))
            self._marks[i] = key
            self._release_locked(self._drain_locked())

    def _drain_locked(self) -> List[dict]:
        """Pop every releasable buffered hit, in global key order
        (caller holds ``_lock``)."""
        out: List[dict] = []
        if not self._armed:
            return out
        while True:
            best: Optional[Tuple[int, int]] = None
            src = -1
            for k in range(self.n):
                if self._bufs[k] and (
                    best is None or self._bufs[k][0][0] < best
                ):
                    best = self._bufs[k][0][0]
                    src = k
            if best is None:
                return out
            blocked = any(
                self._ended[k] is None and not self._bufs[k]
                and (self._marks[k] is None or self._marks[k] < best)
                for k in range(self.n)
            )
            if blocked:
                return out
            out.append(self._bufs[src].popleft()[1])

    def _release_locked(self, evs: List[dict]) -> None:
        """Forward merged hits downstream as the PARENT's hits (caller
        holds ``_lock`` — releases serialize).  Rebuilt through the
        typed constructor so key order matches a solo engine's stream
        byte for byte."""
        job = self.job
        for ev in evs:
            job.n_forwarded += 1
            self.router._forward(job, protocol.ev_hit(
                job.id,
                digest=ev["digest"],
                plain_hex=ev["plain_hex"],
                word_index=ev["word_index"],
                rank=ev["rank"],
            ))

    # -- completion ----------------------------------------------------

    def _maybe_finish(self) -> None:
        with self._lock:
            if (
                self._finished or not self._armed
                or any(e is None for e in self._ended)
            ):
                return
            self._finished = True
            ended = list(self._ended)
            stats = [s for s in self._stats if s is not None]
            if all(e == "done" for e in ended):
                # All stripes drained: nothing gates — flush.
                self._release_locked(self._drain_locked())
        job = self.job
        job.split = None
        if all(e == "done" for e in ended):
            n_emitted = sum(
                int(s.get("n_emitted", 0)) for s in stats
            ) - (self.n - 1) * self._ck_emitted
            wall = max(
                (float(s.get("wall_s", 0.0)) for s in stats),
                default=0.0,
            )
            self.router._forward(job, protocol.ev_done(
                job.id, n_hits=job.n_forwarded,
                n_emitted=n_emitted, wall_s=wall,
                resumed=self._resumed,
            ))
            self.router._settle(job, "done")
        elif self._failure is not None:
            ev = dict(self._failure)
            ev["id"] = job.id
            self.router._forward(job, ev)
            self.router._settle(job, "failed")
        else:
            self.router._forward(job, protocol.ev_cancelled(job.id))
            self.router._settle(job, "cancelled")

    # -- control -------------------------------------------------------

    def cancel(self) -> None:
        """Client cancel of the split parent: cancel every live
        shard; the merge finishes ``cancelled`` once they all park."""
        self._cancel_live(exclude=None)
        self._maybe_finish()

    def _cancel_live(self, exclude: Optional[int]) -> None:
        router = self.router
        for j, s in enumerate(self.shards):
            if j == exclude:
                continue
            with self._lock:
                if self._ended[j] is not None:
                    continue
            link = s.link
            if s.state == "routed" and link is not None:
                try:
                    link.send(protocol.op_cancel(s.id))
                except (OSError, FleetError,
                        faults_mod.FaultError):
                    pass  # dying link: crash-replay owns the shard
            else:
                # Reassignment parked it on the pending queue (or it
                # sits paused): settle it router-side.
                with router._lock:
                    pending = s in router._pending and not s.claimed
                    if pending:
                        router._pending.remove(s)
                if pending or (s.state == "paused" and not s.claimed):
                    router._settle(s, "cancelled")
                    with self._lock:
                        self._ended[j] = "cancelled"


# ---------------------------------------------------------------------------
# The router
# ---------------------------------------------------------------------------


class FleetRouter:
    """Front-end router over a pool of engines (PERF.md §25).

    ``place``: ``'affinity'`` (default — co-locate equal-token jobs,
    tie-break by load) or ``'round-robin'`` (the A/B control arm).
    ``replay_budget``: checkpoint-bearing ``failed`` events (engine
    quarantine) are resubmitted to another engine this many times per
    job before the failure reaches the client.  ``poll_s``: health
    scrape cadence (0 disables the poller — tests drive scrapes
    manually); an engine missing ``poll_misses`` consecutive scrapes
    (or whose process exited) is declared dead and its jobs
    crash-replay.  ``defaults``: the SweepConfig the ENGINES were
    started with — used only to fill doc-level gaps when computing
    affinity tokens, so attach-mode routers should pass the engines'
    flags (a mismatch degrades placement, never correctness).

    Elastic knobs (PERF.md §27).  ``engine_capacity``: routed jobs one
    engine accepts before placements queue (0 = unbounded — the PR 13
    behavior); ``max_pending``: the bounded router-side pending queue;
    ``per_tenant``: in-flight cap per submit-doc ``tenant`` (0 = off);
    ``shed_policy``: what a full pending queue does to a new submit —
    ``reject`` it typed (default), shed the ``oldest`` pending job to
    admit it, or ``queue`` unboundedly (the legacy escape hatch; the
    overload-semantics rule in CONTRIBUTING says don't).  Health
    ladder: ``degrade_after`` consecutive strained scrapes mark an
    engine degraded (places last), ``quarantine_after`` mark it
    quarantined (never places; the autoscaler drains + replaces),
    ``recover_after`` clean scrapes walk degraded back to healthy, and
    ``quarantine_replays`` checkpoint-bearing job failures quarantine
    the engine outright.  ``poll_jitter``: per-engine fraction of
    ``poll_s`` each engine's scrape tick is deterministically offset
    by, so N engines never stampede one tick."""

    def __init__(self, *, place: str = "affinity",
                 replay_budget: int = 1, poll_s: float = 2.0,
                 poll_misses: int = 3, defaults: Optional[Any] = None,
                 control_timeout: float = 120.0,
                 engine_capacity: int = 0, max_pending: int = 256,
                 per_tenant: int = 0, shed_policy: str = "reject",
                 degrade_after: int = 1, quarantine_after: int = 3,
                 recover_after: int = 2, quarantine_replays: int = 2,
                 poll_jitter: float = 0.25,
                 split: Optional[str] = None,
                 split_threshold: int = 4096) -> None:
        if place not in ("affinity", "round-robin"):
            raise ValueError(
                f"place must be affinity|round-robin, got {place!r}"
            )
        if shed_policy not in ("reject", "queue", "oldest"):
            raise ValueError(
                f"shed_policy must be reject|queue|oldest, got "
                f"{shed_policy!r}"
            )
        if split not in (None, "auto", "on", "off"):
            raise ValueError(
                f"split must be auto|on|off, got {split!r}"
            )
        self._place = place
        self._replay_budget = int(replay_budget)
        self._poll_s = float(poll_s)
        self._poll_misses = int(poll_misses)
        self._defaults = defaults
        self._control_timeout = float(control_timeout)
        self._engine_capacity = int(engine_capacity)
        self._max_pending = int(max_pending)
        self._per_tenant = int(per_tenant)
        self._shed_policy = shed_policy
        self._degrade_after = max(1, int(degrade_after))
        self._quarantine_after = max(1, int(quarantine_after))
        self._recover_after = max(1, int(recover_after))
        self._quarantine_replays = max(1, int(quarantine_replays))
        self._poll_jitter = max(0.0, float(poll_jitter))
        #: giant-job striping (PERF.md §31): None = the A5GEN_SPLIT
        #: env hatch decides (``auto`` by default); the threshold is
        #: the ``auto`` mode's oversized floor in WORDS (a word expands
        #: to ≥1 lattice blocks, so it lower-bounds the block count).
        self._split = split
        self._split_threshold = int(split_threshold)
        self._links: List[EngineLink] = []
        self._jobs: Dict[str, RoutedJob] = {}
        #: admission-queued jobs (FIFO), bounded by ``max_pending``
        #: unless ``shed_policy='queue'``; mutated under ``_lock``.
        self._pending: List[RoutedJob] = []
        #: unsettled jobs per explicit tenant (the ``per_tenant``
        #: in-flight cap's ledger); mutated under ``_lock``.
        self._tenant_counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._rr = itertools.count()
        self._closed = False
        #: the attached Autoscaler (None = fixed pool); set once by
        #: ``Autoscaler.bind`` before any scaling runs.
        self.autoscaler = None
        #: fleet counters report as since-THIS-router deltas (the
        #: Engine.stats() convention): the registry is process-wide,
        #: and an embedder running several routers (tests, benches)
        #: must not read its neighbors' deaths.
        self._counters0 = {
            name: int(telemetry.counter(f"fleet.{name}").value)
            for name in ("engine_deaths", "jobs_replayed",
                         "migrations", "jobs_rejected", "jobs_shed",
                         "jobs_queued", "scrape_retries",
                         "engines_quarantined", "engines_detached",
                         "jobs_split", "shards_reassigned")
        }
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        #: re-dispatch work (crash-replay, migrate's second half,
        #: quarantine resubmission) runs on THIS worker, never on a
        #: link's reader thread: a reader dispatching to its own link
        #: (the single-survivor fallback) would block the very loop
        #: that must deliver the ack.
        self._requeue: "queue.Queue" = queue.Queue()
        self._requeue_thread = threading.Thread(
            target=self._requeue_worker, name="a5-fleet-requeue",
            daemon=True,
        )
        self._requeue_thread.start()
        if self._poll_s > 0:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="a5-fleet-health",
                daemon=True,
            )
            self._poll_thread.start()

    # -- pool management -----------------------------------------------

    def attach(self, endpoint: str, engine_id: Optional[str] = None,
               *, proc: "Optional[subprocess.Popen]" = None,
               timeout: float = 180.0) -> EngineLink:
        """Connect one engine endpoint into the pool (spawned or
        pre-existing) and scrape it once so placement has signals
        before the first poll tick."""
        with self._lock:
            index = len(self._links)
        link = EngineLink.connect(
            endpoint, engine_id, timeout=timeout, proc=proc,
            index=index, on_event=self._on_job_event,
            on_death=self._on_death,
        )
        link.next_poll = time.monotonic() + self._jitter_of(link)
        with self._lock:
            self._links.append(link)
        self._scrape(link)
        # Fresh capacity: admission-queued jobs can place now.
        self._schedule_pump()
        return link

    def detach(self, engine_id: str, *, shutdown: bool = True,
               timeout: float = 30.0) -> None:
        """Remove one engine from the pool — the autoscaler's reap
        half (PERF.md §27).  The engine must be EMPTY (drained, or
        dead): detaching with jobs still routed raises loudly — drain
        first.  ``shutdown`` sends the engine its shutdown op and
        reaps a spawned process."""
        link = self._resolve(engine_id)
        with self._lock:
            if link.routed:
                raise FleetError(
                    f"engine {engine_id!r} still runs "
                    f"{len(link.routed)} job(s) — drain it before "
                    "detaching"
                )
            self._links.remove(link)
        link._closing = True
        if shutdown and link.alive:
            try:
                link.request(protocol.op_shutdown(), timeout=timeout)
            except FleetError:
                pass
        link.close()
        if shutdown and link.proc is not None:
            try:
                link.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                link.proc.kill()
                link.proc.wait()
        telemetry.counter("fleet.engines_detached").add(1)

    def engines(self) -> List[EngineLink]:
        with self._lock:
            return list(self._links)

    def pending_depth(self) -> int:
        """Admission-queued jobs right now (the autoscaler's queue-
        depth signal)."""
        with self._lock:
            return len(self._pending)

    def _resolve(self, engine_id: str) -> EngineLink:
        with self._lock:
            for link in self._links:
                if link.engine_id == engine_id:
                    return link
        raise FleetError(f"unknown engine {engine_id!r}")

    # -- placement -----------------------------------------------------

    def _doc_token(self, doc: dict) -> str:
        """The submit document's affinity token — the same
        static-trace-config prefix ``runtime.fuse.affinity_token``
        hashes engine-side.  Config gaps fill from the ENGINES'
        resolved defaults (scraped ``config_defaults`` — serve
        resolves device-dependent lanes/blocks at start, and
        ``_job_from_doc`` merges docs into exactly those), falling
        back to the router's own ``defaults``; a heterogeneous pool
        degrades placement quality, never correctness."""
        cfg = doc.get("config") or {}
        scraped: dict = {}
        for link in self.engines():
            scraped = link.scrape.get("config_defaults") or {}
            if scraped:
                break
        d = self._defaults

        def field(key: str, attr: str, fallback: Any) -> Any:
            if key in cfg:
                return cfg[key]
            if key in scraped:
                return scraped[key]
            return getattr(d, attr, fallback) if d is not None \
                else fallback

        return static_affinity_token(
            mode=doc.get("mode", "default"),
            algo=doc.get("algo", "md5"),
            table_min=int(doc.get("table_min", 0)),
            table_max=int(doc.get("table_max", 15)),
            lanes=field("lanes", "lanes", None),
            num_blocks=field("blocks", "num_blocks", None),
            superstep=field("superstep", "superstep", None),
            devices=field("devices", "devices", 1),
            pair=field("pair", "pair", None),
        )

    def _resident_tokens(self, link: EngineLink) -> set:
        """The engine's resident affinity tokens as the router sees
        them: its own routing table (authoritative for jobs IT placed)
        unioned with the engine's last-scraped ``resident_groups`` (so
        an attach-mode router also respects jobs other clients run
        directly against the engine)."""
        toks = set(link.scrape.get("resident_groups") or ())
        with self._lock:
            for jid in link.routed:
                job = self._jobs.get(jid)
                if job is not None and job.token:
                    toks.add(job.token)
        return toks

    def _load_score(self, link: EngineLink) -> tuple:
        return (
            # Circuit half-open: a degraded engine places only when
            # every healthy one loses the tie (PERF.md §27).
            0 if link.health == "healthy" else 1,
            len(link.routed),
            scraped_load(link.scrape),
            link.index,
        )

    def _pick(self, token: str,
              exclude: Sequence[EngineLink] = ()) -> EngineLink:
        with self._lock:
            live = [
                l for l in self._links
                if l.alive and not l.draining
                and l.health != "quarantined"
            ]
            any_alive = any(l.alive for l in self._links)
        if not live:
            if any_alive:
                # Every alive engine is quarantined or draining:
                # capacity is being replaced (the autoscaler's
                # replacement-first discipline), so this is OVERLOAD,
                # not absence — queue bounded / reject typed, never
                # an untyped hard failure mid-degradation.
                raise _NoCapacity(
                    "every live engine is quarantined or draining "
                    "(replacement capacity is on the way)"
                )
            raise FleetError("no live engine to place the job on")
        pool = [l for l in live if l not in exclude] or live
        if self._engine_capacity > 0:
            with self._lock:
                fits = [
                    l for l in pool
                    if len(l.routed) < self._engine_capacity
                ]
            if not fits:
                raise _NoCapacity(
                    "every live engine is at engine_capacity "
                    f"({self._engine_capacity})"
                )
            pool = fits
        if self._place == "round-robin":
            return pool[next(self._rr) % len(pool)]
        matches = [
            l for l in pool if token and token in
            self._resident_tokens(l)
        ]
        return min(matches or pool, key=self._load_score)

    # -- client surface (the serve protocol, routed) -------------------

    def submit(self, doc: dict, emit: Optional[Callable] = None) -> dict:
        """Route one submit document; returns the ``accepted`` event to
        forward downstream.  The document passes through UNCHANGED to
        the placed engine (clients keep their serve contract), except
        the router strips and holds a migrate-in ``checkpoint`` as the
        job's replay origin and re-injects it on dispatch.

        Admission control (PERF.md §27): a submit that cannot place
        (every engine at ``engine_capacity``) queues on the bounded
        pending list and the ack carries ``"queued": true``; a FULL
        pending list rejects typed (:class:`FleetOverloaded`) — or, under
        ``shed_policy='oldest'``, sheds the oldest pending job (deadline
        carriers first) to admit this one.  A tenant over its
        ``per_tenant`` in-flight cap rejects typed without queueing."""
        if self._closed:
            raise FleetError("router is shut down")
        jid = doc.get("id") or f"fleet-{next(self._ids)}"
        kind = "crack" if (
            "digests" in doc or "digest_list" in doc
        ) else "candidates"
        ck = doc.get("checkpoint")
        if ck is not None:
            # Capture-time validation (PERF.md §27): a malformed
            # migrate-in checkpoint fails the SUBMIT typed, not the
            # eventual crash-replay resubmit.
            validate_checkpoint_doc(ck)
        sdoc = {k: v for k, v in doc.items()
                if k not in ("checkpoint", "replay_mute")}
        sdoc["id"] = jid
        protocol.op_submit(sdoc)
        job = RoutedJob(jid, kind, sdoc, self._doc_token(sdoc), emit)
        job.checkpoint = ck
        job.n_forwarded = int(doc.get("replay_mute", 0))
        tenant = doc.get("tenant")
        job.tenant = str(tenant) if tenant is not None else None
        if doc.get("deadline_s") is not None:
            job.deadline = time.monotonic() + float(doc["deadline_s"])
        with self._lock:
            prev = self._jobs.get(jid)
            if prev is not None and prev.unsettled:
                raise FleetError(f"job id {jid!r} is still active")
            if (
                self._per_tenant > 0 and job.tenant is not None
                and self._tenant_counts.get(job.tenant, 0)
                >= self._per_tenant
            ):
                telemetry.counter("fleet.jobs_rejected").add(1)
                raise FleetOverloaded(
                    f"tenant {job.tenant!r} has "
                    f"{self._tenant_counts[job.tenant]} jobs in "
                    f"flight (per_tenant cap {self._per_tenant})",
                    self._retry_after_locked(),
                )
            self._jobs[jid] = job
            if job.tenant is not None:
                self._tenant_counts[job.tenant] = \
                    self._tenant_counts.get(job.tenant, 0) + 1
        try:
            ack = None
            n_split = self._auto_split_width(job, doc)
            if n_split >= 2:
                # Oversized: scatter across engines (PERF.md §31).  A
                # part-failed scatter unwinds to None and the job
                # falls through to the ordinary solo dispatch.
                ack = self._split_scatter(job, n_split, strict=False)
            if ack is None:
                ack = dict(self._dispatch(job))
        except _NoCapacity:
            ack = self._enqueue_pending(job)
        except (FleetError, faults_mod.FaultError):
            # Never admitted anywhere (engine rejection, or an injected
            # router.place fault): drop the table entry so the client
            # can retry under the same id.
            self._forget(job)
            raise
        ack["engine"] = job.link.engine_id if job.link else None
        telemetry.counter("fleet.jobs_routed").add(1)
        return ack

    def _forget(self, job: RoutedJob) -> None:
        """Unregister a job that was never admitted anywhere (rejected
        or failed before placement) so the client can retry its id."""
        with self._lock:
            if self._jobs.get(job.id) is job:
                del self._jobs[job.id]
            self._tenant_release_locked(job)

    def _tenant_release_locked(self, job: RoutedJob) -> None:
        """Release ``job``'s per-tenant in-flight slot (caller holds
        ``_lock``) — the ONE decrement both the never-admitted and the
        terminal-settle paths share."""
        if job.tenant is not None and job.tenant in \
                self._tenant_counts:
            self._tenant_counts[job.tenant] -= 1
            if self._tenant_counts[job.tenant] <= 0:
                del self._tenant_counts[job.tenant]

    def _retry_after(self) -> float:
        """The overload rejection's backoff estimate: one poll cadence
        scaled by how deep the backlog stands per live engine — coarse,
        monotone in load, and cheap (no scrape)."""
        with self._lock:
            return self._retry_after_locked()

    def _enqueue_pending(self, job: RoutedJob, *,
                         forget_on_reject: bool = True) -> dict:
        """Queue one admitted-but-unplaceable job on the bounded
        pending list; returns the synthesized ``accepted`` ack.  A full
        list applies ``shed_policy`` (PERF.md §27): ``oldest`` evicts a
        pending job (deadline carriers first) to admit the newcomer,
        ``reject`` refuses the newcomer typed, ``queue`` grows
        unboundedly (the legacy escape hatch).  ``forget_on_reject``:
        a rejected fresh SUBMIT drops its table entry (the id stays
        retryable); a rejected RESUME must keep the job — it is
        already admitted, paused, and holding its checkpoint."""
        victim: Optional[RoutedJob] = None
        overloaded: Optional[FleetOverloaded] = None
        with self._lock:
            if (
                len(self._pending) >= self._max_pending
                and self._shed_policy == "oldest"
            ):
                victim = self._shed_victim_locked()
            if (
                len(self._pending) >= self._max_pending
                and self._shed_policy != "queue"
            ):
                overloaded = FleetOverloaded(
                    f"router pending queue is full ({self._max_pending}"
                    " jobs; every engine at capacity)",
                    self._retry_after_locked(),
                )
            else:
                self._pending.append(job)
        if victim is not None:
            self._shed(victim, "pending queue full: oldest-policy "
                               "eviction for a newer arrival")
        if overloaded is not None:
            if forget_on_reject:
                self._forget(job)
            telemetry.counter("fleet.jobs_rejected").add(1)
            raise overloaded
        telemetry.counter("fleet.jobs_queued").add(1)
        return protocol.ev_accepted(job.id, job.kind, queued=True)

    def _retry_after_locked(self) -> float:
        depth = len(self._pending)
        alive = sum(
            1 for l in self._links
            if l.alive and not l.draining
            and l.health != "quarantined"
        )
        base = self._poll_s if self._poll_s > 0 else 1.0
        return round(max(0.5, base) * (1.0 + depth / max(1, alive)), 3)

    def _shed_victim_locked(self) -> Optional[RoutedJob]:
        """Pick (and remove) the pending job to shed: deadline
        carriers first — soonest deadline — then the oldest arrival
        (PERF.md §27: a job that declared a deadline already agreed
        staleness is failure; shedding it costs the least)."""
        if not self._pending:
            return None
        deadline_jobs = [
            j for j in self._pending if j.deadline is not None
        ]
        if deadline_jobs:
            victim = min(deadline_jobs, key=lambda j: j.deadline)
            self._pending.remove(victim)
            return victim
        return self._pending.pop(0)

    def _shed(self, job: RoutedJob, reason: str) -> None:
        """Fail one shed job downstream with the typed overload event
        (checkpoint attached when the router holds one — a shed
        migrate-in loses no progress)."""
        telemetry.counter("fleet.jobs_shed").add(1)
        self._forward(job, protocol.ev_failed(
            job.id, "overloaded",
            reason=reason,
            retry_after_s=self._retry_after(),
            checkpoint=job.checkpoint,
        ))
        self._settle(job, "failed")

    def pause(self, jid: str) -> None:
        job = self._job(jid)
        if job.split is not None:
            raise FleetError(
                f"job {jid!r} is split across engines — it has no "
                "single pause point; cancel it or let it finish"
            )
        if job.shard is not None:
            raise FleetError(
                f"job {jid!r} is a split shard — operate on its "
                f"parent {job.parent.id!r}"
            )
        if job.state != "routed" or job.link is None:
            raise FleetError(f"job {jid!r} is {job.state}, not running")
        job.link.send(protocol.op_pause(jid))

    def resume(self, jid: str) -> dict:
        """Re-place a paused job from its router-held checkpoint;
        returns the ``accepted`` event (``resumed`` flagged) to
        forward downstream.  Under admission control a resume with no
        free capacity queues like a submit would."""
        job = self._job(jid)
        if job.shard is not None:
            raise FleetError(
                f"job {jid!r} is a split shard — the router owns its "
                "lifecycle"
            )
        with self._lock:
            # ONE atomic read of the admission state: a state check
            # outside this lock could interleave with the pump
            # completing a queued resume's dispatch (queued→routed)
            # and let a retry double-dispatch the running id.
            queued = job in self._pending or job.claimed
            paused = job.state == "paused"
        if queued:
            # Already admission-queued by an earlier resume (or being
            # dispatched by the pump right now): the retry is
            # idempotent — never a second pending entry or a second
            # dispatch of a running id.
            return protocol.ev_accepted(
                jid, job.kind, queued=True, resumed=True
            )
        if not paused:
            raise FleetError(f"job {jid!r} is {job.state}, not paused")
        try:
            ack = dict(self._dispatch(job))
        except _NoCapacity:
            # An overloaded-too reject must NOT forget an already-
            # admitted job: it stays paused, checkpoint intact, and
            # the client retries the resume after retry_after_s.
            ack = self._enqueue_pending(job, forget_on_reject=False)
        ack["resumed"] = True
        return ack

    # -- giant-job striping (PERF.md §31) ------------------------------

    def _placeable_width(self) -> int:
        """Engines a scatter could stripe across right now."""
        with self._lock:
            return sum(
                1 for l in self._links
                if l.alive and not l.draining
                and l.health != "quarantined"
            )

    def _auto_split_width(self, job: RoutedJob, doc: dict) -> int:
        """How many stripes a fresh submit should scatter across (0 =
        keep it solo).  Gates: the resolved split mode (ctor >
        A5GEN_SPLIT > ``auto``); crack jobs only (candidates output is
        engine-local); an explicit client ``config.pod`` wins (the
        client already striped it); ``superstep: 0`` has no block
        lattice to stripe; ``auto`` requires an oversized inline
        wordlist (``split_threshold`` words) so fleet-of-small-jobs
        traffic never pays scatter overhead; and at least two
        placeable engines must exist."""
        mode = self._split
        if mode is None:
            from .env import split_setting

            mode = split_setting()
        if mode == "off" or job.kind != "crack":
            return 0
        cfg = job.doc.get("config") or {}
        if cfg.get("pod") is not None:
            return 0
        superstep = cfg.get("superstep")
        if superstep is None:
            superstep = getattr(self._defaults, "superstep", None)
        if superstep == 0:
            return 0
        words = doc.get("words")
        if not isinstance(words, list):
            return 0
        if mode != "on" and len(words) < self._split_threshold:
            return 0
        n = self._placeable_width()
        return n if n >= 2 else 0

    def _split_scatter(self, job: RoutedJob, n: int, *,
                       strict: bool) -> Optional[dict]:
        """Scatter one admitted crack job across ``n`` engines as
        disjoint ``config.pod = [i, n]`` rank-stride stripes, each a
        full resubmittable job doc riding the job's checkpoint (pod
        cursors are GLOBAL, so every shard resumes from the SAME doc
        and walks only its stripe) with already-forwarded hits muted.
        On success the merge arms and the parent streams through it.
        On any placement failure the scatter unwinds completely —
        nothing reached the client — and either returns None
        (``strict=False``: submit falls back to solo dispatch) or
        raises typed (``strict=True``: the explicit op's job stays
        paused, checkpoint intact)."""
        merge = _SplitMerge(self, job, n)
        shards: List[RoutedJob] = []
        for i in range(n):
            sdoc = dict(job.doc)
            cfg = dict(sdoc.get("config") or {})
            cfg["pod"] = [i, n]
            sdoc["config"] = cfg
            sdoc["id"] = f"{job.id}::s{i}"
            protocol.op_submit(sdoc)
            shard = RoutedJob(sdoc["id"], "crack", sdoc, job.token,
                              merge.shard_emit(i))
            shard.shard = (i, n)
            shard.parent = job
            shard.checkpoint = job.checkpoint
            # Double duty, both correct: the mute each dispatch sends
            # (the checkpoint prefix is already client-forwarded) AND
            # the shard's forwarded counter (replayed hits never
            # re-enter the merge).
            shard.n_forwarded = job.n_forwarded
            shards.append(shard)
        merge.shards = shards
        with self._lock:
            for shard in shards:
                self._jobs[shard.id] = shard
            job.split = merge
            job.state = "routed"
        used: List[EngineLink] = []
        try:
            for shard in shards:
                # Affinity would co-locate equal-token stripes: spread
                # them instead — distinct engines are the whole win.
                self._dispatch(shard, tuple(used))
                if shard.link is not None and shard.link not in used:
                    used.append(shard.link)
        except (FleetError, faults_mod.FaultError) as exc:
            self._split_undo(job, shards)
            if strict:
                raise FleetError(
                    f"split of {job.id!r} failed mid-scatter: {exc} "
                    "(the job is intact — resume it solo or retry)"
                ) from exc
            return None
        merge.arm()
        telemetry.counter("fleet.jobs_split").add(1)
        return protocol.ev_accepted(job.id, job.kind, shards=n)

    def _split_undo(self, job: RoutedJob,
                    shards: List[RoutedJob]) -> None:
        """Unwind a part-placed scatter: the merge never armed, so no
        hit reached the client — cancel the placed stripes (their
        buffered hits die with the merge) and drop the unplaced shard
        entries; the job returns to its pre-scatter admission state."""
        for shard in shards:
            link = shard.link
            if link is not None:
                try:
                    link.send(protocol.op_cancel(shard.id))
                except (OSError, FleetError, faults_mod.FaultError):
                    pass  # dying link: its death path settles the id
        with self._lock:
            job.split = None
            job.state = "paused" if job.checkpoint is not None \
                else "queued"
            for shard in shards:
                if shard.link is None:
                    if self._jobs.get(shard.id) is shard:
                        del self._jobs[shard.id]
                    shard.state = "cancelled"
                    shard.settled.set()

    def split(self, jid: str, shards: Optional[int] = None) -> dict:
        """The explicit ``split`` op (PERF.md §31): scatter one
        admitted crack job across engines mid-flight.  A RUNNING job
        parks first (pause → checkpoint over the wire — the same §20
        discipline migrate rides; the paused event signals the park
        instead of reaching the client), then the checkpoint scatters
        as N disjoint pod stripes with already-forwarded hits muted; a
        PAUSED job scatters directly.  Returns the ``accepted`` ack
        with ``shards`` set."""
        job = self._job(jid)
        if job.kind != "crack":
            raise FleetError(
                f"job {jid!r} is {job.kind} — only crack jobs split "
                "(candidates output is engine-local)"
            )
        if job.split is not None or job.shard is not None:
            raise FleetError(f"job {jid!r} is already split")
        if (job.doc.get("config") or {}).get("pod") is not None:
            raise FleetError(
                f"job {jid!r} already carries a client pod stripe"
            )
        n_live = self._placeable_width()
        n = int(shards) if shards is not None else n_live
        n = min(n, max(n_live, 1))
        if n < 2:
            raise FleetError(
                "split needs at least 2 placeable engines (have "
                f"{n_live})"
            )
        if job.state == "routed" and job.link is not None:
            parked = threading.Event()
            job.splitting = parked
            job.link.send(protocol.op_pause(jid))
            if not parked.wait(self._control_timeout):
                job.splitting = None
                raise FleetError(
                    f"job {jid!r} did not park for split within "
                    f"{self._control_timeout:g}s"
                )
        if job.state != "paused":
            raise FleetError(
                f"job {jid!r} is {job.state}, not splittable"
            )
        return self._split_scatter(job, n, strict=True)

    def cancel(self, jid: str) -> None:
        job = self._job(jid)
        if job.split is not None:
            job.split.cancel()
            return
        if job.shard is not None:
            raise FleetError(
                f"job {jid!r} is a split shard — cancel its parent "
                f"{job.parent.id!r}"
            )
        if job.state == "routed" and job.link is not None:
            job.link.send(protocol.op_cancel(jid))
            return
        with self._lock:
            # Claim-by-removal: once this cancel takes the job OFF the
            # pending list, the pump can never pop it; conversely a
            # job the pump already claimed is dispatch-in-flight and
            # must be cancelled engine-side once it binds (retry).
            claimed = job.claimed
            queued = job in self._pending and not claimed
            if queued:
                self._pending.remove(job)
        if (job.state == "paused" and not claimed) or queued:
            # Nothing runs engine-side (paused, or still admission-
            # queued): settle here and tell the client ourselves.
            self._forward(job, protocol.ev_cancelled(jid))
            self._settle(job, "cancelled")
            return
        raise FleetError(f"job {jid!r} is {job.state}")

    def migrate(self, jid: str,
                engine_id: Optional[str] = None) -> dict:
        """Rebalance one running job: pause → checkpoint over the wire
        → resubmit on the target (or placement-chosen) engine, with
        already-delivered hits muted on redelivery.  Candidates jobs
        RESTART on the target instead (cancel + fresh resubmission —
        their output is engine-local).  Asynchronous: returns an ack;
        the job continues streaming on its same client session."""
        job = self._job(jid)
        if job.split is not None:
            raise FleetError(
                f"job {jid!r} is split across engines — its stripes "
                "rebalance individually (drain moves them; cancel "
                "the parent to stop them)"
            )
        if job.state != "routed" or job.link is None:
            raise FleetError(f"job {jid!r} is {job.state}, not running")
        if engine_id is not None:
            self._resolve(engine_id)  # fail loudly before pausing
            if engine_id == job.link.engine_id:
                return protocol.ev_migrating(
                    jid, frm=engine_id, to=engine_id, noop=True
                )
        job.target = engine_id
        job.migrating = True
        telemetry.counter("fleet.migrations").add(1)
        if job.kind == "crack":
            job.link.send(protocol.op_pause(jid))
        else:
            job.link.send(protocol.op_cancel(jid))
        return protocol.ev_migrating(
            jid, frm=job.link.engine_id,
            to=engine_id or "(placement)",
        )

    def drain(self, engine_id: str) -> dict:
        """Empty one engine for shutdown: no new placements land on
        it, and every job routed there migrates off (placement picks
        the targets).  Returns the count of jobs set migrating."""
        link = self._resolve(engine_id)
        link.draining = True
        with self._lock:
            jids = [
                jid for jid in link.routed
                if (j := self._jobs.get(jid)) is not None
                and j.state == "routed" and not j.migrating
            ]
        for jid in jids:
            self.migrate(jid)
        return protocol.ev_draining(engine_id, len(jids))

    def stats(self) -> dict:
        """The fleet's merged ``stats`` event: per-engine scrapes
        summed (so serve clients reading job counts keep working) plus
        a ``fleet`` section with per-engine detail and the router's
        own counters."""
        agg: dict = {}
        members = []
        for link in self.engines():
            s = dict(link.scrape)
            if link.alive:
                try:
                    s = self._scrape(link)
                except FleetError:
                    pass  # poller/watchdog owns the death call
            if link.alive:
                # Only LIVE engines sum into the fleet aggregate — a
                # dead member's stale last scrape would double-count
                # the jobs that crash-replayed onto the survivors
                # (its detail row below still shows the final state).
                for k, v in s.items():
                    # Ratios (fill instruments) are per-engine facts —
                    # summing them across members is meaningless; the
                    # detail rows below carry them instead.
                    if isinstance(v, bool) or k in (
                        "packed_fill", "packed_fill_last",
                        "packed_fill_min", "config_defaults"
                    ):
                        continue
                    if isinstance(v, (int, float)):
                        agg[k] = agg.get(k, 0) + v
                    elif isinstance(v, dict):
                        cur = agg.setdefault(k, {})
                        for gk, gv in v.items():
                            if isinstance(gv, (int, float)) \
                                    and not isinstance(gv, bool):
                                cur[gk] = cur.get(gk, 0) + gv
            members.append({
                "engine": link.engine_id,
                "endpoint": link.endpoint,
                "alive": link.alive,
                "draining": link.draining,
                "health": link.health,
                "jobs_routed": len(link.routed),
                "resident_groups": sorted(
                    self._resident_tokens(link)
                ),
                "packed_fill": s.get("packed_fill", 0.0),
                # Post-departure fill decay + re-fuse activity
                # (PERF.md §28): the router's view of how well each
                # member keeps its fused groups tight under churn.
                "packed_fill_min": s.get("packed_fill_min", 0.0),
                "refuse_total": s.get("refuse_total", 0),
            })
        with self._lock:
            unsettled = sum(
                1 for j in self._jobs.values() if j.unsettled
            )
            pending = len(self._pending)
        fleet = {
            "place": self._place,
            "engines": members,
            "engines_alive": sum(1 for m in members if m["alive"]),
            "jobs_tracked": unsettled,
            # The admission surface (PERF.md §27): queued depth and
            # the bounds the overload semantics enforce.
            "jobs_pending": pending,
            "max_pending": self._max_pending,
            "engine_capacity": self._engine_capacity,
            "shed_policy": self._shed_policy,
            **{
                name: int(
                    telemetry.counter(f"fleet.{name}").value
                ) - base
                for name, base in self._counters0.items()
            },
        }
        scaler = self.autoscaler
        if scaler is not None:
            fleet["autoscale"] = scaler.describe()
        return protocol.ev_stats(agg, fleet=fleet)

    def metrics(self) -> dict:
        """Merged registry scrape: every live engine's snapshot (each
        labeled with its engine identity) merged with the router's own
        — counters sum fleet-wide, per-engine gauges stay per-engine
        series (``telemetry.merge``) — plus the Prometheus text."""
        snaps = []
        for link in self.engines():
            if not link.alive:
                continue
            try:
                ev = link.request(protocol.op_metrics(),
                                  timeout=self._control_timeout)
            except FleetError:
                continue
            snaps.append(ev.get("metrics") or {})
        snaps.append(telemetry.snapshot())
        merged = telemetry.merge(snaps)
        return protocol.ev_metrics(
            merged, telemetry.to_prometheus(merged)
        )

    def passthrough(self, doc: dict) -> None:
        """Forward an op the router does not interpret to the engine
        running its job — new serve ops stay fleet-compatible without
        a router release (CONTRIBUTING: router-passthrough-safe)."""
        job = self._job(doc.get("id"))
        if job.link is None:
            raise FleetError(f"job {job.id!r} is not on an engine")
        job.link.send(doc)

    def wait(self, jid: str, timeout: Optional[float] = None) -> bool:
        """Block until a job settles (done/failed/cancelled) or pauses
        — the embedder/test convenience."""
        return self._job(jid).settled.wait(timeout)

    def job(self, jid: str) -> RoutedJob:
        return self._job(jid)

    def close(self, *, shutdown_engines: bool = True,
              timeout: float = 30.0) -> None:
        """Stop routing.  ``shutdown_engines`` sends each engine the
        shutdown op (and reaps spawned processes); attach-mode callers
        pass False to leave the engines serving."""
        self._closed = True
        if self.autoscaler is not None:
            self.autoscaler.close()
        self._poll_stop.set()
        self._requeue.put(None)
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
        self._requeue_thread.join(timeout=5.0)
        for link in self.engines():
            link._closing = True
            if shutdown_engines and link.alive:
                try:
                    link.request(protocol.op_shutdown(),
                                 timeout=timeout)
                except FleetError:
                    pass
            link.close()
            if shutdown_engines and link.proc is not None:
                try:
                    link.proc.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    link.proc.kill()
                    link.proc.wait()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()

    # -- internals -----------------------------------------------------

    def _job(self, jid: str) -> RoutedJob:
        with self._lock:
            job = self._jobs.get(jid)
        if job is None:
            raise FleetError(f"unknown job id {jid!r}")
        return job

    def _dispatch(self, job: RoutedJob,
                  exclude: Sequence[EngineLink] = ()) -> dict:
        """Place (or re-place) one job: pick an engine, ship the
        document — with the router-held checkpoint and the
        exactly-once mute for crack jobs — and bind the routing
        state.  Raises :class:`FleetError` when no engine accepts.

        The binding lands BEFORE the submit request goes out: the
        engine's pump can start streaming hits the instant it accepts,
        and the link reader must already resolve them to this job — a
        bind-after-ack would drop the first fetch's hits on the
        floor."""
        # The placement seam (PERF.md §27): an injected fault fails
        # THIS placement exactly like an engine rejection — submit
        # reports it typed to the client; a requeue-time fault fails
        # the job with its checkpoint attached (the quarantine token).
        if faults_mod.ACTIVE is not None:
            faults_mod.ACTIVE.fire("router.place")
        target = job.target
        job.target = None
        link = (
            self._resolve(target) if target is not None
            else self._pick(job.token, exclude)
        )
        doc = dict(job.doc)
        # The checkpoint rides for BOTH kinds (a client-provided
        # candidates resume must keep the engine's append-resume
        # contract — the router-initiated restart paths clear
        # ``job.checkpoint`` instead); the mute is crack-only (it
        # gates the hit-delivery queue).
        if job.checkpoint is not None:
            doc["checkpoint"] = job.checkpoint
        if job.kind == "crack" and job.n_forwarded:
            doc["replay_mute"] = job.n_forwarded
        prev_state = job.state
        with self._lock:
            if job.link is not None:
                # Two dispatchers raced (e.g. concurrent resumes of
                # one id): the first bound; a second binding would
                # orphan the running placement and double-run the
                # sweep.  Every legitimate dispatch path starts from
                # link=None (fresh submit, pause, requeue, pump).
                raise FleetError(
                    f"job {job.id!r} is already bound to engine "
                    f"{job.link.engine_id}"
                )
            if (
                self._engine_capacity > 0 and target is None
                and job.id not in link.routed
                and len(link.routed) >= self._engine_capacity
            ):
                # Close the check-then-act window: _pick's capacity
                # test ran under an earlier lock acquisition, and a
                # concurrent dispatch may have bound here since —
                # re-verify at bind time so the cap cannot overshoot
                # (explicit-target migrates stay operator-privileged).
                raise _NoCapacity(
                    f"engine {link.engine_id} reached "
                    f"engine_capacity ({self._engine_capacity}) "
                    "before this placement bound"
                )
            job.link = link
            job.state = "routed"
            job.acked = False
            link.routed.add(job.id)
            job.settled.clear()
        try:
            ack = link.request(doc, timeout=self._control_timeout)
        except FleetError:
            with self._lock:
                if job.link is link:
                    job.link = None
                    job.state = prev_state
                link.routed.discard(job.id)
            raise
        with self._lock:
            job.acked = True
        return ack

    def _settle(self, job: RoutedJob, state: str) -> None:
        freed = False
        with self._lock:
            job.state = state
            if job.link is not None:
                job.link.routed.discard(job.id)
                job.link = None
                freed = True
            job.migrating = False
            if job in self._pending:
                self._pending.remove(job)
            if state != "paused":
                # Terminal: release the heavy references — the full
                # submit document (a service-scale router must not
                # retain every tenant's wordlist forever; the table
                # entry itself stays as the id-reuse guard) and the
                # session callback (a dead client's entry must not pin
                # its outbound buffer).
                job.doc = {"id": job.id}
                job.emit = None
                self._tenant_release_locked(job)
        job.settled.set()
        if freed:
            # An engine slot opened: admission-queued jobs can place.
            self._schedule_pump()

    def _forward(self, job: RoutedJob, ev: dict) -> None:
        emit = job.emit
        if emit is None:
            return
        try:
            emit(ev)
        except (OSError, ValueError):
            # Client gone: stop forwarding, keep the job running —
            # the serve tier's dead-client discipline (PERF.md §23).
            job.emit = None

    def _remigrate(self, job: RoutedJob, old: EngineLink) -> None:
        """The second half of a drain/migrate: the job parked (crack:
        paused with checkpoint; candidates: cancelled, restart
        fresh) — re-place it, muted, without bothering the client.  A
        failed re-place must not strand the job silently: it settles
        failed downstream with the checkpoint attached."""
        job.migrating = False
        with self._lock:
            old.routed.discard(job.id)
            job.link = None
        # A migrating split stripe is a range reassignment too (the
        # drain rebalancer rides this path): same parent-side event
        # and counter as the crash path, same mute discipline.
        self._note_reassign(job, old)
        self._requeue.put((job, (old,), None))

    def _schedule_pump(self) -> None:
        """Ask the requeue worker to drain the pending queue — called
        from reader/event threads, which must never dispatch
        themselves (the GT003 handoff discipline)."""
        if self._closed:
            return
        self._requeue.put(("pump",))

    def _pump_pending(self) -> None:
        """Dispatch admission-queued jobs while capacity lasts
        (requeue-worker only).  Jobs whose ``deadline_s`` lapsed while
        queued shed typed first — under overload the freed slot must
        not go to work nobody is waiting for."""
        while True:
            now = time.monotonic()
            with self._lock:
                expired = [
                    j for j in self._pending
                    if j.deadline is not None and j.deadline <= now
                ]
                for j in expired:
                    self._pending.remove(j)
            for j in expired:
                self._shed(j, "deadline_s lapsed while queued")
            with self._lock:
                job = self._pending.pop(0) if self._pending else None
                if job is not None:
                    # Claim: a concurrent cancel/resume must not treat
                    # the popped job as settled-able or re-admittable
                    # while its dispatch is in flight.
                    job.claimed = True
            if job is None:
                return
            try:
                self._dispatch(job)
            except _NoCapacity:
                # Still no room: back to the FRONT (it is the oldest)
                # until the next capacity-freed pump.
                with self._lock:
                    job.claimed = False
                    self._pending.insert(0, job)
                return
            except (FleetError, faults_mod.FaultError) as exc:
                with self._lock:
                    job.claimed = False
                self._fail_unplaceable(job, exc)
            else:
                with self._lock:
                    job.claimed = False
                if job.requeue_counter:
                    telemetry.counter(job.requeue_counter).add(1)
                    job.requeue_counter = None

    def _requeue_worker(self) -> None:
        while True:
            item = self._requeue.get()
            if item is None:
                return
            if item == ("pump",):
                self._pump_pending()
                continue
            job, exclude, counter = item
            if counter:
                job.requeue_counter = counter
            try:
                self._dispatch(job, exclude)
            except _NoCapacity:
                # A crash-replay/migrate job was already admitted once:
                # it queues AHEAD of new arrivals and re-places as
                # capacity frees.
                with self._lock:
                    self._pending.insert(0, job)
            except (FleetError, faults_mod.FaultError) as exc:
                self._fail_unplaceable(job, exc)
            else:
                if job.requeue_counter:
                    telemetry.counter(job.requeue_counter).add(1)
                    job.requeue_counter = None

    def _fail_unplaceable(self, job: RoutedJob,
                          exc: Exception) -> None:
        # Forward BEFORE settling (here and in the event plane): a
        # caller woken by ``wait()`` must find the terminal event
        # already delivered.
        self._forward(job, protocol.ev_failed(
            job.id, f"FleetError: {exc}", checkpoint=job.checkpoint,
        ))
        self._settle(job, "failed")

    # -- engine event plane (link reader threads) ----------------------

    def _on_job_event(self, link: EngineLink, ev: dict) -> None:
        with self._lock:
            job = self._jobs.get(ev.get("id"))
        if job is None or job.link is not link:
            return  # stale event from an engine the job left
        event = protocol.doc_event(ev)
        if event == "hit":
            job.n_forwarded += 1
            self._forward(job, ev)
        elif event == "done":
            self._forward(job, ev)
            self._settle(job, "done")
        elif event == "paused":
            ck = ev.get("checkpoint")
            if ck is not None:
                # Capture-time validation (PERF.md §27): a malformed
                # checkpoint fails the pause/drain TYPED here, not the
                # eventual crash-replay resubmit.
                try:
                    validate_checkpoint_doc(ck)
                except ValueError as exc:
                    self._forward(job, protocol.ev_failed(
                        job.id,
                        f"{type(exc).__name__}: {exc} "
                        "(checkpoint captured on pause "
                        "failed validation)",
                    ))
                    self._settle(job, "failed")
                    return
            job.checkpoint = ck
            parked = job.splitting
            if parked is not None:
                # The explicit split op's park (PERF.md §31): the
                # pause was ours — hand the checkpointed job back to
                # the waiting scatter instead of the client.
                job.splitting = None
                with self._lock:
                    job.state = "paused"
                    link.routed.discard(job.id)
                    job.link = None
                parked.set()
                return
            if job.migrating:
                self._remigrate(job, link)
                return
            with self._lock:
                job.state = "paused"
                link.routed.discard(job.id)
                job.link = None
            self._forward(job, ev)
            job.settled.set()
            self._schedule_pump()
        elif event == "cancelled":
            if job.migrating and job.kind == "candidates":
                # Restart-style migration: the cancel was ours.
                job.checkpoint = None
                self._remigrate(job, link)
                return
            self._forward(job, ev)
            self._settle(job, "cancelled")
        elif event == "failed":
            ck = ev.get("checkpoint")
            if ck is not None:
                try:
                    validate_checkpoint_doc(ck)
                except ValueError as exc:
                    # A quarantine token this build cannot resume is no
                    # replay origin: surface the failure typed instead
                    # of resubmitting a doc that would explode later.
                    ev = dict(ev)
                    ev["checkpoint_invalid"] = \
                        f"{type(exc).__name__}: {exc}"
                    ck = None
                else:
                    # Engine-side quarantine (the §23 ladder exhausted
                    # on this engine) is the repeated-crash-replay
                    # strain signal: enough of them circuit-break the
                    # engine (PERF.md §27).
                    with self._lock:
                        link.replay_fails += 1
                        trip = (
                            link.replay_fails
                            >= self._quarantine_replays
                        )
                    if trip:
                        self._quarantine_link(
                            link,
                            f"{link.replay_fails} checkpoint-bearing "
                            "job failures",
                        )
            if ck is not None and job.replays < self._replay_budget:
                # Quarantine resubmission (PERF.md §23→§25): the
                # failed event's checkpoint IS the migrate token.
                job.replays += 1
                job.checkpoint = ck
                with self._lock:
                    link.routed.discard(job.id)
                    job.link = None
                self._note_reassign(job, link)
                self._requeue.put((job, (link,),
                                   "fleet.jobs_replayed"))
                return
            if ck is not None:
                job.checkpoint = ck
            self._forward(job, ev)
            self._settle(job, "failed")
        elif event == "accepted":
            # A resumed/duplicate ack that missed the request window;
            # nothing to do.
            pass
        else:
            self._forward(job, ev)  # future per-job events pass through

    def _on_death(self, link: EngineLink) -> None:
        """Crash-replay (the fleet's whole point): every job routed to
        the dead engine requeues onto the survivors from its last
        router-held checkpoint, with already-forwarded hits muted so
        the client stream stays exactly-once and byte-identical."""
        if self._closed:
            return
        with self._lock:
            if link not in self._links:
                return
            link.alive = False
            # Only ACKED placements requeue here: a job whose dispatch
            # request is still in flight belongs to its dispatching
            # thread — that request is failing with "connection lost"
            # right now, and its caller handles the job exactly once.
            jobs = [
                self._jobs[jid] for jid in sorted(link.routed)
                if jid in self._jobs and self._jobs[jid].acked
            ]
            link.routed.clear()
        telemetry.counter("fleet.engine_deaths").add(1)
        if link.proc is not None and link.proc.poll() is None:
            # Torn socket but live process: a half-dead engine must
            # not keep burning the device for jobs we re-place.
            try:
                link.proc.terminate()
            except OSError:
                pass
        for job in jobs:
            job.link = None
            job.migrating = False
            job.target = None
            if not job.unsettled or job.state == "paused":
                continue
            if job.kind == "candidates":
                job.checkpoint = None  # restart: output truncates
            self._note_reassign(job, link)
            self._requeue.put((job, (), "fleet.jobs_replayed"))

    def _note_reassign(self, job: RoutedJob,
                       link: EngineLink) -> None:
        """A split shard's stripe is moving engines (PERF.md §31):
        count it and tell the parent's client — the stripe resumes
        from its last router-held checkpoint with ``acked`` already-
        merged hits muted, so the merged stream never replays."""
        if job.shard is None or job.parent is None:
            return
        telemetry.counter("fleet.shards_reassigned").add(1)
        self._forward(job.parent, protocol.ev_range_reassign(
            job.parent.id, shard=job.shard[0], shards=job.shard[1],
            frm=link.engine_id, acked=job.n_forwarded,
        ))

    # -- health --------------------------------------------------------

    def _scrape(self, link: EngineLink, *,
                observe: bool = False) -> dict:
        # The stats op answers from a session thread (counter reads,
        # no device work) on the link's DEDICATED health connection —
        # blocking ops on the main op stream (a pause parking at a
        # superstep boundary) can never make a healthy engine look
        # dead.  The short cadence-scaled timeout bounds how long the
        # watchdog takes to declare a wedged engine (poll_misses ×
        # this).
        timeout = max(2.0 * self._poll_s, 2.0)
        with telemetry.stopwatch(
            "fleet.scrape_s", edges=(0.01, 0.05, 0.25, 1.0, 5.0)
        ) as sw:
            ev = link.health_request(protocol.op_stats(),
                                     timeout=timeout)
        if protocol.doc_event(ev) == "error":
            raise FleetError(
                f"engine {link.engine_id}: {ev.get('error')}"
            )
        link.scrape = ev
        link.misses = 0
        if observe:
            # Latency budget (PERF.md §27): a reply slower than half
            # the scrape timeout is a strain signal even when it
            # arrives — a struggling engine degrades before it wedges.
            # ONLY the poll loop's cadenced scrapes feed the ladder:
            # client-driven stats scrapes would otherwise make
            # quarantine timing a function of how often clients poll
            # (fast polls could both rush strikes and mask strain by
            # resetting them between ticks).
            self._ladder_observe(link, ev, sw.elapsed_s > 0.5 * timeout)
        return ev

    # -- the health ladder (PERF.md §27) -------------------------------

    def _ladder_observe(self, link: EngineLink, ev: dict,
                        slow: bool) -> None:
        """One successful scrape's ladder input: strain = a slow reply
        OR rising recovery-ladder deltas (``group_demotions``/
        ``job_restarts`` climbing between scrapes — the engine's §23
        ladder is working, which means its device is failing)."""
        cur = {
            k: int(ev.get(k, 0))
            for k in ("group_demotions", "job_restarts")
        }
        with self._lock:
            prev = link.ladder_prev
            link.ladder_prev = cur
        # The FIRST scrape is the baseline: attaching to an engine
        # with recovery history must not instantly degrade it.
        rising = bool(prev) and any(
            cur[k] > prev.get(k, 0) for k in cur
        )
        if slow or rising:
            self._ladder_strike(link)
        else:
            self._ladder_clean(link)

    def _ladder_strike(self, link: EngineLink) -> None:
        quarantine = False
        with self._lock:
            link.strikes += 1
            link.clean = 0
            if link.health != "quarantined":
                if (
                    link.strikes >= self._quarantine_after
                    and self.autoscaler is not None
                ):
                    quarantine = True
                elif link.health == "healthy" and \
                        link.strikes >= self._degrade_after:
                    link.health = "degraded"
        if quarantine:
            self._quarantine_link(
                link, f"{link.strikes} consecutive strained scrapes"
            )

    def _ladder_clean(self, link: EngineLink) -> None:
        with self._lock:
            link.strikes = 0
            # A clean POLL tick also closes the repeated-crash-replay
            # window: ``quarantine_replays`` means failures bunched
            # within one health window, not accumulated over an
            # engine's whole lifetime (a long-lived engine with one
            # recovered transient per week must never circuit-break).
            link.replay_fails = 0
            if link.health == "degraded":
                link.clean += 1
                if link.clean >= self._recover_after:
                    link.health = "healthy"
                    link.clean = 0

    def _quarantine_link(self, link: EngineLink, reason: str) -> None:
        """Circuit-break one engine: no further placements land on it;
        the autoscaler drains + replaces it (its routed jobs migrate
        off with their checkpoints — nothing is lost).  One-way: a
        quarantined engine never un-quarantines (replacement is the
        recovery, mirroring the §23 job quarantine).  Only reachable
        when an autoscaler is attached — a fixed pool has no replacer,
        so its ladder tops out at ``degraded`` (place-last) and the
        poll watchdog stays the kill path for truly wedged engines:
        permanently losing live capacity would be strictly worse than
        degraded placements."""
        if self.autoscaler is None:
            return
        with self._lock:
            if link.health == "quarantined":
                return
            link.health = "quarantined"
        telemetry.counter("fleet.engines_quarantined").add(1)
        print(
            f"a5gen: fleet: engine {link.engine_id} QUARANTINED "
            f"({reason}); placements stop — the autoscaler drains "
            "and replaces it",
            file=sys.stderr,
        )

    def _jitter_of(self, link: EngineLink) -> float:
        """Deterministic per-engine scrape offset: a stable hash
        fraction of ``poll_s × poll_jitter``, so N engines spread over
        the scrape tick instead of stampeding it (PERF.md §27)."""
        if self._poll_s <= 0:
            return 0.0
        frac = (
            zlib.crc32(link.engine_id.encode("utf-8")) % 997
        ) / 997.0
        return self._poll_s * self._poll_jitter * frac

    def _poll_loop(self) -> None:
        while True:
            now = time.monotonic()
            due = []
            wait = self._poll_s
            for link in self.engines():
                if not link.alive:
                    continue
                if link.next_poll <= now:
                    due.append(link)
                    link.next_poll = (
                        now + self._poll_s + self._jitter_of(link)
                    )
                else:
                    wait = min(wait, link.next_poll - now)
            for link in due:
                if link.proc is not None and link.proc.poll() is not None:
                    link.kill_socket()  # reaped: reader EOF replays
                    continue
                try:
                    self._scrape(link, observe=True)
                except FleetError:
                    # One immediate in-poll retry before the failure
                    # counts (PERF.md §27): a dropped health connection
                    # or one slow reply must not walk a healthy engine
                    # toward the watchdog.
                    telemetry.counter("fleet.scrape_retries").add(1)
                    try:
                        self._scrape(link, observe=True)
                    except FleetError:
                        link.misses += 1
                        self._ladder_strike(link)
                        if link.misses >= self._poll_misses:
                            # Wedged engine (socket up, serve loop
                            # gone): the watchdog declares it dead the
                            # same way a torn socket would.
                            link.kill_socket()
            with self._lock:
                backlog = bool(self._pending)
            if backlog:
                # Belt-and-braces: capacity can free without a settle
                # this router observes (quarantine recovery, operator
                # action engine-side) — the tick re-pumps.
                self._schedule_pump()
            if self._poll_stop.wait(max(0.05, min(wait, self._poll_s))):
                return


# ---------------------------------------------------------------------------
# Local engine spawning
# ---------------------------------------------------------------------------


def spawn_engines(n: int, directory: str, *,
                  engine_args: Sequence[str] = (),
                  engine_id_prefix: str = "eng",
                  start_index: int = 0,
                  env: Optional[dict] = None,
                  stderr: Any = subprocess.DEVNULL
                  ) -> List[Tuple[str, str, subprocess.Popen]]:
    """Spawn ``n`` local ``a5gen serve`` engine processes, each on its
    own unix socket under ``directory``, all sharing ``engine_args``
    (geometry flags, and — the fleet artifact store — one
    ``--schema-cache`` directory).  Returns ``(socket_path, engine_id,
    proc)`` triples; callers attach them to a :class:`FleetRouter`
    (which retries until each engine's post-jax-import bind lands).
    ``start_index`` offsets the id/socket numbering — the autoscaler
    spawns incrementally and must never reuse a reaped engine's
    socket path (PERF.md §27)."""
    os.makedirs(directory, exist_ok=True)
    out = []
    for i in range(int(start_index), int(start_index) + int(n)):
        sock = os.path.join(directory, f"{engine_id_prefix}{i}.sock")
        eid = f"{engine_id_prefix}{i}"
        cmd = [
            sys.executable, "-m", _PACKAGE, "serve",
            "--socket", sock, "--engine-id", eid, *engine_args,
        ]
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.DEVNULL, stderr=stderr,
        )
        out.append((sock, eid, proc))
    return out


# ---------------------------------------------------------------------------
# JSONL front-ends (``a5gen fleet``): the serve protocol, routed
# ---------------------------------------------------------------------------


#: Serve ops deliberately left to ``_RouterSession._handle``'s
#: unknown-op fallback (forwarded verbatim to the job's engine):
#: id-carrying and router-state-free by construction.  graftrace GT004
#: diffs the engine session's op table against the router's handled
#: set ∪ this declaration — a new serve op with NEITHER is a lint
#: failure (CONTRIBUTING: router-passthrough-safe), so the decision is
#: a diff, not a review catch.
ROUTER_PASSTHROUGH_OPS: frozenset = frozenset()


class _RouterSession:
    """One upstream JSONL command stream against a shared
    :class:`FleetRouter` — the same protocol ``_JsonlSession`` speaks
    for one engine, so serve clients work unmodified.  Job events are
    forwarded by the router from the engine links onto the session
    that submitted the job; the job registry is router-global, so any
    session operates on any job by id (the serve tier's adoption
    semantics)."""

    #: outbound event buffer per session; a client further behind than
    #: this is dropped (see ``_emit``).
    OUT_DEPTH = 4096

    def __init__(self, router: FleetRouter, fin: TextIO,
                 fout: TextIO) -> None:
        self._router = router
        self._fin = fin
        self._fout = fout
        #: all writes ride ONE bounded queue drained by a dedicated
        #: writer thread: ``_emit`` is called from engine-link reader
        #: threads (event forwarding), and a client that stops
        #: draining its socket must never block a reader — that would
        #: stall every tenant on that engine and make it look dead to
        #: the watchdog.  A full queue means the client is
        #: irrecoverably behind: the session goes dead (the router's
        #: ``_forward`` then stops forwarding; jobs keep running — the
        #: serve tier's dead-client discipline).
        self._out: "queue.Queue" = queue.Queue(maxsize=self.OUT_DEPTH)
        self._dead = False
        self._writer = threading.Thread(
            target=self._write_loop, name="a5-fleet-session-out",
            daemon=True,
        )
        self._writer.start()

    def _write_loop(self) -> None:
        while True:
            obj = self._out.get()
            if obj is None:
                return
            if self._dead:
                continue  # drain and discard: producers never block
            try:
                self._fout.write(json.dumps(obj) + "\n")
                self._fout.flush()
            except (OSError, ValueError):
                self._dead = True

    def _emit(self, obj: dict) -> None:
        if self._dead:
            raise OSError("client connection is gone")
        try:
            self._out.put_nowait(obj)
        except queue.Full:
            self._dead = True
            raise OSError(
                "client outbound queue overflowed (slow consumer)"
            ) from None

    def _handle(self, doc: dict) -> bool:
        op = protocol.doc_op(doc)
        jid = doc.get("id")
        if op == "shutdown":
            self._emit(protocol.ev_bye())
            return False
        if op == "stats":
            self._emit(self._router.stats())
            return True
        if op == "metrics":
            self._emit(self._router.metrics())
            return True
        if op == "submit":
            ack = self._router.submit(doc, emit=self._emit)
            # Admission-queued (PERF.md §27): accepted, not yet
            # placed — the client's events flow once it dispatches.
            self._emit(protocol.ev_accepted(
                ack.get("id", jid), ack.get("kind"),
                engine=ack.get("engine"),
                queued=bool(ack.get("queued")),
                shards=ack.get("shards"),
            ))
            return True
        if op == "pause":
            self._router.pause(jid)
        elif op == "resume":
            ack = self._router.resume(jid)
            self._emit(protocol.ev_accepted(
                jid, ack.get("kind"),
                queued=bool(ack.get("queued")), resumed=True,
            ))
        elif op == "cancel":
            self._router.cancel(jid)
        elif op == "split":
            ack = self._router.split(jid, doc.get("shards"))
            self._emit(protocol.ev_accepted(
                jid, ack.get("kind"), shards=ack.get("shards"),
            ))
        elif op == "migrate":
            self._emit(self._router.migrate(jid, doc.get("engine")))
        elif op == "drain":
            self._emit(self._router.drain(doc.get("engine")))
        elif jid is not None:
            # Unknown op on a known job: pass through to its engine —
            # new serve ops must not need a router release.
            self._router.passthrough(doc)
        else:
            raise ValueError(f"unknown op {op!r}")
        return True

    def run(self) -> bool:
        """Process the stream; True when an explicit ``shutdown``
        ended it (EOF ends only this session).  Stops the writer
        thread on exit, flushing whatever the client still drains
        (the ``bye`` ack included)."""
        try:
            while True:
                try:
                    line = self._fin.readline()
                except (OSError, ValueError):
                    return False
                if not line:
                    return False
                line = line.strip()
                if not line:
                    continue
                doc = None
                try:
                    doc = json.loads(line)
                    keep_going = self._handle(doc)
                except OSError:
                    return False  # this session's client is gone
                except FleetOverloaded as exc:
                    # The typed overload rejection (PERF.md §27):
                    # machine-parseable error + retry_after_s, so
                    # clients back off instead of hammering.
                    try:
                        self._emit(exc.event(
                            doc.get("id") if isinstance(doc, dict)
                            else None
                        ))
                    except OSError:
                        return False
                    continue
                except Exception as exc:  # noqa: BLE001 — protocol
                    # Id-carrying like the engine session's errors —
                    # clients correlate failures to the op that caused
                    # them (CONTRIBUTING: router-passthrough-safe).
                    err = protocol.ev_error(
                        f"{type(exc).__name__}: {exc}",
                        jid=(doc.get("id")
                             if isinstance(doc, dict) else None),
                    )
                    try:
                        self._emit(err)
                    except OSError:
                        return False
                    continue
                if not keep_going:
                    return True
        finally:
            self._out.put(None)
            self._writer.join(timeout=5.0)
            # Late forwards for still-running jobs must raise into
            # the router's _forward (which then drops the callback),
            # not buffer into a queue nobody drains.
            self._dead = True


def serve_fleet_stdio(router: FleetRouter, fin: TextIO,
                      fout: TextIO) -> None:
    """Serve one JSONL command stream against the router."""
    _RouterSession(router, fin, fout).run()


def serve_fleet_socket(router: FleetRouter, path: str, *,
                       ready: Optional[Callable[[], None]] = None
                       ) -> None:
    """Serve JSONL sessions over a unix socket at ``path`` (one
    session per connection, all sharing the router and its job
    registry); returns when a session sends ``shutdown``."""
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass
    stop = threading.Event()
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        srv.bind(path)
        srv.listen()
        srv.settimeout(0.2)
        if ready is not None:
            ready()
        while not stop.is_set():
            try:
                conn, _addr = srv.accept()
            except socket.timeout:
                continue

            def _session(conn: socket.socket = conn) -> None:
                with conn:
                    fin = conn.makefile("r", encoding="utf-8")
                    fout = conn.makefile("w", encoding="utf-8")
                    if _RouterSession(router, fin, fout).run():
                        stop.set()

            threading.Thread(
                target=_session, name="a5-fleet-conn", daemon=True
            ).start()
    finally:
        srv.close()
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass

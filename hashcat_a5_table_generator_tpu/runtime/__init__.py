"""Sweep runtime: cursors, checkpoint/resume, progress, sinks, and the
launch loop driving the fused device steps (the reference has NONE of this —
its runtime is goroutines + one channel + ``log.Fatal``, SURVEY.md §5; here
recovery is replay-from-cursor because generation is pure and the variant
space is indexable, Q10)."""

from .checkpoint import (  # noqa: F401
    CheckpointState,
    SweepCursor,
    load_checkpoint,
    save_checkpoint,
    sweep_fingerprint,
)
from .progress import ProgressReporter  # noqa: F401
from .sinks import CandidateWriter, HitRecord, HitRecorder  # noqa: F401
from .sweep import Sweep, SweepConfig, SweepResult  # noqa: F401

"""Sweep runtime: cursors, checkpoint/resume, progress, sinks, and the
launch loop driving the fused device steps (the reference has NONE of this —
its runtime is goroutines + one channel + ``log.Fatal``, SURVEY.md §5; here
recovery is replay-from-cursor because generation is pure and the variant
space is indexable, Q10).

``Sweep``/``SweepConfig``/``SweepResult`` are loaded lazily (PEP 562): they
pull in jax, and jax-free consumers (the oracle CLI backend) must be able to
import the checkpoint/progress/sink layers without it.
"""

from .checkpoint import (  # noqa: F401
    CheckpointCorrupt,
    CheckpointState,
    CheckpointWireIncompatible,
    SweepCursor,
    atomic_write_bytes,
    atomic_write_text,
    load_checkpoint,
    save_checkpoint,
    sweep_fingerprint,
)
from .progress import ProgressReporter  # noqa: F401
from .sinks import CandidateWriter, HitRecord, HitRecorder  # noqa: F401

_LAZY = ("Sweep", "SweepConfig", "SweepResult", "BucketedSweep", "Engine",
         "EngineJob")


def __getattr__(name: str):
    if name == "BucketedSweep":
        from .bucketed import BucketedSweep

        return BucketedSweep
    if name in ("Engine", "EngineJob"):
        from . import engine

        return getattr(engine, name)
    if name in _LAZY:
        from . import sweep

        return getattr(sweep, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))

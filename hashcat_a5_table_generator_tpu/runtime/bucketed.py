"""Length-bucketed sweeps: one compiled program per bucket width.

The device kernels operate on fixed-shape ``uint8[B, width]`` batches, and a
word's bucket width sets the whole launch's candidate ``out_width`` and hash
block count — so packing a rockyou-class dictionary at one global width lets
a single 300-byte line inflate EVERY lane of EVERY launch (VERDICT r1 weak
#6).  The bucketed sweep instead partitions the wordlist by length bucket
(``ops.packing.bucket_words`` / ``native.read_packed_buckets``) and runs one
ordinary :class:`~.sweep.Sweep` per bucket, each compiled at its own width —
SURVEY.md §5's ``uint8[B, Lmax]`` long-context plan made real.

Semantics vs a single-width sweep:

* **multiset**: identical — bucketing permutes words, never candidates
  within a word; hits still report global dictionary positions via the
  batches' ``index`` field.
* **order** (candidates mode): bucket-major — buckets ascend by width, each
  bucket streams ITS words in dictionary order.  A single-bucket wordlist
  (the common case) is byte-identical to the unbucketed stream.  The oracle
  backend remains the strict-global-order surface.
* **checkpoints**: the user's ``--checkpoint FILE`` path holds a top-level
  *manifest* (bucket widths → per-bucket checkpoint files + fingerprints,
  :func:`~.checkpoint.save_bucket_manifest`); each bucket's cursor state
  lives in ``{path}.w{width}`` and resumes independently.  A legacy
  single-file checkpoint at FILE, or a manifest written under different
  ``--buckets``, fails loudly instead of silently restarting.
"""

from __future__ import annotations

import time
from dataclasses import replace
from typing import Dict, List, Optional, Sequence

from ..ops.packing import PackedWords
from . import telemetry
from .checkpoint import check_bucket_manifest, save_bucket_manifest
from .sweep import Sweep, SweepConfig, SweepResult


class _ForwardRecorder:
    """Per-bucket recorder that streams every hit straight through to the
    user's recorder (hits land as they are found, bucket-major order) while
    keeping a bucket-local list for the merged, globally-sorted result."""

    def __init__(self, sink) -> None:
        self.hits = []
        self.sink = sink

    def emit(self, record) -> None:
        self.hits.append(record)
        if self.sink is not None:
            self.sink.emit(record)


class _BucketProgress:
    """Adapter making per-bucket progress cumulative across buckets."""

    def __init__(self, inner) -> None:
        self.inner = inner
        self.word_base = 0
        self.emit_base = 0
        self.hit_base = 0
        self._routing: dict = {}

    def advance(self, words: int, emitted: int, hits: int) -> None:
        self.word_base += words
        self.emit_base += emitted
        self.hit_base += hits

    def set_routing(self, routing: dict) -> None:
        # Per-bucket routing accumulates into whole-dictionary counts.
        # Guarded like Sweep's call site: a custom reporter implementing
        # only the pre-routing interface must keep working.
        for k, v in routing.items():
            self._routing[k] = self._routing.get(k, 0) + int(v)
        inner_set = getattr(self.inner, "set_routing", None)
        if inner_set is not None:
            inner_set(self._routing)

    def set_geometry(self, geometry: dict, source: str) -> None:
        # Buckets share one SweepConfig, so every bucket resolves the
        # same geometry; last write wins harmlessly.  Guarded like
        # set_routing for pre-geometry custom reporters.
        inner_set = getattr(self.inner, "set_geometry", None)
        if inner_set is not None:
            inner_set(geometry, source)

    def seed_emitted(self, emitted: int) -> None:
        self.inner.seed_emitted(self.emit_base + emitted)

    def seed_hits(self, hits: int) -> None:
        # Guarded like set_routing: pre-seed_hits custom reporters keep
        # working.
        inner_seed = getattr(self.inner, "seed_hits", None)
        if inner_seed is not None:
            inner_seed(self.hit_base + hits)

    def update(self, *, words_done: int, emitted: int, hits: int,
               force: bool = False) -> None:
        self.inner.update(
            words_done=self.word_base + words_done,
            emitted=self.emit_base + emitted,
            hits=self.hit_base + hits,
            force=force,
        )

    def final(self, *, words_done: int, emitted: int, hits: int) -> None:
        # Per-bucket "final" is only a forced update; the real final line is
        # emitted once by BucketedSweep after the last bucket.
        self.update(words_done=words_done, emitted=emitted, hits=hits,
                    force=True)


class BucketedSweep:
    """One wordlist × one table × one spec, split across length buckets.

    ``buckets`` is ``{width: PackedWords}`` (from ``bucket_words`` or
    ``native.read_packed_buckets``); widths run in ascending order.
    """

    def __init__(
        self,
        spec,
        sub_map: Dict[bytes, List[bytes]],
        buckets: Dict[int, PackedWords],
        digests: Sequence[bytes] = (),
        config: Optional[SweepConfig] = None,
    ) -> None:
        self.config = config or SweepConfig()
        self.progress = (
            _BucketProgress(self.config.progress)
            if self.config.progress is not None
            else None
        )
        self.sweeps: Dict[int, Sweep] = {}
        for width in sorted(buckets):
            packed = buckets[width]
            if packed.batch == 0:
                continue
            cfg = self.config
            bucket_cfg = replace(
                cfg,
                checkpoint_path=(
                    f"{cfg.checkpoint_path}.w{width}"
                    if cfg.checkpoint_path
                    else None
                ),
                progress=self.progress,
            )
            self.sweeps[width] = Sweep(
                spec, sub_map, packed, digests, config=bucket_cfg
            )

    @property
    def n_words(self) -> int:
        return sum(s.n_words for s in self.sweeps.values())

    def _sync_manifest(self, resume: bool) -> None:
        """Validate (when resuming) and write the top-level manifest at the
        user's checkpoint path, before any bucket runs — so FILE exists
        even if the run dies inside the first bucket."""
        path = self.config.checkpoint_path
        if not path:
            return
        fps = {w: s.fingerprint for w, s in self.sweeps.items()}
        if resume:
            check_bucket_manifest(path, fps)
        save_bucket_manifest(path, fps)

    def _merge(self, results: List[SweepResult], t0: float) -> SweepResult:
        hits = [h for r in results for h in r.hits]
        hits.sort(key=lambda h: (h.word_index, h.variant_rank))
        # Per-key merge semantics live in ONE place — the telemetry
        # merge specs (PERF.md §21; the multihost reducers walk the
        # same specs): routing/schema-cache counters sum, superstep
        # counters sum with ratio/flag max, stream walls sum with
        # peaks max and sweep-local scalars (ttfc_s, resumed_chunk,
        # first_chunk_compile_s) claimed by the FIRST bucket only —
        # buckets run sequentially, so a later streaming bucket's ttfc
        # says nothing about the run's time to first candidate.
        # Overlap RATIOS are derived: recomputed below from the summed
        # terms (a first-bucket ratio next to summed walls would be
        # self-inconsistent).
        routing = telemetry.ROUTING_MERGE.merge(
            [r.routing for r in results]
        )
        schema_cache = telemetry.SCHEMA_CACHE_MERGE.merge(
            [getattr(r, "schema_cache", {}) for r in results]
        )
        superstep = telemetry.SUPERSTEP_MERGE.merge(
            [getattr(r, "superstep", {}) for r in results]
        )
        stream = telemetry.STREAM_MERGE.merge(
            [getattr(r, "stream", {}) for r in results]
        )
        if stream.get("compile_wall_s", 0) > 0:
            wall = stream["compile_wall_s"]
            over = stream.get("compile_overlap_s", 0.0)
            first = stream.get("first_chunk_compile_s", 0.0)
            stream["overlap_ratio"] = over / wall
            stream["steady_overlap_ratio"] = (
                over / (wall - first) if wall - first > 0 else 0.0
            )
        # Buckets share one SweepConfig, so every bucket resolves the
        # same geometry (PERF.md §29); the first result's stamp stands
        # for the whole run.
        geometry = next(
            (dict(r.geometry) for r in results if r.geometry), {}
        )
        geometry_source = next(
            (r.geometry_source for r in results
             if r.geometry_source != "explicit"),
            results[0].geometry_source if results else "explicit",
        )
        return SweepResult(
            n_emitted=sum(r.n_emitted for r in results),
            n_hits=sum(r.n_hits for r in results),
            hits=hits,
            words_done=sum(r.words_done for r in results),
            resumed=any(r.resumed for r in results),
            wall_s=time.monotonic() - t0,
            routing=routing,
            superstep=superstep,
            stream=stream,
            schema_cache=schema_cache,
            geometry=geometry,
            geometry_source=geometry_source,
        )

    def run_crack(self, recorder=None, *, resume: bool = True) -> SweepResult:
        """Fused crack over every bucket.  Hits stream to ``recorder`` as
        found (bucket-major order); the returned result's ``hits`` list is
        sorted by global (word_index, rank)."""
        t0 = time.monotonic()
        self._sync_manifest(resume)
        results = []
        for width, sweep in self.sweeps.items():
            res = sweep.run_crack(_ForwardRecorder(recorder), resume=resume)
            results.append(res)
            if self.progress is not None:
                self.progress.advance(res.words_done, res.n_emitted,
                                      res.n_hits)
        merged = self._merge(results, t0)
        if self.config.progress is not None:
            self.config.progress.final(
                words_done=merged.words_done,
                emitted=merged.n_emitted,
                hits=merged.n_hits,
            )
        return merged

    def run_candidates(self, writer, *, resume: bool = True) -> SweepResult:
        """Stream every bucket's candidates (ascending width, dictionary
        order within each bucket)."""
        t0 = time.monotonic()
        self._sync_manifest(resume)
        results = []
        for width, sweep in self.sweeps.items():
            res = sweep.run_candidates(writer, resume=resume)
            results.append(res)
            if self.progress is not None:
                self.progress.advance(res.words_done, res.n_emitted, 0)
        merged = self._merge(results, t0)
        if self.config.progress is not None:
            self.config.progress.final(
                words_done=merged.words_done,
                emitted=merged.n_emitted,
                hits=0,
            )
        return merged

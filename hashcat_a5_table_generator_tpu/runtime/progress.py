"""Rate-limited structured progress to stderr.

The reference reports nothing (stdlib ``log`` for errors only, SURVEY.md §5);
candidates own stdout, so progress/metrics keep to stderr — the same clean
split the reference uses for its error logs.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Optional, TextIO


class ProgressReporter:
    """Emits one JSON progress line to ``stream`` at most every
    ``every_s`` seconds (and unconditionally on ``final()``)."""

    def __init__(
        self,
        total_words: int,
        *,
        every_s: float = 5.0,
        stream: Optional[TextIO] = None,
        clock=time.monotonic,
    ) -> None:
        self.total_words = total_words
        self.every_s = every_s
        self.stream = stream if stream is not None else sys.stderr
        self._clock = clock
        self._t0 = clock()
        self._last = float("-inf")
        self._last_emitted = 0
        self._last_hits = 0
        self._last_t = self._t0
        self._routing: "dict | None" = None
        self._stream: "dict | None" = None
        self._geometry: "dict | None" = None

    def set_routing(self, routing: dict) -> None:
        """Attach the sweep's word-routing counts (device_clean /
        device_closed / oracle_fallback — a plan-time fact, constant over
        the run); included in every progress line once known."""
        self._routing = dict(routing)

    def set_stream(self, stream: dict) -> None:
        """Attach a streaming sweep's chunk position
        (``CheckpointState.stream``: the active ``{"chunk", "chunk_words"}``
        marker — updated per chunk, seeded immediately on a resumed
        streaming sweep); included in every progress line once known."""
        self._stream = dict(stream)

    def set_geometry(self, geometry: dict, source: str) -> None:
        """Attach the resolved launch geometry and its provenance
        (PERF.md §29: ``explicit``/``profile``/``default`` — stamped by
        the Sweep's launch-time resolution seam, constant over the run);
        included in every progress line once known, so no throughput
        number in a log is ever ambiguous about its geometry."""
        self._geometry = dict(geometry, source=source)

    def seed_emitted(self, emitted: int) -> None:
        """Base the first rate window on a resumed sweep's prior count, so
        candidates emitted by an earlier process are not attributed to this
        one's first few seconds."""
        self._last_emitted = emitted

    def seed_hits(self, hits: int) -> None:
        """``seed_emitted``'s twin for the hit-rate window: a resumed
        crack sweep re-reports its checkpointed hits up front, and they
        must not inflate this process's first ``hits_per_sec``."""
        self._last_hits = hits

    def update(
        self, *, words_done: int, emitted: int, hits: int, force: bool = False
    ) -> None:
        now = self._clock()
        if not force and now - self._last < self.every_s:
            return
        window = max(now - self._last_t, 1e-9)
        rate = (emitted - self._last_emitted) / window
        hit_rate = (hits - self._last_hits) / window
        self._last, self._last_t = now, now
        self._last_emitted = emitted
        self._last_hits = hits
        body = {
            "words": [words_done, self.total_words],
            "candidates": emitted,
            "cand_per_sec": round(rate, 1),
            "hits": hits,
            "hits_per_sec": round(hit_rate, 3),
            "elapsed_s": round(now - self._t0, 2),
        }
        if self._routing is not None:
            body["routing"] = self._routing
        if self._stream is not None:
            body["stream"] = self._stream
        if self._geometry is not None:
            body["geometry"] = self._geometry
        # Registry-derived enrichment (PERF.md §21; keys in README):
        # pipeline dead-time share, chunk-ring occupancy, cache hit
        # rates — silent when A5GEN_TELEMETRY=off or nothing recorded.
        from .telemetry import progress_fields

        extra = progress_fields()
        if extra:
            body["telemetry"] = extra
        print(
            json.dumps({"progress": body}),
            file=self.stream,
            flush=True,
        )

    def final(self, *, words_done: int, emitted: int, hits: int) -> None:
        self.update(
            words_done=words_done, emitted=emitted, hits=hits, force=True
        )

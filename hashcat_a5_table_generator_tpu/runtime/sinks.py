"""Output sinks — the L4 layer (reference: one writer goroutine draining a
channel into buffered stdout, ``main.go:58-68``).

Here the device already filters (crack mode) or batches (candidates mode),
so sinks are plain synchronous writers: ``CandidateWriter`` streams raw
candidate bytes + ``\\n`` through one buffered binary stream exactly like
the reference's ``bufio.Writer``; ``HitRecorder`` collects crack-mode hits
as structured records. No thread is needed — the "single writer" discipline
the reference gets from its goroutine is the default in a sequential launch
loop, and device→host copies already overlap compute via JAX's async
dispatch.
"""

from __future__ import annotations

import io
import sys
from dataclasses import dataclass
from typing import BinaryIO, List, Optional

from ..utils.hexenc import hex_notation_encode, needs_hex_notation


class CandidateWriter:
    """Buffered line writer for candidate bytes (reference-compatible raw
    emission; optional ``$HEX[]`` wrapping for line-corrupting bytes)."""

    def __init__(
        self,
        stream: Optional[BinaryIO] = None,
        *,
        hex_unsafe: bool = False,
        buffer_size: int = 1 << 20,
    ) -> None:
        raw = stream if stream is not None else sys.stdout.buffer
        # Wrap in our own buffer only when the target is unbuffered-ish;
        # BufferedWriter on BufferedWriter is harmless but wasteful.
        self._stream = (
            raw
            if isinstance(raw, io.BufferedWriter)
            else io.BufferedWriter(_NonClosingRaw(raw), buffer_size=buffer_size)
            if isinstance(raw, io.RawIOBase)
            else raw
        )
        self._own = self._stream is not raw
        self.hex_unsafe = hex_unsafe
        self.n_written = 0

    def emit(self, candidate: bytes) -> None:
        if self.hex_unsafe and needs_hex_notation(candidate):
            candidate = hex_notation_encode(candidate)
        self._stream.write(candidate)
        self._stream.write(b"\n")
        self.n_written += 1

    def write_block(self, data: bytes, n_candidates: int) -> None:
        """Bulk path: ``data`` is ``n_candidates`` pre-assembled
        newline-terminated candidate lines (the sweep runner's vectorized
        ragged flatten)."""
        self._stream.write(data)
        self.n_written += n_candidates

    def flush(self) -> None:
        self._stream.flush()

    def close(self) -> None:
        self._stream.flush()
        if self._own:
            self._stream.close()

    def __enter__(self) -> "CandidateWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _NonClosingRaw(io.RawIOBase):
    """Raw wrapper that flushes through but never closes the underlying
    stream (closing sys.stdout.buffer would kill the process's stdout)."""

    def __init__(self, raw: BinaryIO) -> None:
        self._raw = raw

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        return self._raw.write(b)


@dataclass(frozen=True)
class HitRecord:
    """One cracked digest: where it came from and what it was."""

    word_index: int  # wordlist ordinal
    variant_rank: int  # rank in the word's variant space
    candidate: bytes
    digest_hex: str


def potfile_line(digest_hex: str, candidate: bytes) -> bytes:
    """One ``digest:plain`` potfile line; a plain that would corrupt the
    line format — embedded newline, or a ``:`` that colon-splitting potfile
    consumers would mis-parse — is ``$HEX[]``-wrapped.  Only the plain,
    never the digest prefix, matching hashcat's potfile convention."""
    if needs_hex_notation(candidate) or b":" in candidate:
        candidate = hex_notation_encode(candidate)
    return digest_hex.encode("ascii") + b":" + candidate + b"\n"


class HitRecorder:
    """Collects crack-mode hits; optionally tees potfile lines to a binary
    stream as they arrive."""

    def __init__(self, stream: Optional[BinaryIO] = None) -> None:
        self.hits: List[HitRecord] = []
        self._stream = stream

    def emit(self, record: HitRecord) -> None:
        self.hits.append(record)
        if self._stream is not None:
            self._stream.write(
                potfile_line(record.digest_hex, record.candidate)
            )
            self._stream.flush()

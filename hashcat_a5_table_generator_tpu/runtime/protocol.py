"""The serve/fleet wire contract, declared once (PERF.md §25–§27).

The JSONL protocol the engine session (``runtime/engine.py``), the
fleet router (``runtime/fleet.py``), and every client speak is the
system's compatibility boundary — ROADMAP item 4 (replicated routers,
an HTTP/gRPC front door) replicates it, so it must be ENUMERABLE, not
scattered across string-literal dicts.  This module is the single
declared registry (the ``env.py``/``telemetry.py`` centralization
pattern):

* :data:`WIRE_OPS` / :data:`WIRE_EVENTS` — every op and event, their
  required and optional fields, which role handles/emits each, and the
  declared asymmetries (router-synthesized events the engine never
  emits carry ``route: "synthesized"`` with a justification — an
  annotation, not a silent allowlist).
* :data:`CHECKPOINT_WIRE` — the checkpoint wire doc's version and
  required fields, mirrored from ``runtime/checkpoint.py`` (an
  import-time assert keeps the two from drifting).
* Constructors (``ev_*`` / ``op_*``) — the ONE place each doc shape is
  built.  They are emission-identical to the historical inline dicts
  (key insertion order included: JSONL byte parity is a fleet test
  contract), so migrating a call site never changes the wire bytes.
* ``doc_op`` / ``doc_event`` — the dispatch-side reads, so the string
  keys ``"op"``/``"event"`` appear in exactly one module.

``tools/graftwire`` extracts this registry via AST (never importing
the package) and audits every emission and dispatch site against it;
``PROTOCOL.json`` pins the registry at the repo root (the
KERNEL_BUDGETS discipline — any drift fails CI in both directions;
deliberate changes go through ``python -m tools.graftwire
--update-protocol``, which enforces the :data:`PROTOCOL_VERSION` bump
rule: additions need a minor bump, removals/renames a major).

The registry literals are pure (no computed values): both
``ast.literal_eval`` (graftwire) and ``json`` (the pin) must be able
to round-trip them.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, MutableMapping, Optional

from .checkpoint import _WIRE_REQUIRED, WIRE_VERSION

__all__ = [
    "PROTOCOL_VERSION",
    "K_OP",
    "K_EVENT",
    "OP_DEFAULT",
    "WIRE_OPS",
    "WIRE_EVENTS",
    "CHECKPOINT_WIRE",
    "doc_op",
    "doc_event",
    "op_submit",
    "op_pause",
    "op_cancel",
    "op_split",
    "op_stats",
    "op_metrics",
    "op_shutdown",
    "ev_accepted",
    "ev_hit",
    "ev_done",
    "ev_paused",
    "ev_cancelled",
    "ev_failed",
    "ev_refused",
    "ev_shard_done",
    "ev_range_reassign",
    "ev_migrating",
    "ev_draining",
    "ev_stats",
    "ev_metrics",
    "ev_error",
    "ev_error_overloaded",
    "ev_bye",
    "validate_doc",
]

#: The wire contract's own version (MAJOR.MINOR), independent of the
#: checkpoint doc's ``wire_version``: field/op/event ADDITIONS bump the
#: minor (old readers ignore unknown fields), removals/renames bump the
#: major.  ``--update-protocol`` refuses a re-pin that violates this.
PROTOCOL_VERSION = "1.2"

#: The two envelope keys.  Outside this module they are banned as raw
#: string literals (graftwire GW005, the GL012 sprawl discipline) —
#: dispatch reads go through :func:`doc_op` / :func:`doc_event`,
#: emissions through the constructors below.
K_OP = "op"
K_EVENT = "event"

#: A document with no ``op`` is a submit (the serve tier's historical
#: default — bare job docs piped into ``a5gen serve`` just work).
OP_DEFAULT = "submit"

#: Every op a session dispatches.  ``handlers`` names the roles whose
#: session MUST decide the op (graftwire GW002 diffs this against the
#: extracted ``_handle`` tables, generalizing graftrace GT004):
#: ``engine`` = ``_JsonlSession``, ``router`` = ``_RouterSession``.
#: The router additionally forwards unknown id-carrying ops verbatim
#: (``ROUTER_PASSTHROUGH_OPS`` + the fallback branch), so engine-only
#: ops stay fleet-compatible without a router release.
WIRE_OPS: Dict[str, Dict[str, Any]] = {
    "submit": {
        "required": [],
        "optional": [
            "id", "tables", "table_map", "dict", "words",
            "digests", "digest_list", "algo", "mode",
            "table_min", "table_max", "config", "checkpoint",
            "replay_mute", "output", "tenant", "deadline_s",
        ],
        "handlers": ["engine", "router"],
        "default": True,
        "note": (
            "tenant/deadline_s are router-side admission fields; the "
            "router strips checkpoint/replay_mute into its own replay "
            "origin and re-injects them on dispatch"
        ),
    },
    "pause": {
        "required": ["id"],
        "optional": [],
        "handlers": ["engine", "router"],
    },
    "resume": {
        "required": ["id"],
        "optional": [],
        "handlers": ["engine", "router"],
    },
    "cancel": {
        "required": ["id"],
        "optional": [],
        "handlers": ["engine", "router"],
    },
    "migrate": {
        "required": ["id"],
        "optional": ["engine"],
        "handlers": ["router"],
        "note": (
            "router-only: rebalance one running job (pause -> "
            "checkpoint over the wire -> resubmit); the engine never "
            "sees it"
        ),
    },
    "drain": {
        "required": ["engine"],
        "optional": [],
        "handlers": ["router"],
        "note": (
            "router-only: stop placements on one engine and migrate "
            "every routed job off it (the autoscaler's reap half)"
        ),
    },
    "split": {
        "required": ["id"],
        "optional": ["shards"],
        "handlers": ["router"],
        "note": (
            "router-only: scatter one running crack job's superstep "
            "block lattice across shards engines as disjoint "
            "rank-stride pod ranges (pause -> checkpoint -> N shard "
            "resubmits); the merged client stream stays (word,rank)-"
            "ordered and exactly-once, and each shard checkpoint "
            "stays interchangeable with a solo resume"
        ),
    },
    "stats": {
        "required": [],
        "optional": [],
        "handlers": ["engine", "router"],
    },
    "metrics": {
        "required": [],
        "optional": [],
        "handlers": ["engine", "router"],
    },
    "shutdown": {
        "required": [],
        "optional": [],
        "handlers": ["engine", "router"],
    },
}

#: Every event a session emits.  ``emitters`` names who builds it;
#: ``route`` declares the router's event-plane decision for it
#: (graftwire GW002 checks ``dispatch`` events against the extracted
#: ``_on_job_event`` chain):
#:
#: * ``dispatch`` — engine-emitted per-job event the router handles
#:   explicitly (mute/settle/validate logic).
#: * ``passthrough`` — per-job event the router's fallback forwards
#:   verbatim (future engine events stay fleet-compatible).
#: * ``control`` — request-plane reply consumed by
#:   ``EngineLink.request``/``health_request`` (id-less, or correlated
#:   to the op that asked); never enters the event plane.
#: * ``synthesized`` — router-built and client-facing only: a DECLARED
#:   sender/handler asymmetry (the engine never emits it), with the
#:   justification in ``note``.
WIRE_EVENTS: Dict[str, Dict[str, Any]] = {
    "accepted": {
        "required": ["id", "kind"],
        "optional": ["engine", "queued", "resumed", "shards"],
        "emitters": ["engine", "router"],
        "route": "control",
        "note": (
            "the engine's ack answers the router's dispatch request "
            "plane; the router synthesizes its own client-facing ack "
            "with the engine/queued additions (which engine the job "
            "placed on — null while admission-queued — and whether it "
            "waits in the pending queue); shards appends only on a "
            "split scatter's ack (how many rank-stride shard ranges "
            "the job fanned out over)"
        ),
    },
    "hit": {
        "required": ["id", "digest", "plain_hex", "word_index", "rank"],
        "optional": [],
        "emitters": ["engine"],
        "route": "dispatch",
        "note": (
            "rank is a decimal string: variant spaces exceed JSON's "
            "safe ints"
        ),
    },
    "done": {
        "required": ["id", "n_hits", "n_emitted", "wall_s", "resumed"],
        "optional": ["ttfc_s", "schema_cache", "spans"],
        "emitters": ["engine"],
        "route": "dispatch",
    },
    "paused": {
        "required": ["id", "checkpoint"],
        "optional": ["spans"],
        "emitters": ["engine"],
        "route": "dispatch",
        "note": "checkpoint is the CHECKPOINT_WIRE doc (a paused job "
                "IS its checkpoint)",
    },
    "cancelled": {
        "required": ["id"],
        "optional": [],
        "emitters": ["engine", "router"],
        "route": "dispatch",
        "note": (
            "router-emitted for jobs nothing runs engine-side "
            "(paused or admission-queued cancels)"
        ),
    },
    "failed": {
        "required": ["id", "error"],
        "optional": [
            "reason", "retry_after_s", "checkpoint",
            "checkpoint_invalid",
        ],
        "emitters": ["engine", "router"],
        "route": "dispatch",
        "note": (
            "checkpoint is the quarantine token (resubmittable replay "
            "origin); checkpoint_invalid replaces it when capture-time "
            "validation rejected the doc; error=overloaded sheds "
            "carry reason + retry_after_s"
        ),
    },
    "refused": {
        "required": ["id"],
        "optional": ["jobs", "fill"],
        "emitters": ["engine"],
        "route": "passthrough",
        "note": (
            "dynamic re-fuse notification (PERF.md 28): the job's "
            "fused group dropped below the fill threshold after a "
            "tenant departed and its survivors were re-fused into a "
            "tighter group; jobs = survivor count, fill = the "
            "triggering fill ratio.  Informational — streams, "
            "checkpoints and results are unchanged — so the router's "
            "fallback forwards it verbatim"
        ),
    },
    "shard_done": {
        "required": ["id", "shard", "shards"],
        "optional": ["engine", "n_hits"],
        "emitters": ["router"],
        "route": "synthesized",
        "note": (
            "router-synthesized split-job progress: shard (0-based "
            "stripe index) of shards finished its disjoint block "
            "range on engine with n_hits forwarded into the merge; "
            "the engine only ever sees ordinary pod-striped crack "
            "jobs, so it never emits this"
        ),
    },
    "range_reassign": {
        "required": ["id", "shard", "shards"],
        "optional": ["from", "to", "acked"],
        "emitters": ["router"],
        "route": "synthesized",
        "note": (
            "router-synthesized split-job recovery: shard's block "
            "range moved engines (from -> to) after a death or "
            "rebalance, resuming from its last acked checkpoint "
            "boundary with acked hits muted — never replayed into "
            "the client"
        ),
    },
    "migrating": {
        "required": ["id", "from", "to"],
        "optional": ["noop"],
        "emitters": ["router"],
        "route": "synthesized",
        "note": (
            "router-synthesized migrate ack (to='(placement)' when "
            "the target is placement-chosen); the engine has no "
            "migrate op to answer"
        ),
    },
    "draining": {
        "required": ["engine", "jobs"],
        "optional": [],
        "emitters": ["router"],
        "route": "synthesized",
        "note": (
            "router-synthesized drain ack (jobs = count set "
            "migrating); drain never reaches an engine"
        ),
    },
    "stats": {
        "required": [],
        "optional": [],
        "open": True,
        "emitters": ["engine", "router"],
        "route": "control",
        "note": (
            "open doc: the engine's counter scrape spread flat (the "
            "router sums live engines and adds a fleet section), so "
            "the field set is the stats surface, not a fixed schema"
        ),
    },
    "metrics": {
        "required": ["metrics", "prometheus"],
        "optional": [],
        "emitters": ["engine", "router"],
        "route": "control",
    },
    "error": {
        "required": ["error"],
        "optional": ["id", "reason", "retry_after_s"],
        "emitters": ["engine", "router"],
        "route": "passthrough",
        "note": (
            "correlated replies answer the request plane; an "
            "id-carrying error with no waiter rides the event plane's "
            "fallback to the client.  error=overloaded (typed "
            "admission rejection) carries reason + retry_after_s"
        ),
    },
    "bye": {
        "required": [],
        "optional": [],
        "emitters": ["engine", "router"],
        "route": "control",
    },
}

#: The checkpoint wire doc (the pause/migrate handoff payload and the
#: replicated-ledger handoff guarantee): mirrored from
#: ``runtime/checkpoint.py`` so the pin covers it; the assert below
#: fails the import if the two modules ever disagree.
CHECKPOINT_WIRE: Dict[str, Any] = {
    "version": "1.0",
    "required": [
        "fingerprint", "cursor", "n_emitted", "n_hits", "hits",
        "wall_s",
    ],
    "note": (
        "minor-newer docs may carry unknown extra fields; "
        "state_from_doc -> state_to_doc round-trips them verbatim"
    ),
}

assert CHECKPOINT_WIRE["version"] == WIRE_VERSION, (
    "protocol.CHECKPOINT_WIRE drifted from checkpoint.WIRE_VERSION"
)
assert CHECKPOINT_WIRE["required"] == list(_WIRE_REQUIRED), (
    "protocol.CHECKPOINT_WIRE drifted from checkpoint._WIRE_REQUIRED"
)

#: Sentinel distinguishing "key absent" from "key present with None"
#: (the router's accepted ack carries ``engine: null`` while a job is
#: admission-queued).
_UNSET: Any = object()


# ---------------------------------------------------------------------------
# Dispatch-side reads
# ---------------------------------------------------------------------------


def doc_op(doc: Mapping[str, Any]) -> Any:
    """The op a command doc names (:data:`OP_DEFAULT` when absent)."""
    return doc.get(K_OP, OP_DEFAULT)


def doc_event(ev: Mapping[str, Any]) -> Any:
    """The event kind of a reply/event doc (None when absent)."""
    return ev.get(K_EVENT)


# ---------------------------------------------------------------------------
# Op constructors (what the router sends its engines)
# ---------------------------------------------------------------------------


def op_submit(sdoc: MutableMapping[str, Any]) -> MutableMapping[str, Any]:
    """Stamp the submit op onto a sanitized job doc IN PLACE (the
    client's fields keep their wire order; ``op`` lands where the
    client put it, or appends) and return it — the router's
    re-submittable replay origin."""
    sdoc[K_OP] = "submit"
    return sdoc


def op_pause(jid: str) -> Dict[str, Any]:
    return {K_OP: "pause", "id": jid}


def op_cancel(jid: str) -> Dict[str, Any]:
    return {K_OP: "cancel", "id": jid}


def op_split(jid: str, *, shards: Optional[int] = None
             ) -> Dict[str, Any]:
    """The router-only split op: scatter one running crack job across
    ``shards`` engines (placement-chosen when omitted)."""
    doc: Dict[str, Any] = {K_OP: "split", "id": jid}
    if shards is not None:
        doc["shards"] = shards
    return doc


def op_stats() -> Dict[str, Any]:
    return {K_OP: "stats"}


def op_metrics() -> Dict[str, Any]:
    return {K_OP: "metrics"}


def op_shutdown() -> Dict[str, Any]:
    return {K_OP: "shutdown"}


# ---------------------------------------------------------------------------
# Event constructors (one per declared event; key order is the wire
# order the fleet byte-parity suites pin)
# ---------------------------------------------------------------------------


def ev_accepted(
    jid: Any,
    kind: Any,
    *,
    engine: Any = _UNSET,
    queued: bool = False,
    resumed: bool = False,
    shards: Optional[int] = None,
) -> Dict[str, Any]:
    """The admission ack.  ``engine`` is router-only (pass even when
    None — a queued job's ack carries ``engine: null``); ``queued`` /
    ``resumed`` append only when set, matching the historical docs;
    ``shards`` appends only on a split scatter's ack (PERF.md §31)."""
    ev: Dict[str, Any] = {"id": jid, K_EVENT: "accepted", "kind": kind}
    if engine is not _UNSET:
        ev["engine"] = engine
    if queued:
        ev["queued"] = True
    if resumed:
        ev["resumed"] = True
    if shards is not None:
        ev["shards"] = int(shards)
    return ev


def ev_hit(
    jid: Any,
    *,
    digest: str,
    plain_hex: str,
    word_index: int,
    rank: str,
) -> Dict[str, Any]:
    return {
        "id": jid, K_EVENT: "hit",
        "digest": digest,
        "plain_hex": plain_hex,
        "word_index": word_index,
        "rank": rank,
    }


def ev_done(
    jid: Any,
    *,
    n_hits: int,
    n_emitted: int,
    wall_s: float,
    resumed: bool,
    ttfc_s: Optional[float] = None,
    schema_cache: Any = None,
    spans: Any = None,
) -> Dict[str, Any]:
    ev: Dict[str, Any] = {
        "id": jid, K_EVENT: "done",
        "n_hits": n_hits, "n_emitted": n_emitted,
        "wall_s": wall_s, "resumed": resumed,
    }
    if ttfc_s is not None:
        ev["ttfc_s"] = ttfc_s
    if schema_cache:
        ev["schema_cache"] = schema_cache
    if spans:
        ev["spans"] = spans
    return ev


def ev_paused(
    jid: Any, checkpoint: Dict[str, Any], *, spans: Any = None
) -> Dict[str, Any]:
    ev: Dict[str, Any] = {
        "id": jid, K_EVENT: "paused",
        "checkpoint": checkpoint,
    }
    if spans:
        ev["spans"] = spans
    return ev


def ev_cancelled(jid: Any) -> Dict[str, Any]:
    return {"id": jid, K_EVENT: "cancelled"}


def ev_failed(
    jid: Any,
    error: str,
    *,
    reason: Optional[str] = None,
    retry_after_s: Optional[float] = None,
    checkpoint: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The failure event.  ``reason``/``retry_after_s`` are the typed
    overload shed's fields; ``checkpoint`` is the quarantine token
    (PERF.md §23) — appended last, matching the historical docs."""
    ev: Dict[str, Any] = {"id": jid, K_EVENT: "failed", "error": error}
    if reason is not None:
        ev["reason"] = reason
    if retry_after_s is not None:
        ev["retry_after_s"] = retry_after_s
    if checkpoint is not None:
        ev["checkpoint"] = checkpoint
    return ev


def ev_refused(
    jid: Any,
    *,
    jobs: Optional[int] = None,
    fill: Optional[float] = None,
) -> Dict[str, Any]:
    """The dynamic re-fuse notification (PERF.md §28): this job's
    fused group fell below the fill threshold and its survivors were
    re-fused into a tighter group.  Informational — the job's stream,
    checkpoints and results are unchanged."""
    ev: Dict[str, Any] = {"id": jid, K_EVENT: "refused"}
    if jobs is not None:
        ev["jobs"] = jobs
    if fill is not None:
        ev["fill"] = fill
    return ev


def ev_shard_done(
    jid: Any,
    *,
    shard: int,
    shards: int,
    engine: Optional[str] = None,
    n_hits: Optional[int] = None,
) -> Dict[str, Any]:
    """Router-synthesized split-job progress: one shard's disjoint
    block range finished; the merged client stream keeps flowing from
    the other shards."""
    ev: Dict[str, Any] = {
        "id": jid, K_EVENT: "shard_done",
        "shard": shard, "shards": shards,
    }
    if engine is not None:
        ev["engine"] = engine
    if n_hits is not None:
        ev["n_hits"] = n_hits
    return ev


def ev_range_reassign(
    jid: Any,
    *,
    shard: int,
    shards: int,
    frm: Optional[str] = None,
    to: Optional[str] = None,
    acked: Optional[int] = None,
) -> Dict[str, Any]:
    """Router-synthesized split-job recovery: one shard's block range
    moved engines, resuming from its last acked checkpoint boundary
    with ``acked`` already-forwarded hits muted."""
    ev: Dict[str, Any] = {
        "id": jid, K_EVENT: "range_reassign",
        "shard": shard, "shards": shards,
    }
    if frm is not None:
        ev["from"] = frm
    if to is not None:
        ev["to"] = to
    if acked is not None:
        ev["acked"] = acked
    return ev


def ev_migrating(
    jid: Any, *, frm: str, to: str, noop: bool = False
) -> Dict[str, Any]:
    ev: Dict[str, Any] = {
        "id": jid, K_EVENT: "migrating", "from": frm, "to": to,
    }
    if noop:
        ev["noop"] = True
    return ev


def ev_draining(engine_id: str, jobs: int) -> Dict[str, Any]:
    return {K_EVENT: "draining", "engine": engine_id, "jobs": jobs}


def ev_stats(
    payload: Mapping[str, Any], *, fleet: Any = _UNSET
) -> Dict[str, Any]:
    """The stats reply: ``payload`` (the counter scrape) spreads flat
    after the event key; the router's merged form appends its
    ``fleet`` section last."""
    ev: Dict[str, Any] = {K_EVENT: "stats"}
    ev.update(payload)
    if fleet is not _UNSET:
        ev["fleet"] = fleet
    return ev


def ev_metrics(
    metrics: Mapping[str, Any], prometheus: str
) -> Dict[str, Any]:
    return {
        K_EVENT: "metrics",
        "metrics": metrics,
        "prometheus": prometheus,
    }


def ev_error(error: str, *, jid: Any = None) -> Dict[str, Any]:
    """The protocol-scoped error reply; ``id`` appends when the
    failing op named one (routing layers demux events by id)."""
    ev: Dict[str, Any] = {K_EVENT: "error", "error": error}
    if jid is not None:
        ev["id"] = jid
    return ev


def ev_error_overloaded(
    reason: str, retry_after_s: float, *, jid: Any = None
) -> Dict[str, Any]:
    """The typed admission rejection (PERF.md §27): machine-parseable
    ``error: overloaded`` plus the router's backoff estimate."""
    ev: Dict[str, Any] = {
        K_EVENT: "error", "error": "overloaded",
        "reason": reason,
        "retry_after_s": retry_after_s,
    }
    if jid is not None:
        ev["id"] = jid
    return ev


def ev_bye() -> Dict[str, Any]:
    return {K_EVENT: "bye"}


# ---------------------------------------------------------------------------
# Validation
# ---------------------------------------------------------------------------


def validate_doc(doc: Mapping[str, Any]) -> Mapping[str, Any]:
    """Cheap structural validation against the registry: the doc's
    op/event is declared and every required field is present (open
    docs skip the field check).  Returns the doc; raises
    :class:`ValueError` otherwise.  This is the dynamic twin of
    graftwire's static GW001/GW003 — tests and future front doors
    (ROADMAP item 4) share one definition of well-formed."""
    if K_EVENT in doc:
        kind, spec = doc[K_EVENT], WIRE_EVENTS.get(doc[K_EVENT])
        family = "event"
    else:
        kind, spec = doc_op(doc), WIRE_OPS.get(doc_op(doc))
        family = "op"
    if spec is None:
        raise ValueError(
            f"undeclared {family} {kind!r} (runtime/protocol.py is "
            "the registry; new ops/events are declared there and "
            "re-pinned via --update-protocol)"
        )
    if not spec.get("open"):
        missing: List[str] = [
            f for f in spec["required"] if f not in doc
        ]
        if missing:
            raise ValueError(
                f"{family} {kind!r} doc is missing required "
                f"field(s): {', '.join(missing)}"
            )
    return doc

"""The sweep runner: the launch loop driving the fused device steps.

This is the reference's L5 scheduler re-thought for an accelerator
(``main.go:70-99``: one goroutine per word behind a counting semaphore, all
candidates funneled through one channel). Here the unit of work is a
*variant block* — a contiguous rank range of one word's mixed-radix space —
so per-word skew disappears and the whole sweep is a single linear cursor
``(word, rank)`` (SURVEY.md §5): checkpointable, resumable by pure replay,
and splittable across devices.

Two modes, mirroring the two halves of the reference's pipeline:

* **candidates** (:meth:`Sweep.run_candidates`) — the reference-compatible
  surface: every candidate streamed to a sink as raw bytes, per-word
  multiset-identical to the CPU oracle (global order is word order; in-word
  order is rank order, a documented divergence from DFS order — Q9 defines
  parity per word, not globally).
* **crack** (:meth:`Sweep.run_crack`) — what the reference pipes into
  hashcat for (``README.MD:69``): expand + hash + digest-membership fused
  on device; only hits cross back to the host, where the candidate is
  re-derived from its (word, rank) cursor and its digest re-verified with a
  host hash — every reported hit is double-checked by construction.

Words the device plans cannot handle exactly (substitute-all cascade
hazards, ``ops.expand_suball``) are routed through the byte-exact CPU
oracle *in word order*, interleaved at the word's position so candidates
mode preserves global word ordering.

Device launches are double-buffered: launch N+1 is dispatched before launch
N's outputs are fetched, so host block-cutting and device compute overlap
(JAX async dispatch does the rest).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..models.attack import (
    AttackSpec,
    block_arrays,
    build_plan,
    decode_variant,
    digest_arrays,
    lane_cursor,
    make_candidates_step,
    make_crack_step,
    make_superstep_step,
    piece_arrays,
    plan_arrays,
    scalar_units_arrays,
    superstep_arrays,
    table_arrays,
    unpack_bits,
)
from ..oracle.engines import iter_candidates
from ..ops.blocks import block_cursor, make_blocks, superstep_index
from ..ops.membership import HostDigestLookup, build_digest_set
from ..ops.packing import PackedWords, pack_words
from ..tables.compile import compile_table
from ..utils.digests import HOST_DIGEST
from .checkpoint import (
    CheckpointState,
    SweepCursor,
    load_checkpoint,
    save_checkpoint,
    sweep_fingerprint,
)
from .progress import ProgressReporter
from .sinks import CandidateWriter, HitRecord, HitRecorder


@dataclass
class SweepConfig:
    """Launch geometry + runtime knobs (none of these affect WHAT is
    emitted — the checkpoint fingerprint deliberately excludes them, so a
    checkpoint taken at one geometry/device count resumes at any other)."""

    lanes: int = 1 << 17  # variant lanes per device per launch
    num_blocks: Optional[int] = 1024  # static per-device block count (jit
    #   stability). None = auto: resolved by the Sweep once plan/table
    #   eligibility is known — lanes/512 (lanes/256 for suball) when the
    #   fused kernel will take the launch, else lanes/128: the measured
    #   per-arm best geometries (PERF.md §9b).
    max_in_flight: int = 2  # double-buffered launches
    fetch_chunk: int = 16  # crack mode: max launches whose counts accumulate
    #   ON DEVICE between host fetches. A device->host fetch costs a full
    #   round trip (~65 ms over the remote-device tunnel — several times a
    #   launch's device time; PERF.md §4), so the crack loop chains per-
    #   launch (n_emitted, n_hits) into a device accumulator and fetches
    #   once per chunk; per-launch hit masks are fetched only for chunks
    #   whose hit count is nonzero (hits are rare). The chunk fetch is a
    #   completion barrier over its whole chain, so in-flight device work
    #   stays bounded at fetch_chunk + max_in_flight launches. Chunks grow
    #   adaptively 1 -> fetch_chunk while drains stay under ~1 s, so small
    #   sweeps and fast backends keep per-launch checkpoint granularity.
    devices: Optional[int] = 1  # 1 = single-device; N = shard over first N
    #                             local devices; None = all local devices
    superstep: "Optional[int]" = None  # crack mode: launches fused into ONE
    #   device dispatch via the device-resident superstep executor (a
    #   lax.scan cuts each step's blocks ON DEVICE from per-sweep index
    #   arrays — no per-launch host cutting, dispatch, or block-field
    #   transfer; PERF.md §15). None = auto: engage when the plan/geometry
    #   qualify (fixed-stride layout, int32-safe block index), with
    #   fetch_chunk steps per superstep. 0 = off (the per-launch pipeline).
    #   N >= 1 pins the steps-per-superstep (capped so a superstep's int32
    #   emitted-count accumulator cannot overflow). The streams are
    #   identical either way; A5GEN_SUPERSTEP=off is the env escape hatch.
    pipeline: Optional[bool] = None  # crack mode: double-buffered superstep
    #   drive (PERF.md §18). The driver keeps TWO alternating device hit/
    #   counter buffer sets and dispatches superstep N+1 into set B before
    #   fetching set A's counters, so the once-per-superstep fetch overlaps
    #   the next superstep's compute instead of barriering the chain (the
    #   honest-sync rule moves: the fetch of set A is the completion
    #   barrier for superstep N ONLY). Replay and checkpoints land at the
    #   fetched (lagged) superstep boundary; shutdown drains the in-flight
    #   superstep. None = auto: on whenever the superstep executor engages
    #   and max_in_flight >= 2. False = barriered drive (fetch right after
    #   dispatch — the A/B arm). A5GEN_PIPELINE=off is the env escape
    #   hatch; the streams are identical either way.
    superstep_hit_cap: int = 4096  # capped device (word, rank) hit buffer
    #   carried through the superstep scan, PER DEVICE. A superstep whose
    #   device-local hits exceed the cap is replayed exactly through the
    #   per-launch path (hits are rare; replay is the graceful-degradation
    #   guarantee — never a dropped hit).
    packed_blocks: Optional[bool] = None  # True = variable-offset (tightly
    #   packed) block layout; False = fixed-stride blocks (stride = lanes //
    #   num_blocks) — the kernels map lane -> block arithmetically instead
    #   of binary-searching per lane (PERF.md). None = auto: fixed-stride
    #   whenever num_blocks divides lanes evenly (it wins on every backend
    #   since the f32 decode + vectorized cutter landed — PERF.md §4c),
    #   packed otherwise. The layouts are stream-identical; only throughput
    #   differs.
    checkpoint_path: Optional[str] = None
    checkpoint_every_s: float = 30.0
    progress: Optional[ProgressReporter] = None

    def resolve_block_stride(self) -> Optional[int]:
        """Lanes-per-block of the fixed-stride layout; None = packed.

        An EXPLICIT stride request (``packed_blocks=False``) with a
        non-divisible geometry raises instead of silently degrading to
        packed; auto mode quietly falls back (the layouts are
        stream-identical, only throughput differs)."""
        if self.num_blocks is None:
            raise ValueError(
                "num_blocks=None (auto) is resolved by the Sweep once plan "
                "eligibility is known; resolve_block_stride needs a "
                "concrete block count"
            )
        packed = self.packed_blocks
        if packed is None:
            packed = self.lanes % self.num_blocks != 0
        if packed:
            return None
        if self.lanes % self.num_blocks:
            raise ValueError(
                f"fixed-stride layout needs lanes ({self.lanes}) divisible "
                f"by blocks ({self.num_blocks}); adjust the geometry or use "
                "the packed layout"
            )
        return self.lanes // self.num_blocks


@dataclass
class SweepResult:
    n_emitted: int = 0
    n_hits: int = 0
    hits: List[HitRecord] = field(default_factory=list)
    words_done: int = 0
    resumed: bool = False
    wall_s: float = 0.0
    #: word routing counts: device_clean / device_closed / oracle_fallback
    routing: Dict[str, int] = field(default_factory=dict)
    #: superstep executor stats (empty when the per-launch path ran):
    #: supersteps / launches (steps executed inside them) / replays
    #: (overflow supersteps re-run per-launch) / launches_per_fetch
    superstep: Dict[str, int] = field(default_factory=dict)


class _FallbackPrefetcher:
    """Oracle-fallback expansion on a worker thread (VERDICT r3 #5).

    The launch loop spends most of its wall-clock blocked on device fetches
    — which release the GIL — so a single producer thread expands the
    oracle-routed hazard words CONCURRENTLY with device execution instead
    of serially between launches. A bounded queue gives backpressure
    (bounded memory even for huge fallback expansions); candidates still
    reach the sink in word order because the consumer drains row by row.
    """

    _END = object()

    def __init__(self, sweep: "Sweep", start_index: int,
                 maxsize: int = 8192) -> None:
        import queue
        import threading

        self._queue: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._sweep = sweep
        self._start = start_index
        self._stop = False
        self._thread = threading.Thread(
            target=self._produce, name="a5-fallback-oracle", daemon=True
        )
        self._thread.start()

    def _produce(self) -> None:
        rows = self._sweep.fallback_rows
        try:
            for idx in range(self._start, len(rows)):
                for i, cand in enumerate(
                    self._sweep._oracle_candidates(rows[idx])
                ):
                    if self._stop:
                        return
                    self._queue.put((i, cand))
                self._queue.put(self._END)
        except BaseException as e:  # noqa: BLE001 — re-raised in iter_row
            # A dying producer must not strand the consumer on a queue.get
            # that no sentinel will ever satisfy: ship the exception across
            # the queue so the sweep aborts with the real error, exactly as
            # the old inline oracle path did.
            self._queue.put(e)

    def iter_row(self):
        """Yield this row's (dfs_index, candidate) pairs; stops at the row's
        end marker. Must be called once per fallback row, in row order.
        Re-raises any exception the producer hit."""
        while True:
            item = self._queue.get()
            if item is self._END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def close(self) -> None:
        """Stop the producer; safe to call with the queue in any state."""
        self._stop = True
        # Unblock a producer stuck on a full queue, then wait briefly.
        for _ in range(100):
            if not self._thread.is_alive():
                return
            try:
                while True:
                    self._queue.get_nowait()
            except Exception:
                pass
            self._thread.join(timeout=0.05)


class Sweep:
    """One wordlist × one merged table × one attack spec."""

    def __init__(
        self,
        spec: AttackSpec,
        sub_map: Dict[bytes, List[bytes]],
        words: "Sequence[bytes] | PackedWords",
        digests: Sequence[bytes] = (),
        config: Optional[SweepConfig] = None,
    ) -> None:
        self.spec = spec
        self.sub_map = sub_map
        # A [N, digest_bytes] uint8 matrix (the CLI's vectorized left-list
        # parser) stays a matrix — hashmob-scale lists must not explode
        # into tens of millions of Python bytes objects.
        self.digests = (
            digests if isinstance(digests, np.ndarray) else list(digests)
        )
        # One sort serves both the fingerprint's canonical blob and
        # per-hit host membership (matrix/list duality lives in the
        # lookup, ops.membership.HostDigestLookup).
        self._digest_lookup = HostDigestLookup(self.digests)
        self.config = config or SweepConfig()
        self.ct = compile_table(sub_map)
        # A pre-packed batch (e.g. the native scanner's read_packed) is
        # accepted directly — the rockyou-scale path never materializes a
        # Python list of words.
        self.packed = (
            words if isinstance(words, PackedWords) else pack_words(list(words))
        )
        self.n_words = self.packed.batch
        self.plan = build_plan(spec, self.ct, self.packed)
        # Windowed plans renumber every (word, rank) cursor, so a checkpoint
        # from one enumeration scheme must never resume under the other —
        # the scheme is part of the fingerprint's mode token. (Scheme choice
        # is deterministic in the fingerprinted inputs; the token guards
        # against cross-version resumes.) Cascade closure likewise changes
        # WHICH words the device cursor covers (closed words leave the
        # fallback set), so it gets its own token.
        closed_arr = getattr(self.plan, "closed", None)
        n_closed = int(closed_arr.sum()) if closed_arr is not None else 0
        mode_token = spec.mode + (
            "+windowed" if getattr(self.plan, "windowed", False) else ""
        ) + ("+closed" if n_closed else "")
        self.fingerprint = sweep_fingerprint(
            mode_token,
            spec.algo,
            spec.min_substitute,
            spec.max_substitute,
            sub_map,
            self.packed,  # buffer-level hash, no per-word Python loop
            self.digests,
            digest_lookup=self._digest_lookup,  # reuse its one sort
        )
        self._host_digest = HOST_DIGEST[spec.algo]
        #: fallback word rows in word order (oracle-routed, SURVEY.md §2.4)
        self.fallback_rows: List[int] = [
            int(i) for i in np.nonzero(self.plan.fallback)[0]
        ]
        #: three-way word routing (PERF.md §5/§14): clean device words,
        #: cascade-closed device words, oracle-routed pathological words.
        self.routing: Dict[str, int] = {
            "device_clean": self.n_words - n_closed - len(self.fallback_rows),
            "device_closed": n_closed,
            "oracle_fallback": len(self.fallback_rows),
        }
        set_routing = getattr(self.config.progress, "set_routing", None)
        if set_routing is not None:
            set_routing(self.routing)

    def _auto_num_blocks(self, kind: str) -> int:
        """Resolve ``num_blocks=None``: the measured per-arm best geometry
        (PERF.md §9b/§11) — when the fused Pallas kernel will take the
        launch, the K=1 scalar-units path peaks at stride 128 (best
        fill; §11 removed most of the per-block cost), the general
        kernel at stride 512 (256 for suball: its Π(options+1) variant
        space fills larger strides poorly); the XLA path peaks at
        stride 128.  Candidates mode never engages the fused kernel
        (``make_candidates_step`` has no fused path), so it always gets
        the XLA-best stride."""
        from ..ops.pallas_expand import opts_for, scalar_units_for

        lanes = self.config.lanes
        if kind == "crack":
            if scalar_units_for(self.plan):
                pref = 128
            else:
                pref = 256 if self.spec.mode.startswith("suball") else 512
            if lanes % pref == 0:
                nb = lanes // pref
                if opts_for(self.spec, self.plan, self.ct,
                            block_stride=pref, num_blocks=nb) is not None:
                    return nb
        if lanes % 128 == 0:
            return lanes // 128
        return 1024

    def _digest_contains(self, dig: bytes) -> bool:
        """Host-side membership in the target digest list (fallback-word
        hits and device-hit re-verification)."""
        return dig in self._digest_lookup

    # ------------------------------------------------------------------
    # Shared machinery
    # ------------------------------------------------------------------

    def _oracle_candidates(self, row: int) -> Iterator[bytes]:
        word = self.packed.word(row)
        substitute_all = self.spec.mode.startswith("suball")
        reverse = self.spec.mode in ("reverse", "suball-reverse")
        # Hazard fallback words were the sweep's Amdahl bottleneck
        # (PERF.md §5: Python generators at ~1e5 cand/s against a device
        # at 1e8); the native engines stream the identical candidates
        # ~17x faster when eligible.
        eng = self._native_oracle(substitute_all=substitute_all,
                                  reverse=reverse)
        if eng is not None:
            return eng.iter_word(
                word, self.spec.min_substitute, self.spec.max_substitute,
                substitute_all=substitute_all, reverse=reverse,
            )
        return iter_candidates(
            word,
            self.sub_map,
            self.spec.min_substitute,
            self.spec.max_substitute,
            substitute_all=substitute_all,
            reverse=reverse,
        )

    def _native_oracle(self, *, substitute_all: bool, reverse: bool):
        """A cached NativeDefaultOracle for the fallback path, or None
        (ineligible / no toolchain — Python engines remain)."""
        cached = getattr(self, "_native_oracle_cache", ())
        if cached != ():
            return cached
        eng = None
        try:
            from ..native.oracle_engine import (
                NativeDefaultOracle,
                available,
                default_engine_eligible,
            )

            if default_engine_eligible(
                self.sub_map,
                substitute_all=substitute_all,
                reverse=reverse,
                crack=False,
                hex_unsafe=False,
                max_substitute=self.spec.max_substitute,
            ) and available():
                eng = NativeDefaultOracle(self.sub_map)
        except Exception:  # pragma: no cover - toolchain-dependent
            eng = None
        self._native_oracle_cache = eng
        return eng

    def _load_state(self, resume: bool) -> Tuple[CheckpointState, bool]:
        cfg = self.config
        if resume and cfg.checkpoint_path:
            state = load_checkpoint(cfg.checkpoint_path, self.fingerprint)
            if state is not None:
                return state, True
        return CheckpointState(fingerprint=self.fingerprint), False

    def _resolve_devices(self) -> int:
        """Device count for this run: config.devices, or all local devices
        when None (the mesh constructor validates availability)."""
        n = self.config.devices
        if n is None:
            import jax

            n = len(jax.devices())
        n = int(n)
        if n < 1:
            raise ValueError(f"SweepConfig.devices must be >= 1, got {n}")
        return n

    def _make_launch(self, kind: str):
        """Build this run's launch callable: ``kind`` is 'crack' or
        'candidates'.  Single-device builds the plain jitted step; multi-
        device builds the shard_map'd step over a 1-D mesh with plan/table
        (and digests, for crack) replicated.  Returns
        (launch(blocks) -> out, n_devices, mesh)."""
        if self.config.num_blocks is None:
            from dataclasses import replace

            self.config = replace(
                self.config, num_blocks=self._auto_num_blocks(kind)
            )
        spec, cfg, plan = self.spec, self.config, self.plan
        n_devices = self._resolve_devices()
        stride = cfg.resolve_block_stride()
        from ..ops.packing import piece_schema_for
        from ..ops.pallas_expand import (
            k_opts_for,
            opts_for,
            scalar_units_for,
        )

        # On TPU an eligible config swaps the crack step's expand+hash
        # pair for the fused Pallas kernel by default (ops.pallas_expand;
        # A5GEN_PALLAS=off opts out).
        fused_opts = opts_for(
            spec, plan, self.ct, block_stride=stride,
            num_blocks=cfg.num_blocks,
        )
        scalar_units = scalar_units_for(plan)
        # K=1 tables (all radices <= 2): the XLA decode collapses to bit
        # extraction (expand_matches.decode_digits radix2 path).
        radix2 = k_opts_for(plan) == 1
        # Per-slot piece emission (PERF.md §17; A5GEN_EMIT=bytescan opts
        # out): one schema drives the Pallas kernels AND the XLA splice.
        pieces = piece_schema_for(plan, self.ct)
        if n_devices == 1:
            p, t = plan_arrays(plan), table_arrays(self.ct)
            if fused_opts is not None and scalar_units:
                # Word-level scalar-unit fields precomputed once per
                # sweep; the kernel wrapper preps by gathering.
                p.update(scalar_units_arrays(plan, self.ct))
            if pieces is not None:
                p.update(piece_arrays(pieces))
            if kind == "crack":
                step = make_crack_step(
                    spec, num_lanes=cfg.lanes, out_width=plan.out_width,
                    block_stride=stride, fused_expand_opts=fused_opts,
                    fused_scalar_units=scalar_units, radix2=radix2,
                    pieces=pieces,
                )
                darrs = digest_arrays(
                    build_digest_set(self.digests, spec.algo)
                )
                # Step-build context the superstep executor reuses (same
                # device-resident arrays, same kernel selection — the two
                # paths must trace the identical fused body).
                self._step_ctx = dict(
                    arrays=(p, t, darrs), fused_opts=fused_opts,
                    scalar_units=scalar_units, radix2=radix2, stride=stride,
                    pieces=pieces,
                )
                return (lambda blocks: step(p, t, blocks, darrs)), 1, None
            step = make_candidates_step(
                spec, num_lanes=cfg.lanes, out_width=plan.out_width,
                block_stride=stride, radix2=radix2, pieces=pieces,
            )
            return (lambda blocks: step(p, t, blocks)), 1, None

        from ..parallel.mesh import (
            make_mesh,
            make_sharded_candidates_step,
            make_sharded_crack_step,
            replicate,
        )

        mesh = make_mesh(n_devices)
        if kind == "crack":
            step = make_sharded_crack_step(
                spec, mesh, lanes_per_device=cfg.lanes,
                out_width=plan.out_width, block_stride=stride,
                fused_expand_opts=fused_opts,
                fused_scalar_units=scalar_units, radix2=radix2,
                pieces=pieces,
            )
            parr = plan_arrays(plan)
            if fused_opts is not None and scalar_units:
                parr.update(scalar_units_arrays(plan, self.ct))
            if pieces is not None:
                parr.update(piece_arrays(pieces))
            p, t, darrs = replicate(
                mesh,
                (
                    parr,
                    table_arrays(self.ct),
                    digest_arrays(build_digest_set(self.digests, spec.algo)),
                ),
            )
            self._step_ctx = dict(
                arrays=(p, t, darrs), fused_opts=fused_opts,
                scalar_units=scalar_units, radix2=radix2, stride=stride,
                pieces=pieces,
            )
            return (lambda blocks: step(p, t, darrs, blocks)), n_devices, mesh
        step = make_sharded_candidates_step(
            spec, mesh, lanes_per_device=cfg.lanes, out_width=plan.out_width,
            block_stride=stride, radix2=radix2, pieces=pieces,
        )
        parr = plan_arrays(plan)
        if pieces is not None:
            parr.update(piece_arrays(pieces))
        p, t = replicate(mesh, (parr, table_arrays(self.ct)))
        return (lambda blocks: step(p, t, blocks)), n_devices, mesh

    # ------------------------------------------------------------------
    # Superstep executor (crack mode, PERF.md §15)
    # ------------------------------------------------------------------

    def _superstep_steps(self) -> Optional[int]:
        """Requested steps-per-superstep, or None when the superstep
        executor is off (``SweepConfig.superstep=0`` or
        ``A5GEN_SUPERSTEP=off``)."""
        from .env import env_opt_out

        if env_opt_out(
            "A5GEN_SUPERSTEP", "superstep on for eligible crack sweeps"
        ):
            return None
        cfg = self.config
        if cfg.superstep is not None and int(cfg.superstep) <= 0:
            return None
        return max(
            1, int(cfg.superstep) if cfg.superstep else int(cfg.fetch_chunk)
        )

    def _pipeline_depth(self) -> int:
        """In-flight superstep budget for :meth:`_drive_superstep`:
        ``max_in_flight`` buffer sets (default 2 — the double-buffered
        pipeline, PERF.md §18; deeper configs keep the pre-§18 loop's
        dispatch-ahead contract for long-latency links) unless the
        config or ``A5GEN_PIPELINE`` pins the barriered drive."""
        from .env import pipeline_enabled

        cfg = self.config
        if not pipeline_enabled():
            return 1
        if not (cfg.pipeline is None or cfg.pipeline):
            return 1
        # max_in_flight bounds the in-flight working set even when the
        # pipeline is explicitly requested — it is the device-memory
        # contract the per-launch path honors too (one buffer set per
        # in-flight superstep).
        return max(1, int(cfg.max_in_flight))

    def _make_superstep(self, cursor: SweepCursor, n_devices: int, mesh):
        """Build this run's superstep executor, or None when the
        per-launch pipeline should carry it: config/env opt-out, packed
        block layout, an int32-unsafe block index (huge words), or a
        stride-misaligned resume cursor (cross-geometry checkpoints).

        Returns a descriptor dict whose ``call(b0, bufs)`` dispatches one
        superstep starting at global block index ``b0`` into the device
        hit-buffer set ``bufs`` — ONE device program running ``steps``
        fused launches with on-device block cutting
        (``models.attack.make_superstep_body``); ``make_bufs()``
        allocates one buffer set (the pipelined driver cycles ``depth``
        of them).
        Must run after :meth:`_make_launch` (which resolves the geometry
        and stashes the step-build context the executor shares)."""
        steps = self._superstep_steps()
        if steps is None:
            return None
        cfg, plan = self.config, self.plan
        stride = cfg.resolve_block_stride()
        if stride is None:
            return None
        idx = superstep_index(plan, stride)
        if idx is None:
            return None
        cum, _totals, total_blocks = idx
        # Normalize the cursor exactly as make_blocks does (skip fallback
        # and finished words), then require stride alignment — misaligned
        # resumes keep the scalar per-launch path, as they always have.
        w, rank = cursor.word, cursor.rank
        while w < plan.batch and (
            plan.fallback[w] or rank >= plan.n_variants[w]
        ):
            w, rank = w + 1, 0
        if w < plan.batch and rank % stride:
            return None
        b0 = total_blocks if w >= plan.batch else int(cum[w]) + rank // stride
        if w < plan.batch and block_cursor(plan, stride, cum, b0) != (w, rank):
            # Resume integrity: the executor's start block must round-trip
            # to the (normalized) checkpoint cursor exactly — a cum/cursor
            # mismatch here would silently re-sweep or skip blocks, and a
            # drained pipelined run must land where the checkpoint says it
            # did (cross-path resumes pin this in tests).
            raise RuntimeError(
                f"superstep resume cursor mismatch: block {b0} decodes to "
                f"{block_cursor(plan, stride, cum, b0)}, checkpoint says "
                f"({w}, {rank}); the checkpoint does not match this "
                "plan/geometry"
            )
        # The superstep's device accumulator is int32: cap steps so a
        # worst case of every lane emitting cannot reach 2^31 per fetch.
        steps = max(1, min(
            steps, ((1 << 31) - 1) // max(1, cfg.lanes * n_devices)
        ))
        # The tail superstep's device cursor overshoots the sweep end by
        # up to one full superstep (those blocks cut zero-count); the
        # overshot indices must themselves stay int32, or `b < total`
        # comparisons wrap negative and resurrect word-0 blocks.
        if (
            total_blocks + (steps + 1) * cfg.num_blocks * n_devices
            >= (1 << 31)
        ):
            return None
        ctx = self._step_ctx
        hit_cap = int(cfg.superstep_hit_cap)
        common = dict(
            out_width=plan.out_width, block_stride=stride, steps=steps,
            hit_cap=hit_cap, total_blocks=total_blocks,
            windowed=bool(getattr(plan, "windowed", False)),
            fused_expand_opts=ctx["fused_opts"],
            fused_scalar_units=ctx["scalar_units"], radix2=ctx["radix2"],
            pieces=ctx["pieces"],
        )
        p, t, darrs = ctx["arrays"]
        if n_devices == 1:
            from ..models.attack import superstep_buffers

            step = make_superstep_step(
                self.spec, num_lanes=cfg.lanes, num_blocks=cfg.num_blocks,
                **common,
            )
            ss = superstep_arrays(plan, stride)
            make_bufs = lambda: superstep_buffers(hit_cap)  # noqa: E731

            def call(b: int, bufs):
                return step(p, t, darrs, ss, np.int32(b), bufs)
        else:
            from ..parallel.mesh import (
                make_sharded_superstep_step,
                replicate,
                shard_leading,
            )

            step = make_sharded_superstep_step(
                self.spec, mesh, lanes_per_device=cfg.lanes,
                num_blocks=cfg.num_blocks, **common,
            )
            ss = replicate(mesh, superstep_arrays(plan, stride))
            nb = cfg.num_blocks

            def make_bufs():
                per_dev = hit_cap + 1
                return shard_leading(mesh, {
                    "hit_word": np.full(
                        (n_devices * per_dev,), -1, np.int32
                    ),
                    "hit_rank": np.zeros(
                        (n_devices * per_dev,), np.int32
                    ),
                })

            def call(b: int, bufs):
                b0_dev = shard_leading(mesh, np.asarray(
                    [b + d * nb for d in range(n_devices)], np.int32
                ))
                return step(p, t, darrs, ss, b0_dev, bufs)

        return {
            "call": call,
            "make_bufs": make_bufs,
            "depth": self._pipeline_depth(),
            "steps": steps,
            "stride": stride,
            "cum": cum,
            "total_blocks": total_blocks,
            "hit_cap": hit_cap,
            "b0": b0,
            "advance": steps * cfg.num_blocks * n_devices,
        }

    def _drive_superstep(
        self, ss, state: CheckpointState, launch: Callable, n_devices: int,
        mesh, device_hit: Callable, fallback_candidate: Callable,
        prefetch, last_ckpt: List[float], process_launch_hits: Callable,
    ) -> Dict[str, int]:
        """The superstep launch loop: one dispatch and ONE device→host
        fetch per ``steps`` fused launches.  The drive is double-buffered
        over ``depth`` alternating device hit-buffer sets
        (``max_in_flight``, default 2 — PERF.md §18): superstep N+1 is
        dispatched into set B before set A's counters are fetched, so
        the fetch overlaps the next
        superstep's compute — the honest-sync rule moves to the lagged
        barrier: fetching set A completes superstep N ONLY, never the
        in-flight one, and nothing calls ``block_until_ready``.  A set is
        recycled only after its counters (and any hit slice) were
        consumed, which with donation makes the cycle a true double
        buffer.  A device whose capped hit buffer overflowed triggers an
        exact per-launch replay of that superstep's block range;
        checkpoint/progress/replay all land at the FETCHED (lagged)
        superstep boundary, and the loop exits only once the in-flight
        superstep is drained."""
        cfg, plan = self.config, self.plan
        cum, stride = ss["cum"], ss["stride"]
        total_blocks, hit_cap = ss["total_blocks"], ss["hit_cap"]
        advance, depth = ss["advance"], ss["depth"]
        stats = {"supersteps": 0, "launches": 0, "replays": 0,
                 "launches_per_fetch": ss["steps"],
                 "pipelined": int(depth > 1)}
        free_bufs = [ss["make_bufs"]() for _ in range(depth)]
        inflight: deque = deque()
        b0 = ss["b0"]
        while b0 < total_blocks or inflight:
            while b0 < total_blocks and len(inflight) < depth:
                inflight.append((b0, ss["call"](b0, free_bufs.pop())))
                b0 += advance
            sb0, out = inflight.popleft()
            # The ONE per-superstep fetch — the completion barrier for
            # superstep N only (N+1 keeps running on device).
            ne, nh = (int(x) for x in np.asarray(out["counters"]))
            end_b = min(sb0 + advance, total_blocks)
            end_w, end_r = block_cursor(plan, stride, cum, end_b)
            if nh:
                dev_hits = np.asarray(out["dev_hits"])
                if int(dev_hits.max()) > hit_cap:
                    # Graceful degradation: the capped device buffer
                    # dropped entries — replay this superstep exactly
                    # through the per-launch path (its hit processing is
                    # the accounting; the scan's counts stand).
                    stats["replays"] += 1
                    self._replay_superstep(
                        sb0, end_b, ss, launch, n_devices, mesh,
                        process_launch_hits,
                    )
                else:
                    hw = np.asarray(out["hit_word"])
                    hr = np.asarray(out["hit_rank"])
                    per_dev = hit_cap + 1  # trailing trash slot
                    entries: List[Tuple[int, int]] = []
                    for d in range(n_devices):
                        k = int(dev_hits[d])
                        lo = d * per_dev
                        entries.extend(zip(hw[lo:lo + k].tolist(),
                                           hr[lo:lo + k].tolist()))
                    # (word, rank) sort = cursor order: device stripes
                    # interleave by scan step, so the raw buffer order is
                    # per-device, not global.
                    entries.sort()
                    for w_row, rank in entries:
                        device_hit(int(w_row), int(rank))
            # Superstep N's buffers are fully consumed — recycle the set
            # for superstep N+2 (donation aliases the next dispatch's
            # outputs onto it).
            free_bufs.append({"hit_word": out["hit_word"],
                              "hit_rank": out["hit_rank"]})
            # Fallback words wholly before the cursor are due now.
            self._flush_fallback_until(
                end_w, state, fallback_candidate, prefetch
            )
            state.n_emitted += ne
            state.cursor = SweepCursor(end_w, end_r)
            stats["supersteps"] += 1
            stats["launches"] += ss["steps"]
            self._maybe_checkpoint(state, last_ckpt)
            if cfg.progress:
                cfg.progress.update(
                    words_done=end_w,
                    emitted=state.n_emitted,
                    hits=state.n_hits,
                )
        return stats

    def _replay_superstep(
        self, b_lo: int, b_hi: int, ss, launch: Callable, n_devices: int,
        mesh, process_launch_hits: Callable,
    ) -> None:
        """Exact per-launch replay of one superstep's block range — the
        hit-buffer overflow fallback.  The host fast cutter shares the
        device cutter's index arrays, so the replay cuts the SAME blocks
        and its per-launch hit bitmasks recover every dropped hit."""
        plan = self.plan
        stride, cum = ss["stride"], ss["cum"]
        w, rank = block_cursor(plan, stride, cum, b_lo)
        end = block_cursor(plan, stride, cum, b_hi)
        for segments, out, cur in self._launches(
            SweepCursor(w, rank), launch, n_devices=n_devices, mesh=mesh
        ):
            if int(out["n_hits"]):
                process_launch_hits(segments, out)
            if (cur.word, cur.rank) >= end:
                # In-flight launches past the range are dropped unfetched
                # (their hits belong to later supersteps' own buffers).
                break

    def _launches(
        self, cursor: SweepCursor, launch: Callable, *, n_devices: int = 1,
        mesh=None,
    ) -> Iterator[Tuple[list, object, SweepCursor]]:
        """Double-buffered launch stream: yields (segments, device out,
        cursor AFTER this launch); ``segments`` is a cursor-ordered list of
        ``(batch, lane_lo, lane_hi)`` — one entry per device, slicing the
        launch's flat lane axis. Dispatch runs ``max_in_flight`` ahead of
        fetch, so host block-cutting overlaps device execution."""
        import jax.profiler

        cfg = self.config
        stride = cfg.resolve_block_stride()
        pending: deque = deque()
        w, rank = cursor.word, cursor.rank
        lanes = cfg.lanes
        while True:
            # Annotated so a --profile trace shows how much wall-clock the
            # host-side scheduler costs vs the overlapped device launches.
            with jax.profiler.TraceAnnotation("a5.host_cut_blocks"):
                if n_devices == 1:
                    batch, w2, rank2 = make_blocks(
                        self.plan,
                        start_word=w,
                        start_rank=rank,
                        max_variants=lanes,
                        max_blocks=cfg.num_blocks,
                        fixed_stride=stride,
                    )
                    if batch.total == 0:
                        break
                    blocks = block_arrays(batch, num_blocks=cfg.num_blocks)
                    segments = [(batch, 0, lanes)]
                else:
                    from ..parallel.mesh import (
                        make_device_blocks,
                        shard_leading,
                        stack_blocks,
                    )

                    batches, w2, rank2 = make_device_blocks(
                        self.plan,
                        n_devices=n_devices,
                        lanes_per_device=lanes,
                        start_word=w,
                        start_rank=rank,
                        max_blocks=cfg.num_blocks,
                        fixed_stride=stride,
                    )
                    if sum(b.total for b in batches) == 0:
                        break
                    blocks = shard_leading(
                        mesh, stack_blocks(batches, num_blocks=cfg.num_blocks)
                    )
                    segments = [
                        (batches[d], d * lanes, (d + 1) * lanes)
                        for d in range(n_devices)
                    ]
            out = launch(blocks)
            pending.append((segments, out, SweepCursor(w2, rank2)))
            w, rank = w2, rank2
            if len(pending) >= cfg.max_in_flight:
                yield pending.popleft()
        while pending:
            yield pending.popleft()

    def _maybe_checkpoint(self, state: CheckpointState, last: List[float],
                          *, force: bool = False,
                          before_save: Optional[Callable[[], None]] = None
                          ) -> None:
        cfg = self.config
        if cfg.checkpoint_path is None:
            return
        now = time.monotonic()
        if force or now - last[0] >= cfg.checkpoint_every_s:
            if before_save is not None:
                # Durably land everything the cursor claims was emitted
                # BEFORE the checkpoint asserts it (else a crash between
                # the save and the flush loses output resume cannot replay).
                before_save()
            save_checkpoint(cfg.checkpoint_path, state)
            last[0] = now

    def _flush_fallback_until(
        self,
        word_row: int,
        state: CheckpointState,
        on_candidate: Callable[[int, int, bytes], None],
        prefetch: "Optional[_FallbackPrefetcher]" = None,
    ) -> None:
        """Emit every unprocessed fallback word < ``word_row`` (pass
        ``len(words)`` to flush all). Candidate callback gets (word_row,
        dfs_index, candidate). With ``prefetch``, rows come from the
        worker thread's queue (expanded concurrently with device
        launches); without, the oracle runs inline."""
        while (
            state.fallback_done < len(self.fallback_rows)
            and self.fallback_rows[state.fallback_done] < word_row
        ):
            row = self.fallback_rows[state.fallback_done]
            source = (
                prefetch.iter_row()
                if prefetch is not None
                else enumerate(self._oracle_candidates(row))
            )
            for i, cand in source:
                on_candidate(row, i, cand)
                state.n_emitted += 1
            state.fallback_done += 1

    def _make_prefetcher(
        self, state: CheckpointState
    ) -> "Optional[_FallbackPrefetcher]":
        if state.fallback_done >= len(self.fallback_rows):
            return None
        return _FallbackPrefetcher(self, state.fallback_done)

    # ------------------------------------------------------------------
    # Crack mode
    # ------------------------------------------------------------------

    def run_crack(
        self,
        recorder: Optional[HitRecorder] = None,
        *,
        resume: bool = True,
    ) -> SweepResult:
        """Fused expand→hash→membership; only hits return to the host."""
        spec, cfg, plan = self.spec, self.config, self.plan
        recorder = recorder if recorder is not None else HitRecorder()
        state, resumed = self._load_state(resume)
        if cfg.progress is not None:
            cfg.progress.seed_emitted(state.n_emitted)

        launch, n_devices, mesh = self._make_launch("crack")

        # Replay checkpointed hits into the recorder (resume produces the
        # same final hit list a never-interrupted run would). Fallback-word
        # hits carry a DFS index, not a variant rank — re-derive via oracle.
        fallback_set = set(self.fallback_rows)
        for w_row, rank in state.hits:
            if w_row in fallback_set:
                cand = next(
                    c
                    for i, c in enumerate(self._oracle_candidates(w_row))
                    if i == rank
                )
            else:
                cand = decode_variant(plan, self.ct, spec, w_row, rank)
            recorder.emit(
                HitRecord(
                    word_index=int(self.packed.index[w_row]),
                    variant_rank=rank,
                    candidate=cand,
                    digest_hex=self._host_digest(cand).hex(),
                )
            )

        def fallback_candidate(row: int, i: int, cand: bytes) -> None:
            dig = self._host_digest(cand)
            if self._digest_contains(dig):
                state.n_hits += 1
                state.hits.append((row, i))
                recorder.emit(
                    HitRecord(
                        word_index=int(self.packed.index[row]),
                        variant_rank=i,
                        candidate=cand,
                        digest_hex=dig.hex(),
                    )
                )

        import jax
        import jax.numpy as jnp

        # Per-launch counts chain into a device-side accumulator; the host
        # fetches it once per chunk (see SweepConfig.fetch_chunk). The fetch
        # is the completion barrier for the chunk's whole launch chain.
        accum = jax.jit(lambda acc, ne, nh: acc + jnp.stack([ne, nh]))
        acc_zero = jnp.zeros((2,), jnp.int32)

        def device_hit(w_row: int, rank: int) -> None:
            """One device-flagged hit, shared by the per-launch and
            superstep paths: flush oracle words that sit before this
            hit's word (the hit list stays word-ordered), re-derive the
            candidate, re-verify its digest on the host, record."""
            self._flush_fallback_until(
                w_row, state, fallback_candidate, prefetch
            )
            cand = decode_variant(plan, self.ct, spec, w_row, rank)
            dig = self._host_digest(cand)
            # Host re-verification: the device flagged this lane;
            # its digest must really be in the target set.
            if not self._digest_contains(dig):
                raise RuntimeError(
                    f"device hit failed host re-verification: "
                    f"word {w_row} rank {rank} candidate {cand!r}"
                )
            state.n_hits += 1
            state.hits.append((w_row, rank))
            recorder.emit(
                HitRecord(
                    word_index=int(self.packed.index[w_row]),
                    variant_rank=rank,
                    candidate=cand,
                    digest_hex=dig.hex(),
                )
            )

        def process_launch_hits(segments, out) -> None:
            hit = unpack_bits(out["hit_bits"], cfg.lanes * n_devices)
            # Segments are cursor-ordered (device d's lane slice precedes
            # device d+1's), so walking them in order keeps hits
            # word-ordered.
            for batch, lo, hi in segments:
                lanes = np.nonzero(hit[lo:hi])[0]
                for w_row, rank in lane_cursor(plan, batch, lanes):
                    device_hit(w_row, rank)

        t0 = time.monotonic()
        last_ckpt = [t0]
        cursor = state.cursor
        prefetch = self._make_prefetcher(state)
        chunk: List[tuple] = []
        # The device accumulator is int32: cap the chunk so a worst case of
        # every lane emitting cannot reach 2^31 counts per chunk.
        chunk_cap = max(1, min(
            int(cfg.fetch_chunk),
            ((1 << 31) - 1) // max(1, cfg.lanes * n_devices),
        ))
        chunk_len = 1  # grows adaptively toward chunk_cap
        acc = acc_zero
        last_drain = [time.monotonic()]

        def drain_chunk() -> None:
            nonlocal chunk, acc, chunk_len
            if not chunk:
                return
            ne_delta, nh_delta = (int(x) for x in np.asarray(acc))
            if nh_delta:
                # Rare path: find the hit-bearing launches (scalar probe
                # each) and fetch only their masks.
                for segments_i, out_i, _cur in chunk:
                    if int(out_i["n_hits"]):
                        process_launch_hits(segments_i, out_i)
            end_cursor = chunk[-1][2]
            # Fallback words wholly before the cursor are due now.
            self._flush_fallback_until(
                end_cursor.word, state, fallback_candidate, prefetch
            )
            state.n_emitted += ne_delta
            state.cursor = end_cursor
            chunk = []
            acc = acc_zero
            self._maybe_checkpoint(state, last_ckpt)
            if cfg.progress:
                cfg.progress.update(
                    words_done=end_cursor.word,
                    emitted=state.n_emitted,
                    hits=state.n_hits,
                )
            # Adapt: grow while full chunk cycles run fast (amortize the
            # fetch round trip), shrink when they crawl (keep checkpoint
            # and progress granularity).
            cycle = time.monotonic() - last_drain[0]
            if cycle < 1.0:
                chunk_len = min(chunk_len * 2, chunk_cap)
            elif cycle > 4.0:
                chunk_len = max(1, chunk_len // 2)
            last_drain[0] = time.monotonic()

        superstep_stats: Dict[str, int] = {}
        sstep = self._make_superstep(cursor, n_devices, mesh)
        try:
            if sstep is not None:
                superstep_stats = self._drive_superstep(
                    sstep, state, launch, n_devices, mesh,
                    device_hit, fallback_candidate, prefetch, last_ckpt,
                    process_launch_hits,
                )
            else:
                for item in self._launches(
                    cursor, launch, n_devices=n_devices, mesh=mesh
                ):
                    out = item[1]
                    acc = accum(acc, out["n_emitted"], out["n_hits"])
                    chunk.append(item)
                    if len(chunk) >= chunk_len:
                        drain_chunk()
                drain_chunk()
            # Tail: any fallback words at/after the last device word.
            self._flush_fallback_until(
                self.n_words, state, fallback_candidate, prefetch
            )
        finally:
            if prefetch is not None:
                prefetch.close()
        state.cursor = SweepCursor(word=self.n_words, rank=0)
        state.wall_s += time.monotonic() - t0
        self._maybe_checkpoint(state, last_ckpt, force=True)
        if cfg.progress:
            cfg.progress.final(
                words_done=self.n_words,
                emitted=state.n_emitted,
                hits=state.n_hits,
            )
        return SweepResult(
            n_emitted=state.n_emitted,
            n_hits=state.n_hits,
            hits=recorder.hits,
            words_done=self.n_words,
            resumed=resumed,
            wall_s=state.wall_s,
            routing=dict(self.routing),
            superstep=superstep_stats,
        )

    # ------------------------------------------------------------------
    # Candidates mode (reference-compatible stdout surface)
    # ------------------------------------------------------------------

    def run_candidates(
        self,
        writer: CandidateWriter,
        *,
        resume: bool = True,
    ) -> SweepResult:
        """Stream every candidate to ``writer`` in word order (in-word order
        is variant-rank order; per-word multiset parity with the oracle).

        Resume is at-least-once: candidates written between the last
        checkpoint and a crash are re-emitted on resume (tune the window
        with ``checkpoint_every_s``); crack mode has no such duplication —
        hits are keyed by (word, rank) in the checkpoint itself."""
        spec, cfg, plan = self.spec, self.config, self.plan
        state, resumed = self._load_state(resume)
        if cfg.progress is not None:
            cfg.progress.seed_emitted(state.n_emitted)

        launch, n_devices, mesh = self._make_launch("candidates")

        def fallback_candidate(row: int, i: int, cand: bytes) -> None:
            writer.emit(cand)

        t0 = time.monotonic()
        last_ckpt = [t0]
        cursor = state.cursor
        prefetch = self._make_prefetcher(state)
        try:
            for segments, out, cursor in self._launches(
                cursor, launch, n_devices=n_devices, mesh=mesh
            ):
                cand, clen, _, emit = out
                cand = np.asarray(cand)
                clen = np.asarray(clen).astype(np.int32)
                emit = np.asarray(emit)
                # Segments in cursor order; within each device's lane slice,
                # walk blocks in order — fallback words interleave at their
                # word position. Within a fallback-free run of blocks, the
                # write is one vectorized ragged flatten (newline planted at
                # clen).
                for batch, seg_lo, _seg_hi in segments:
                    nb = len(batch.count)
                    b0 = 0
                    while b0 < nb:
                        w0 = int(batch.word[b0])
                        self._flush_fallback_until(
                            w0, state, fallback_candidate, prefetch
                        )
                        b1 = b0
                        next_fb = (
                            self.fallback_rows[state.fallback_done]
                            if state.fallback_done < len(self.fallback_rows)
                            else self.n_words
                        )
                        while b1 < nb and int(batch.word[b1]) <= next_fb:
                            b1 += 1
                        lo = seg_lo + int(batch.offset[b0])
                        hi = seg_lo + int(
                            batch.offset[b1 - 1] + batch.count[b1 - 1]
                        )
                        n = self._write_lane_range(
                            writer, cand, clen, emit, lo, hi
                        )
                        state.n_emitted += n
                        b0 = b1
                state.cursor = cursor
                self._maybe_checkpoint(
                    state, last_ckpt, before_save=writer.flush
                )
                if cfg.progress:
                    cfg.progress.update(
                        words_done=cursor.word,
                        emitted=state.n_emitted,
                        hits=0,
                    )
            self._flush_fallback_until(
                self.n_words, state, fallback_candidate, prefetch
            )
        finally:
            if prefetch is not None:
                prefetch.close()
        state.cursor = SweepCursor(word=self.n_words, rank=0)
        state.wall_s += time.monotonic() - t0
        self._maybe_checkpoint(state, last_ckpt, force=True,
                               before_save=writer.flush)
        if cfg.progress:
            cfg.progress.final(
                words_done=self.n_words, emitted=state.n_emitted, hits=0
            )
        return SweepResult(
            n_emitted=state.n_emitted,
            n_hits=0,
            hits=[],
            words_done=self.n_words,
            resumed=resumed,
            wall_s=state.wall_s,
            routing=dict(self.routing),
        )

    @staticmethod
    def _write_lane_range(
        writer: CandidateWriter,
        cand: np.ndarray,
        clen: np.ndarray,
        emit: np.ndarray,
        lo: int,
        hi: int,
    ) -> int:
        """Write emitted lanes in [lo, hi) as candidate+\\n lines with one
        vectorized ragged flatten; returns the number of lines written."""
        sel = emit[lo:hi]
        if not sel.any():
            return 0
        rows = cand[lo:hi][sel]
        lens = clen[lo:hi][sel]
        n, w = rows.shape
        if writer.hex_unsafe:
            # Rare path: per-candidate inspection needed; emit row by row.
            for i in range(n):
                writer.emit(bytes(rows[i, : lens[i]]))
            return n
        buf = np.empty((n, w + 1), dtype=np.uint8)
        buf[:, :w] = rows
        buf[np.arange(n), lens] = 0x0A  # newline at each row's length
        mask = np.arange(w + 1)[None, :] <= lens[:, None]
        writer.write_block(buf[mask].tobytes(), n)
        return n

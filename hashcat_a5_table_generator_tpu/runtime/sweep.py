"""The sweep runner: the launch loop driving the fused device steps.

This is the reference's L5 scheduler re-thought for an accelerator
(``main.go:70-99``: one goroutine per word behind a counting semaphore, all
candidates funneled through one channel). Here the unit of work is a
*variant block* — a contiguous rank range of one word's mixed-radix space —
so per-word skew disappears and the whole sweep is a single linear cursor
``(word, rank)`` (SURVEY.md §5): checkpointable, resumable by pure replay,
and splittable across devices.

Two modes, mirroring the two halves of the reference's pipeline:

* **candidates** (:meth:`Sweep.run_candidates`) — the reference-compatible
  surface: every candidate streamed to a sink as raw bytes, per-word
  multiset-identical to the CPU oracle (global order is word order; in-word
  order is rank order, a documented divergence from DFS order — Q9 defines
  parity per word, not globally).
* **crack** (:meth:`Sweep.run_crack`) — what the reference pipes into
  hashcat for (``README.MD:69``): expand + hash + digest-membership fused
  on device; only hits cross back to the host, where the candidate is
  re-derived from its (word, rank) cursor and its digest re-verified with a
  host hash — every reported hit is double-checked by construction.

Words the device plans cannot handle exactly (substitute-all cascade
hazards, ``ops.expand_suball``) are routed through the byte-exact CPU
oracle *in word order*, interleaved at the word's position so candidates
mode preserves global word ordering.

Device launches are double-buffered: launch N+1 is dispatched before launch
N's outputs are fetched, so host block-cutting and device compute overlap
(JAX async dispatch does the rest).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Dict,
    Generator,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from ..models.attack import (
    AttackSpec,
    block_arrays,
    build_plan,
    decode_variant,
    digest_arrays,
    lane_cursor,
    make_candidates_step,
    make_crack_step,
    make_superstep_step,
    piece_arrays,
    plan_arrays,
    scalar_units_arrays,
    superstep_arrays,
    table_arrays,
    unpack_bits,
)
from ..oracle.engines import iter_candidates
from ..ops.blocks import block_cursor, make_blocks, superstep_index
from ..ops.membership import HostDigestLookup, build_digest_set
from ..ops.packing import PackedWords, pack_words
from ..tables.compile import compile_table
from ..utils.digests import HOST_DIGEST
from . import faults, telemetry
from .checkpoint import (
    CheckpointState,
    SweepCursor,
    load_checkpoint,
    save_checkpoint,
    sweep_fingerprint,
)
from .progress import ProgressReporter
from .sinks import CandidateWriter, HitRecord, HitRecorder


#: Process-level jitted-step memo, shared ACROSS Sweep instances: the
#: step factories (make_crack_step & co.) are pure functions of their
#: static config, so two sweeps with identical config can reuse one jit
#: object — and its compiled executables — instead of re-tracing and
#: re-compiling the same program (repeat sweeps in one process, the
#: resident service seam of ROADMAP item 1, and a test suite that
#: otherwise rebuilds the same tiny-geometry programs hundreds of
#: times).  Keys carry every input the traced body depends on: the
#: sweep-level static config, the mesh CONTENT (device ids) for
#: shard_map'd steps — JAX meshes and shardings compare by content, so
#: a step closed over one sweep's Mesh serves another sweep's
#: content-equal mesh; that equality is load-bearing when bumping the
#: pinned jax version — and the kernel-selection env knobs read at
#: trace time.  Bounded in practice: distinct static configs per
#: process are few.
_STEP_CACHE: Dict = {}
_STEP_CACHE_LOCK = threading.Lock()


#: (step key, argument-shape signature) pairs already executed — the
#: streaming chunk worker's warmup dispatch is skipped when the
#: compiled executable demonstrably exists (PERF.md §19).
_WARMED_STEPS: set = set()
#: Env knobs that change the TRACED body without appearing in the
#: sweep-level static config (Pallas kernel selection/interpret mode).
_STEP_ENV_KNOBS = ("A5GEN_PALLAS", "A5GEN_PALLAS_G",
                   "A5GEN_PALLAS_INTERPRET")


def step_cache_stats() -> Dict[str, int]:
    """Snapshot of the process-level compiled-step cache counters: a
    miss is a program BUILD (trace + XLA compile on first dispatch), a
    hit is a job riding an already-built program — the compile-
    amortization number the resident engine's stats and ``bench.py
    --serve-ab`` report (PERF.md §20).  A derived view of the
    ``step_cache.*`` telemetry counters (PERF.md §21)."""
    return {
        k: int(telemetry.counter(f"step_cache.{k}").value)
        for k in ("hits", "misses")
    }


def _step_env_key() -> tuple:
    from .env import env_str

    return tuple(env_str(k) for k in _STEP_ENV_KNOBS)


def _exhaust(machine: "Iterator") -> "SweepResult":
    """Run a sweep machine to completion and return its result — the
    solo (non-interleaved) drive ``run_crack``/``run_candidates`` wrap
    around the machine protocol (PERF.md §20)."""
    while True:
        try:
            next(machine)
        except StopIteration as done:
            return done.value


def _stats_delta(before: Dict[str, int], after: Dict[str, int]
                 ) -> Dict[str, int]:
    """Nonzero counter deltas between two stats snapshots (the run's
    share of the process-wide schema-cache activity)."""
    return {
        k: after[k] - before.get(k, 0)
        for k in after
        if after[k] - before.get(k, 0)
    }


def _tree_shape_sig(tree) -> tuple:
    """Hashable (shape, dtype) signature of a pytree's arrays — with a
    step's cache key, it identifies one compiled executable (jit
    specializes per argument shapes), so the chunk worker can tell
    whether a warmup dispatch would actually compile anything."""
    import jax

    return tuple(
        (tuple(x.shape), str(getattr(x, "dtype", type(x))))
        for x in jax.tree_util.tree_leaves(tree)
    )


def _pieces_static(pieces) -> "Optional[tuple]":
    """The hashable STATIC trace structure of a ``packing.PieceSchema``
    — everything the kernel builders bake into the traced program (the
    data tables ride the plan dict as inputs).  Step-cache key material:
    two chunk plans with equal static structure share one compiled
    step."""
    if pieces is None:
        return None
    return (
        pieces.kind, pieces.groups, pieces.closed, pieces.n_cols,
        pieces.max_out, pieces.gw is None, pieces.gw16 is None,
        pieces.gl is None, pieces.sel_bit is None,
        pieces.sel_slot is None,
        # Pair-lane gate fields (PERF.md §24) are trace structure: the
        # pair kernel bakes the patched group index and the static
        # delta bounds into the program.
        pieces.pair_ok, pieces.pair_g0, pieces.pair_dmin,
        pieces.pair_dmax,
    )


@dataclass
class SweepConfig:
    """Launch geometry + runtime knobs (none of these affect WHAT is
    emitted — the checkpoint fingerprint deliberately excludes them, so a
    checkpoint taken at one geometry/device count resumes at any other)."""

    lanes: Optional[int] = 1 << 17  # variant lanes per device per launch.
    #   None = resolve at launch (PERF.md §29): the sweep fills lanes —
    #   and every other None geometry knob — from the device kind's
    #   autotune profile (runtime/tune.py, explicit flag > profile >
    #   built-in defaults); the CLI/bench pass None when the user gave
    #   no flag.  An explicit lanes value (every test and library
    #   construction) pins the whole config: no profile is consulted.
    num_blocks: Optional[int] = 1024  # static per-device block count (jit
    #   stability). None = auto: resolved by the Sweep once plan/table
    #   eligibility is known — lanes/512 (lanes/256 for suball) when the
    #   fused kernel will take the launch, else lanes/128: the measured
    #   per-arm best geometries (PERF.md §9b).
    max_in_flight: int = 2  # double-buffered launches
    fetch_chunk: int = 16  # crack mode: max launches whose counts accumulate
    #   ON DEVICE between host fetches. A device->host fetch costs a full
    #   round trip (~65 ms over the remote-device tunnel — several times a
    #   launch's device time; PERF.md §4), so the crack loop chains per-
    #   launch (n_emitted, n_hits) into a device accumulator and fetches
    #   once per chunk; per-launch hit masks are fetched only for chunks
    #   whose hit count is nonzero (hits are rare). The chunk fetch is a
    #   completion barrier over its whole chain, so in-flight device work
    #   stays bounded at fetch_chunk + max_in_flight launches. Chunks grow
    #   adaptively 1 -> fetch_chunk while drains stay under ~1 s, so small
    #   sweeps and fast backends keep per-launch checkpoint granularity.
    devices: Optional[int] = 1  # 1 = single-device; N = shard over first N
    #                             local devices; None = all local devices
    superstep: "Optional[int]" = None  # crack mode: launches fused into ONE
    #   device dispatch via the device-resident superstep executor (a
    #   lax.scan cuts each step's blocks ON DEVICE from per-sweep index
    #   arrays — no per-launch host cutting, dispatch, or block-field
    #   transfer; PERF.md §15). None = auto: engage when the plan/geometry
    #   qualify (fixed-stride layout, int32-safe block index), with
    #   fetch_chunk steps per superstep. 0 = off (the per-launch pipeline).
    #   N >= 1 pins the steps-per-superstep (capped so a superstep's int32
    #   emitted-count accumulator cannot overflow). The streams are
    #   identical either way; A5GEN_SUPERSTEP=off is the env escape hatch.
    pipeline: Optional[bool] = None  # crack mode: double-buffered superstep
    #   drive (PERF.md §18). The driver keeps TWO alternating device hit/
    #   counter buffer sets and dispatches superstep N+1 into set B before
    #   fetching set A's counters, so the once-per-superstep fetch overlaps
    #   the next superstep's compute instead of barriering the chain (the
    #   honest-sync rule moves: the fetch of set A is the completion
    #   barrier for superstep N ONLY). Replay and checkpoints land at the
    #   fetched (lagged) superstep boundary; shutdown drains the in-flight
    #   superstep. None = auto: on whenever the superstep executor engages
    #   and max_in_flight >= 2. False = barriered drive (fetch right after
    #   dispatch — the A/B arm). A5GEN_PIPELINE=off is the env escape
    #   hatch; the streams are identical either way.
    pair: "Optional[int | str]" = None  # pair-lane tier (PERF.md §24):
    #   K=2 candidates per hash lane where the substitution geometry
    #   allows — the superstep executor's blocks then cover 2x the
    #   candidate ranks per lane span, halving per-candidate message-
    #   build cost (the schema-compile pair gate decides eligibility;
    #   ineligible schemas keep K=1 exactly as before). None / 'auto' =
    #   engage when eligible; 0 / 'off' = never. The candidate/hit
    #   streams, checkpoints and fingerprints are identical either way;
    #   A5GEN_PAIR=off is the env escape hatch (one release).
    superstep_hit_cap: int = 4096  # capped device (word, rank) hit buffer
    #   carried through the superstep scan, PER DEVICE. A superstep whose
    #   device-local hits exceed the cap is replayed exactly through the
    #   per-launch path (hits are rare; replay is the graceful-degradation
    #   guarantee — never a dropped hit).
    packed_blocks: Optional[bool] = None  # True = variable-offset (tightly
    #   packed) block layout; False = fixed-stride blocks (stride = lanes //
    #   num_blocks) — the kernels map lane -> block arithmetically instead
    #   of binary-searching per lane (PERF.md). None = auto: fixed-stride
    #   whenever num_blocks divides lanes evenly (it wins on every backend
    #   since the f32 decode + vectorized cutter landed — PERF.md §4c),
    #   packed otherwise. The layouts are stream-identical; only throughput
    #   differs.
    stream_chunk_words: "Optional[int | str]" = None  # streaming plan
    #   pipeline (PERF.md §19): compile the dictionary's plan + piece
    #   schema in word CHUNKS on a host worker thread while the device
    #   sweeps the previous chunk, with consumed chunks freed — resident
    #   plan memory is O(ring x chunk) regardless of dictionary length,
    #   and time-to-first-candidate is one chunk's schema compile plus a
    #   cheap whole-dictionary prescan (the light vectorized fraction of
    #   the plan build; the dominant schema/table compile streams). None /
    #   'auto' = engage when the dictionary spans more than one
    #   auto-sized (~64 MB of compiled plan) chunk; 0 / 'off' = always
    #   materialize whole; N = chunk at N words (engages when the
    #   dictionary exceeds N). The candidate/hit streams, checkpoints
    #   and fingerprints are identical either way (a streaming
    #   checkpoint resumes under the whole-dictionary path and vice
    #   versa); A5GEN_STREAM=off is the env escape hatch.
    schema_cache: Optional[str] = None  # on-disk PieceSchema cache dir
    #   (default: A5GEN_SCHEMA_CACHE): repeat sweeps of the same
    #   wordlist x table skip schema compilation — the service mode's
    #   compile-once seam (ROADMAP item 1).
    schema_cache_max_mb: Optional[float] = None  # LRU size cap on the
    #   on-disk schema cache (default: A5GEN_SCHEMA_CACHE_MAX_MB; None =
    #   unbounded): after each write the cache evicts oldest-atime
    #   entries until it fits — long-lived engine processes must not
    #   grow the cache without bound (PERF.md §20).
    checkpoint_path: Optional[str] = None
    checkpoint_every_s: float = 30.0
    progress: Optional[ProgressReporter] = None
    retry_attempts: int = 2  # fault supervision (PERF.md §23): max
    #   CONSECUTIVE transient-device-error recoveries per drive before the
    #   error propagates.  A recovery drops the in-flight dispatches and
    #   re-dispatches from the last FETCHED boundary (the lagged-checkpoint
    #   discipline makes that exact); the counter resets on every
    #   successful fetch, so a long sweep survives many isolated flakes
    #   while a persistent failure still surfaces after retry_attempts.
    #   0 = no supervision (every device error propagates immediately).
    retry_backoff_s: float = 0.05  # base of the exponential backoff
    #   between recovery attempts (base * 2^attempt seconds; the wall
    #   spent lands in the faults.backoff_s telemetry counter).
    fetch_timeout_s: Optional[float] = None  # watchdog on each consumed
    #   counters fetch: when set, the drive polls the device result's
    #   readiness and raises a typed FetchTimeout — which the supervisor
    #   treats as transient — instead of blocking forever on a wedged
    #   device/tunnel.  Off by default (CPU sweeps and giant cold
    #   compiles legitimately stall longer than any sane timeout).
    faults: "Optional[object]" = None  # fault-injection arming (PERF.md
    #   §23): a runtime/faults.py spec string or FaultPlan, installed
    #   process-wide at Sweep construction.  None = A5GEN_FAULTS decides
    #   (unset = nothing armed, the production no-op).
    pod: "Optional[Tuple[int, int]]" = None  # pod-sharded giant-job mode
    #   (PERF.md §29): ``(process_index, process_count)`` splits ONE
    #   keyspace job across a pod via per-device block-cursor stripes —
    #   with P processes of D devices each, global device ``p*D + d``
    #   owns blocks ``b0 + (p*D + d) * num_blocks`` of every superstep
    #   and all stripes advance ``steps * num_blocks * P * D`` per
    #   dispatch, so the union of the shards' streams is exactly the
    #   single-device stream.  Every process sweeps the SAME wordlist
    #   (unlike the per-host word stripes of run_crack_multihost); the
    #   cursor stays the global linear (word, rank) cursor, so shard
    #   checkpoints and single-device checkpoints are interchangeable.
    #   Requires the superstep executor (the striping seam); the
    #   per-launch fallback path would silently duplicate work, so an
    #   ineligible plan raises instead.  None = no pod striping.
    geometry_source: str = "explicit"  # provenance of the launch
    #   geometry (PERF.md §29): "explicit" (caller-pinned values),
    #   "profile" (filled from the device kind's autotune profile), or
    #   "default" (built-in defaults).  Stamped by the launch-time
    #   resolution seam; metadata only — never trace-key or
    #   fingerprint material.

    def resolve_block_stride(self) -> Optional[int]:
        """Lanes-per-block of the fixed-stride layout; None = packed.

        An EXPLICIT stride request (``packed_blocks=False``) with a
        non-divisible geometry raises instead of silently degrading to
        packed; auto mode quietly falls back (the layouts are
        stream-identical, only throughput differs)."""
        if self.lanes is None:
            raise ValueError(
                "lanes=None (autotune profile / built-in defaults) is "
                "resolved by the Sweep at launch; resolve_block_stride "
                "needs a concrete lane count"
            )
        if self.num_blocks is None:
            raise ValueError(
                "num_blocks=None (auto) is resolved by the Sweep once plan "
                "eligibility is known; resolve_block_stride needs a "
                "concrete block count"
            )
        packed = self.packed_blocks
        if packed is None:
            packed = self.lanes % self.num_blocks != 0
        if packed:
            return None
        if self.lanes % self.num_blocks:
            raise ValueError(
                f"fixed-stride layout needs lanes ({self.lanes}) divisible "
                f"by blocks ({self.num_blocks}); adjust the geometry or use "
                "the packed layout"
            )
        return self.lanes // self.num_blocks


@dataclass
class SweepResult:
    n_emitted: int = 0
    n_hits: int = 0
    hits: List[HitRecord] = field(default_factory=list)
    words_done: int = 0
    resumed: bool = False
    wall_s: float = 0.0
    #: word routing counts: device_clean / device_closed / oracle_fallback
    routing: Dict[str, int] = field(default_factory=dict)
    #: superstep executor stats (empty when the per-launch path ran):
    #: supersteps / launches (steps executed inside them) / replays
    #: (overflow supersteps re-run per-launch) / launches_per_fetch
    superstep: Dict[str, int] = field(default_factory=dict)
    #: streaming-ingestion stats (empty when the whole-dictionary path
    #: ran, PERF.md §19): chunks / chunks_swept / chunk_words /
    #: compile_wall_s / compile_overlap_s / overlap_ratio / ttfc_s
    #: (time to the first device results fetch) /
    #: peak_resident_plan_bytes / chunk_bytes_max / ring
    stream: Dict[str, float] = field(default_factory=dict)
    #: on-disk PieceSchema cache activity over this run's window
    #: (hits / misses / bytes_read / bytes_written / evictions deltas of
    #: the PROCESS-wide ``ops.packing`` counters; empty when no cache
    #: dir is configured or nothing was looked up — PERF.md §20).
    #: Solo runs own their window; under a multiplexing engine,
    #: interleaved jobs' activity lands in whichever open window
    #: observes it — per-job attribution is ``Engine.stats()``'s
    #: process totals, not this field.
    schema_cache: Dict[str, int] = field(default_factory=dict)
    #: resolved launch geometry provenance (PERF.md §29): the concrete
    #: values this run actually launched with (lanes / num_blocks /
    #: superstep / pair / device_kind) — no throughput number is ever
    #: ambiguous about its geometry again.  Empty when no launch ran
    #: (zero-word sweeps).
    geometry: Dict[str, Any] = field(default_factory=dict)
    #: where that geometry came from: "explicit" (caller-pinned),
    #: "profile" (autotune profile), or "default" (built-ins).
    geometry_source: str = "explicit"


class _FallbackPrefetcher:
    """Oracle-fallback expansion on a worker thread (VERDICT r3 #5).

    The launch loop spends most of its wall-clock blocked on device fetches
    — which release the GIL — so a single producer thread expands the
    oracle-routed hazard words CONCURRENTLY with device execution instead
    of serially between launches. A bounded queue gives backpressure
    (bounded memory even for huge fallback expansions); candidates still
    reach the sink in word order because the consumer drains row by row.
    """

    _END = object()

    def __init__(self, sweep: "Sweep", start_index: int,
                 maxsize: int = 8192) -> None:
        import queue
        import threading

        self._queue: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._sweep = sweep
        self._start = start_index
        self._stop = False
        self._thread = threading.Thread(
            target=self._produce, name="a5-fallback-oracle", daemon=True
        )
        self._thread.start()

    def _produce(self) -> None:
        rows = self._sweep.fallback_rows
        try:
            for idx in range(self._start, len(rows)):
                for i, cand in enumerate(
                    self._sweep._oracle_candidates(rows[idx])
                ):
                    if self._stop:
                        return
                    self._queue.put((i, cand))
                self._queue.put(self._END)
        except BaseException as e:  # noqa: BLE001 — re-raised in iter_row
            # A dying producer must not strand the consumer on a queue.get
            # that no sentinel will ever satisfy: ship the exception across
            # the queue so the sweep aborts with the real error, exactly as
            # the old inline oracle path did.
            self._queue.put(e)

    def iter_row(self):
        """Yield this row's (dfs_index, candidate) pairs; stops at the row's
        end marker. Must be called once per fallback row, in row order.
        Re-raises any exception the producer hit."""
        while True:
            item = self._queue.get()
            if item is self._END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def close(self) -> None:
        """Stop the producer; safe to call with the queue in any state."""
        self._stop = True
        # Unblock a producer stuck on a full queue, then wait briefly.
        for _ in range(100):
            if not self._thread.is_alive():
                return
            try:
                while True:
                    self._queue.get_nowait()
            except Exception:
                pass
            self._thread.join(timeout=0.05)


class Sweep:
    """One wordlist × one merged table × one attack spec."""

    def __init__(
        self,
        spec: AttackSpec,
        sub_map: Dict[bytes, List[bytes]],
        words: "Sequence[bytes] | PackedWords",
        digests: Sequence[bytes] = (),
        config: Optional[SweepConfig] = None,
    ) -> None:
        self.spec = spec
        self.sub_map = sub_map
        # A [N, digest_bytes] uint8 matrix (the CLI's vectorized left-list
        # parser) stays a matrix — hashmob-scale lists must not explode
        # into tens of millions of Python bytes objects.
        self.digests = (
            digests if isinstance(digests, np.ndarray) else list(digests)
        )
        # One sort serves both the fingerprint's canonical blob and
        # per-hit host membership (matrix/list duality lives in the
        # lookup, ops.membership.HostDigestLookup).
        self._digest_lookup = HostDigestLookup(self.digests)
        self.config = config or SweepConfig()
        # Fault arming (PERF.md §23): an explicit SweepConfig.faults plan
        # wins; otherwise A5GEN_FAULTS decides (unset = nothing armed).
        if self.config.faults is not None:
            faults.install(self.config.faults)
        else:
            faults.ensure_env()
        self.ct = compile_table(sub_map)
        # A pre-packed batch (e.g. the native scanner's read_packed) is
        # accepted directly — the rockyou-scale path never materializes a
        # Python list of words.
        self.packed = (
            words if isinstance(words, PackedWords) else pack_words(list(words))
        )
        self.n_words = self.packed.batch
        #: jitted step programs + shared device arrays, keyed by static
        #: trace config — streaming chunks with identical schema
        #: structure share one compiled program (PERF.md §19).
        #: per-sweep device residents (table/digest arrays); compiled
        #: step programs live in the process-level _STEP_CACHE.
        self._step_cache: Dict = {}
        self._mesh = None
        #: device kind of the live backend, resolved at first launch
        #: (geometry-provenance material, PERF.md §29).
        self._device_kind: Optional[str] = None
        self._ttfc: List[Optional[float]] = [None]
        self._run_t0 = 0.0
        #: the live machine's CheckpointState (PERF.md §20): set when a
        #: crack/candidates machine starts, read by the resident engine
        #: for pause (deep-copied into the job's checkpoint) and stats.
        self.active_state: Optional[CheckpointState] = None
        #: cross-job packed dispatch source (PERF.md §22): a
        #: ``runtime.fuse.FusedGroup`` the resident engine binds before
        #: the machine's first tick; the crack drive then CONSUMES its
        #: per-job split results instead of dispatching its own
        #: supersteps (:meth:`_drive_packed`).  None = solo dispatch.
        self._packed_source = None
        #: per-sweep superstep span timeline (PERF.md §21): one record
        #: per consumed fetch boundary; the engine's ``done``/``paused``
        #: events and ``--metrics-json`` report its summary.
        self.timeline = telemetry.SpanTimeline()
        self._stream_lock = threading.Lock()
        self._stream_resident = 0
        self._stream_peak = 0
        self._stream_chunk_max = 0
        #: streaming-ingestion decision (PERF.md §19): chunk bounds +
        #: the batch-level plan facts, or None = whole-dictionary plan.
        self._stream = self._resolve_streaming()
        if self._stream is None:
            self.plan = build_plan(spec, self.ct, self.packed)
            closed_arr = getattr(self.plan, "closed", None)
            n_closed = int(closed_arr.sum()) if closed_arr is not None else 0
            windowed = bool(getattr(self.plan, "windowed", False))
            #: fallback word rows in word order (oracle-routed,
            #: SURVEY.md §2.4)
            self.fallback_rows: List[int] = [
                int(i) for i in np.nonzero(self.plan.fallback)[0]
            ]
        else:
            # Streaming: plans are chunk-local; the batch-level facts
            # the fingerprint, routing, and every chunk plan must agree
            # on come from one cheap prescan (O(chunk) resident).
            self.plan = None
            self._stream.update(self._stream_prescan())
            n_closed = self._stream["n_closed"]
            windowed = self._stream["windowed"]
            self.fallback_rows = self._stream["fallback_rows"]
        # Windowed plans renumber every (word, rank) cursor, so a checkpoint
        # from one enumeration scheme must never resume under the other —
        # the scheme is part of the fingerprint's mode token. (Scheme choice
        # is deterministic in the fingerprinted inputs — the streaming
        # prescan reproduces the whole-batch decision exactly, so
        # streaming and whole-dictionary runs fingerprint identically;
        # the token guards against cross-version resumes.) Cascade
        # closure likewise changes WHICH words the device cursor covers
        # (closed words leave the fallback set), so it gets its own token.
        mode_token = spec.mode + (
            "+windowed" if windowed else ""
        ) + ("+closed" if n_closed else "")
        self.fingerprint = sweep_fingerprint(
            mode_token,
            spec.algo,
            spec.min_substitute,
            spec.max_substitute,
            sub_map,
            self.packed,  # buffer-level hash, no per-word Python loop
            self.digests,
            digest_lookup=self._digest_lookup,  # reuse its one sort
        )
        self._host_digest = HOST_DIGEST[spec.algo]
        #: three-way word routing (PERF.md §5/§14): clean device words,
        #: cascade-closed device words, oracle-routed pathological words.
        self.routing: Dict[str, int] = {
            "device_clean": self.n_words - n_closed - len(self.fallback_rows),
            "device_closed": n_closed,
            "oracle_fallback": len(self.fallback_rows),
        }
        set_routing = getattr(self.config.progress, "set_routing", None)
        if set_routing is not None:
            set_routing(self.routing)
        # Pod-sharded giant-job mode (PERF.md §29): validate the shard
        # coordinates, and route the host-side oracle-fallback words to
        # shard 0 ONLY — fallback expansion is whole-word host work that
        # must not be duplicated P times.  The routing counts above stay
        # global (every shard reports the same totals); shard p>0 simply
        # has nothing to flush, and its checkpoint's fallback_done=0
        # means a single-device resume of that checkpoint emits the
        # fallback words itself — no lost work across the interchange.
        if self.config.pod is not None:
            pidx, pcnt = (int(x) for x in self.config.pod)
            if pcnt < 1 or not 0 <= pidx < pcnt:
                raise ValueError(
                    f"SweepConfig.pod must be (index, count) with "
                    f"0 <= index < count, got {self.config.pod!r}"
                )
            from dataclasses import replace as _replace

            self.config = _replace(self.config, pod=(pidx, pcnt))
            if pidx != 0:
                self.fallback_rows = []

    # ------------------------------------------------------------------
    # Streaming ingestion (PERF.md §19)
    # ------------------------------------------------------------------

    def _resolve_streaming(self) -> "Optional[dict]":
        """The streaming-ingestion decision: chunk word count + bounds,
        or None for whole-dictionary plan materialization.

        ``SweepConfig.stream_chunk_words``: None/'auto' = engage when
        the dictionary spans more than one auto-sized (~64 MB of
        compiled plan) chunk; 0/'off' = never; N = chunk at N words.
        ``A5GEN_STREAM=off`` is the one-release escape hatch.  A
        dictionary that fits one chunk keeps the whole path — it IS the
        chunk, and the whole path skips the ring machinery."""
        from ..ops.packing import auto_chunk_words, chunk_bounds
        from .env import stream_enabled

        requested = self.config.stream_chunk_words
        if requested in (0, "off") or not stream_enabled():
            return None
        if requested in (None, "auto"):
            cw = auto_chunk_words(self.packed.width)
        else:
            cw = int(requested)
            if cw < 1:
                raise ValueError(
                    "SweepConfig.stream_chunk_words must be >= 1, "
                    f"'auto', or 'off'; got {requested!r}"
                )
        if self.n_words <= cw:
            return None
        return {
            "chunk_words": cw,
            "bounds": chunk_bounds(self.n_words, cw),
            # Exactly ONE chunk compiles/waits ahead of the chunk being
            # swept (the ring contract graftaudit pins; deeper prefetch
            # would trade memory for nothing — the worker is one thread).
            "prefetch": 1,
        }

    def _stream_prescan(self) -> dict:
        """One cheap vectorized pass over the dictionary, chunk by chunk
        (plans built and DISCARDED — O(chunk) resident), computing the
        batch-level facts every chunk plan must agree on:

        * ``out_width`` — the global candidate-buffer width (a chunk
          sizing it locally would change kernel shapes mid-sweep);
        * ``windowed`` — the count-windowed enumeration decision.  Its
          2x-lane-saving gate sums over the WHOLE batch
          (``expand_matches.windowed_plan_fields``), so the streaming
          sweep reproduces the whole-dictionary decision here and
          FORCES every chunk plan the same way — rank numbering must be
          chunk-invariant or checkpoints/hits would renumber;
        * ``fallback_rows`` / ``n_closed`` — global oracle routing, so
          fallback interleave, the prefetcher, the fingerprint's mode
          token, and the routing stats are identical to the whole path.

        This pass IS O(dictionary) host work — global decisions cannot
        be cheaper — but only the light fraction of the compile: the
        vectorized match scan and the windowed DP, never the PieceSchema
        variant tables, placement windows, or device arrays, which are
        the dominant cost and stream per chunk behind the device sweep
        (measured split in PERF.md §19b).  The chunk plans built here
        are rebuilt by the ring's worker — the price of O(chunk)
        residency."""
        from ..ops.expand_matches import (
            variant_totals,
            windowed_chunk_terms,
            windowed_gate,
        )
        from ..ops.packing import slice_packed

        spec = self.spec
        emin, emax = spec.effective_min, spec.max_substitute
        win_ok = True
        sum_win = sum_full = 0
        out_width = 4
        fallback_rows: List[int] = []
        n_closed = 0
        for lo, hi in self._stream["bounds"]:
            # force_windowed=False: the prescan reads only out_width /
            # fallback / closed / the (neutralized) radix matrix — all
            # computed before the windowed step — so building the
            # chunk's win_v DP here would run the dominant prescan term
            # twice (windowed_chunk_terms below is the one that counts).
            plan = build_plan(
                spec, self.ct, slice_packed(self.packed, lo, hi),
                force_windowed=False,
            )
            out_width = max(out_width, plan.out_width)
            fb = np.asarray(plan.fallback, bool)
            fallback_rows.extend(
                lo + int(i) for i in np.nonzero(fb)[0]
            )
            closed_arr = getattr(plan, "closed", None)
            if closed_arr is not None:
                n_closed += int(np.asarray(closed_arr).sum())
            if win_ok:
                # The gate's terms come from the SAME implementation the
                # whole-batch decision uses (windowed_chunk_terms):
                # per-word eligibility conjoins, the sums accumulate,
                # and the final vote is the shared windowed_gate.  The
                # plan's radix matrix and full totals arrive fallback-
                # neutralized exactly as the builders pass them.
                radix = np.asarray(plan.pat_radix)
                full = variant_totals(radix)
                n_var = [0 if fb[i] else t for i, t in enumerate(full)]
                ok, _v, _totals, sw, sf = windowed_chunk_terms(
                    radix, n_var, emin, emax, zero_mask=fb,
                )
                if not ok:
                    win_ok = False
                else:
                    sum_win += sw
                    sum_full += sf
        windowed = bool(win_ok and windowed_gate(sum_win, sum_full))
        return {
            "out_width": out_width,
            "windowed": windowed,
            "fallback_rows": fallback_rows,
            "n_closed": n_closed,
        }

    def _auto_num_blocks(self, kind: str, plan) -> int:
        """Resolve ``num_blocks=None``: the measured per-arm best geometry
        (PERF.md §9b/§11) — when the fused Pallas kernel will take the
        launch, the K=1 scalar-units path peaks at stride 128 (best
        fill; §11 removed most of the per-block cost), the general
        kernel at stride 512 (256 for suball: its Π(options+1) variant
        space fills larger strides poorly); the XLA path peaks at
        stride 128.  Candidates mode never engages the fused kernel
        (``make_candidates_step`` has no fused path), so it always gets
        the XLA-best stride.  Streaming sweeps resolve on the FIRST
        chunk's plan and keep the geometry for the whole sweep (jit
        shape stability across chunks)."""
        from ..ops.pallas_expand import opts_for, scalar_units_for

        lanes = self.config.lanes
        if kind == "crack":
            if scalar_units_for(plan):
                pref = 128
            else:
                pref = 256 if self.spec.mode.startswith("suball") else 512
            if lanes % pref == 0:
                nb = lanes // pref
                if opts_for(self.spec, plan, self.ct,
                            block_stride=pref, num_blocks=nb) is not None:
                    return nb
        if lanes % 128 == 0:
            return lanes // 128
        return 1024

    def _digest_contains(self, dig: bytes) -> bool:
        """Host-side membership in the target digest list (fallback-word
        hits and device-hit re-verification)."""
        return dig in self._digest_lookup

    # ------------------------------------------------------------------
    # Shared machinery
    # ------------------------------------------------------------------

    def _oracle_candidates(self, row: int) -> Iterator[bytes]:
        word = self.packed.word(row)
        substitute_all = self.spec.mode.startswith("suball")
        reverse = self.spec.mode in ("reverse", "suball-reverse")
        # Hazard fallback words were the sweep's Amdahl bottleneck
        # (PERF.md §5: Python generators at ~1e5 cand/s against a device
        # at 1e8); the native engines stream the identical candidates
        # ~17x faster when eligible.
        eng = self._native_oracle(substitute_all=substitute_all,
                                  reverse=reverse)
        if eng is not None:
            return eng.iter_word(
                word, self.spec.min_substitute, self.spec.max_substitute,
                substitute_all=substitute_all, reverse=reverse,
            )
        return iter_candidates(
            word,
            self.sub_map,
            self.spec.min_substitute,
            self.spec.max_substitute,
            substitute_all=substitute_all,
            reverse=reverse,
        )

    def _native_oracle(self, *, substitute_all: bool, reverse: bool):
        """A cached NativeDefaultOracle for the fallback path, or None
        (ineligible / no toolchain — Python engines remain)."""
        cached = getattr(self, "_native_oracle_cache", ())
        if cached != ():
            return cached
        eng = None
        try:
            from ..native.oracle_engine import (
                NativeDefaultOracle,
                available,
                default_engine_eligible,
            )

            if default_engine_eligible(
                self.sub_map,
                substitute_all=substitute_all,
                reverse=reverse,
                crack=False,
                hex_unsafe=False,
                max_substitute=self.spec.max_substitute,
            ) and available():
                eng = NativeDefaultOracle(self.sub_map)
        except Exception:  # pragma: no cover - toolchain-dependent
            eng = None
        self._native_oracle_cache = eng
        return eng

    def _load_state(
        self, resume: bool, state: "Optional[CheckpointState]" = None
    ) -> Tuple[CheckpointState, bool]:
        """Resolve the run's starting state: an injected in-memory
        ``state`` (the resident engine's pause→migrate handoff — a
        paused job IS its CheckpointState, PERF.md §20) wins over the
        on-disk checkpoint; both validate the sweep fingerprint."""
        cfg = self.config
        if state is not None:
            if state.fingerprint != self.fingerprint:
                raise ValueError(
                    "checkpoint state was written by a different sweep "
                    "(mode/window/table/wordlist/digests changed); it "
                    "cannot resume this one"
                )
            import copy

            # The caller's token stays pristine (it may be re-submitted
            # to another engine if this resume dies); the machine
            # mutates only its own copy.
            return copy.deepcopy(state), True
        if resume and cfg.checkpoint_path:
            state = load_checkpoint(cfg.checkpoint_path, self.fingerprint)
            if state is not None:
                return state, True
        return CheckpointState(fingerprint=self.fingerprint), False

    def _resolve_devices(self) -> int:
        """Device count for this run: config.devices, or all local devices
        when None (the mesh constructor validates availability)."""
        n = self.config.devices
        if n is None:
            import jax

            n = len(jax.devices())
        n = int(n)
        if n < 1:
            raise ValueError(f"SweepConfig.devices must be >= 1, got {n}")
        return n

    def _geometry_provenance(self) -> "Dict[str, Any]":
        """Resolved-geometry stamp for SweepResult/progress/bench records
        (PERF.md §29): with ``geometry_source`` it makes every reported
        number unambiguous about which geometry produced it.  Metadata
        only — never trace-key or fingerprint material."""
        cfg = self.config
        try:
            stride = cfg.resolve_block_stride()
        except ValueError:
            stride = None  # pre-resolution (lanes/num_blocks still None)
        return {
            "lanes": cfg.lanes,
            "num_blocks": cfg.num_blocks,
            "block_stride": stride,
            "superstep": cfg.superstep,
            "pair": cfg.pair,
            "device_kind": self._device_kind,
            "pod": list(cfg.pod) if cfg.pod is not None else None,
        }

    def _get_step(self, key: tuple, build: Callable):
        """Shared compiled-program cache: jitted steps keyed by their
        static trace config, so streaming chunks — and repeat sweeps in
        the same process — with identical config reuse ONE jit object
        (and its compiled executables) instead of re-tracing
        (PERF.md §19; the process-level ``_STEP_CACHE``).  The env-knob
        suffix keeps sweeps under different Pallas selection/interpret
        settings on separate programs."""
        key = key + (_step_env_key(),)
        with _STEP_CACHE_LOCK:
            step = _STEP_CACHE.get(key)
        telemetry.counter(
            "step_cache.hits" if step is not None else "step_cache.misses"
        ).add(1)
        if step is None:
            step = build()
            with _STEP_CACHE_LOCK:
                # A benign race: concurrent builders produce equivalent
                # pure programs; first write wins.
                step = _STEP_CACHE.setdefault(key, step)
        return step

    def _get_mesh(self, n_devices: int):
        """One mesh per sweep: streaming chunks must replicate onto the
        SAME mesh or shardings drift between chunks."""
        if self._mesh is None:
            from ..parallel.mesh import make_mesh

            self._mesh = make_mesh(n_devices)
        return self._mesh

    def _schema_cache_dir(self) -> "Optional[str]":
        from .env import schema_cache_dir

        return self.config.schema_cache or schema_cache_dir()

    def _schema_cache_max_mb(self) -> "Optional[float]":
        from .env import schema_cache_max_mb

        if self.config.schema_cache_max_mb is not None:
            return self.config.schema_cache_max_mb
        return schema_cache_max_mb()

    def _shared_device_arrays(self, kind: str, mesh) -> tuple:
        """Chunk-independent device residents, built once per sweep:
        the compiled table's value arrays and (crack) the digest set —
        streaming must NOT re-transfer these per chunk."""
        key = ("shared-arrays", kind, mesh is not None)
        got = self._step_cache.get(key)
        if got is None:
            t = table_arrays(self.ct)
            darrs = (
                digest_arrays(build_digest_set(self.digests, self.spec.algo))
                if kind == "crack" else None
            )
            if mesh is not None:
                from ..parallel.mesh import replicate

                t = replicate(mesh, t)
                if darrs is not None:
                    darrs = replicate(mesh, darrs)
            got = (t, darrs)
            self._step_cache[key] = got
        return got

    def _make_launch(self, kind: str, plan):
        """Build a launch callable over one compiled plan — the whole
        dictionary, or one streaming chunk.  ``kind`` is 'crack' or
        'candidates'.  Single-device builds the plain jitted step; multi-
        device builds the shard_map'd step over a 1-D mesh with plan/table
        (and digests, for crack) replicated.  Returns
        ``(launch(blocks) -> out, n_devices, mesh, step_ctx)`` — the
        step-build context the superstep executor (and the streaming
        chunk driver) reuses: same device-resident arrays, same kernel
        selection, so the paths trace the identical fused body."""
        # The accelerator-init seam (PERF.md §23): the class of flake
        # that ate bench rounds r01-r05.  Recovery is the layer above —
        # the CLI's --retries rebuild-and-resume, the bench
        # orchestrator's init-retry budget, the engine's job restart.
        if faults.ACTIVE is not None:
            faults.ACTIVE.fire("device.init")
        from dataclasses import replace

        if self._device_kind is None:
            import jax

            self._device_kind = str(jax.devices()[0].device_kind)
        if self.config.lanes is None:
            # The geometry-resolution seam (PERF.md §29): explicit flag
            # > autotune profile > built-in defaults.  Runs here — not
            # in __init__ — because the profile is keyed by device
            # kind, and nothing before the first launch touches jax.
            from .tune import resolve_config

            resolved, source = resolve_config(
                self.config, self._device_kind
            )
            self.config = replace(resolved, geometry_source=source)
        if self.config.num_blocks is None:
            self.config = replace(
                self.config, num_blocks=self._auto_num_blocks(kind, plan)
            )
        if self.config.progress is not None:
            # Provenance into the progress JSON stream (guarded like the
            # set_routing call site for pre-geometry custom reporters).
            set_geometry = getattr(
                self.config.progress, "set_geometry", None
            )
            if set_geometry is not None:
                set_geometry(
                    self._geometry_provenance(),
                    self.config.geometry_source,
                )
        spec, cfg = self.spec, self.config
        n_devices = self._resolve_devices()
        stride = cfg.resolve_block_stride()
        from ..ops.packing import piece_schema_for
        from ..ops.pallas_expand import (
            k_opts_for,
            opts_for,
            scalar_units_for,
        )

        # On TPU an eligible config swaps the crack step's expand+hash
        # pair for the fused Pallas kernel by default (ops.pallas_expand;
        # A5GEN_PALLAS=off opts out).
        fused_opts = opts_for(
            spec, plan, self.ct, block_stride=stride,
            num_blocks=cfg.num_blocks,
        )
        scalar_units = scalar_units_for(plan)
        # K=1 tables (all radices <= 2): the XLA decode collapses to bit
        # extraction (expand_matches.decode_digits radix2 path).
        radix2 = k_opts_for(plan) == 1
        # Per-slot piece emission (PERF.md §17; A5GEN_EMIT=bytescan opts
        # out): one schema drives the Pallas kernels AND the XLA splice.
        pieces = piece_schema_for(
            plan, self.ct, cache_dir=self._schema_cache_dir(),
            max_mb=self._schema_cache_max_mb(),
        )
        # ``spec`` is baked into every traced body (mode picks the
        # expansion kernel, algo the hash, the window the emit mask) —
        # it MUST be key material or sweeps of different attacks would
        # share a program (AttackSpec is frozen, hence hashable).
        skey = (kind, spec, n_devices, cfg.lanes, plan.out_width, stride,
                fused_opts, scalar_units, radix2, _pieces_static(pieces))
        step_ctx = dict(
            fused_opts=fused_opts, scalar_units=scalar_units,
            radix2=radix2, stride=stride, pieces=pieces, step_key=skey,
        )
        if n_devices == 1:
            t, darrs = self._shared_device_arrays(kind, None)
            p = plan_arrays(plan)
            if fused_opts is not None and scalar_units:
                # Word-level scalar-unit fields precomputed once per
                # plan; the kernel wrapper preps by gathering.
                p.update(scalar_units_arrays(plan, self.ct))
            if pieces is not None:
                p.update(piece_arrays(pieces))
            step_ctx["arrays"] = (p, t, darrs)
            if kind == "crack":
                step = self._get_step(skey, lambda: make_crack_step(
                    spec, num_lanes=cfg.lanes, out_width=plan.out_width,
                    block_stride=stride, fused_expand_opts=fused_opts,
                    fused_scalar_units=scalar_units, radix2=radix2,
                    pieces=pieces,
                ))
                return (
                    (lambda blocks: step(p, t, blocks, darrs)),
                    1, None, step_ctx,
                )
            step = self._get_step(skey, lambda: make_candidates_step(
                spec, num_lanes=cfg.lanes, out_width=plan.out_width,
                block_stride=stride, radix2=radix2, pieces=pieces,
            ))
            return (lambda blocks: step(p, t, blocks)), 1, None, step_ctx

        from ..parallel.mesh import (
            make_sharded_candidates_step,
            make_sharded_crack_step,
            replicate,
        )

        mesh = self._get_mesh(n_devices)
        # shard_map closures bind the mesh; JAX meshes compare by
        # content, so keying on the device ids shares programs across
        # sweeps over the same devices.
        skey = skey + (tuple(int(d.id) for d in mesh.devices.flat),)
        t, darrs = self._shared_device_arrays(kind, mesh)
        parr = plan_arrays(plan)
        if kind == "crack" and fused_opts is not None and scalar_units:
            parr.update(scalar_units_arrays(plan, self.ct))
        if pieces is not None:
            parr.update(piece_arrays(pieces))
        p = replicate(mesh, parr)
        step_ctx["arrays"] = (p, t, darrs)
        if kind == "crack":
            step = self._get_step(skey, lambda: make_sharded_crack_step(
                spec, mesh, lanes_per_device=cfg.lanes,
                out_width=plan.out_width, block_stride=stride,
                fused_expand_opts=fused_opts,
                fused_scalar_units=scalar_units, radix2=radix2,
                pieces=pieces,
            ))
            return (
                (lambda blocks: step(p, t, darrs, blocks)),
                n_devices, mesh, step_ctx,
            )
        step = self._get_step(skey, lambda: make_sharded_candidates_step(
            spec, mesh, lanes_per_device=cfg.lanes,
            out_width=plan.out_width, block_stride=stride, radix2=radix2,
            pieces=pieces,
        ))
        return (lambda blocks: step(p, t, blocks)), n_devices, mesh, step_ctx

    # ------------------------------------------------------------------
    # Superstep executor (crack mode, PERF.md §15)
    # ------------------------------------------------------------------

    def _superstep_steps(self) -> Optional[int]:
        """Requested steps-per-superstep, or None when the superstep
        executor is off (``SweepConfig.superstep=0`` or
        ``A5GEN_SUPERSTEP=off``)."""
        from .env import env_opt_out

        if env_opt_out(
            "A5GEN_SUPERSTEP", "superstep on for eligible crack sweeps"
        ):
            return None
        cfg = self.config
        if cfg.superstep is not None and int(cfg.superstep) <= 0:
            return None
        return max(
            1, int(cfg.superstep) if cfg.superstep else int(cfg.fetch_chunk)
        )

    def _pipeline_depth(self) -> int:
        """In-flight superstep budget for :meth:`_drive_superstep`:
        ``max_in_flight`` buffer sets (default 2 — the double-buffered
        pipeline, PERF.md §18; deeper configs keep the pre-§18 loop's
        dispatch-ahead contract for long-latency links) unless the
        config or ``A5GEN_PIPELINE`` pins the barriered drive."""
        from .env import pipeline_enabled

        cfg = self.config
        if not pipeline_enabled():
            return 1
        if not (cfg.pipeline is None or cfg.pipeline):
            return 1
        # max_in_flight bounds the in-flight working set even when the
        # pipeline is explicitly requested — it is the device-memory
        # contract the per-launch path honors too (one buffer set per
        # in-flight superstep).
        return max(1, int(cfg.max_in_flight))

    def _pair_k(self, plan, pieces, stride) -> "Optional[int]":
        """The pair-lane decision for one compiled plan (PERF.md §24):
        2 when the config, env hatch, schema pair gate, and wrapper
        facts all admit K=2 candidates per lane, else None.  ONE
        implementation — the fuse layer's ``pack_candidate`` calls this
        too, so packed and solo dispatches can never disagree."""
        from ..ops.pallas_expand import pair_for

        cfg_pair = self.config.pair
        if cfg_pair is not None and str(cfg_pair).lower() in (
            "0", "off", "no", "false"
        ):
            return None
        k = pair_for(self.spec, plan, pieces, block_stride=stride)
        if k is None and str(cfg_pair).lower() in ("on", "1", "2", "true"):
            # An EXPLICIT opt-in deserves a diagnostic when it can't be
            # honored (the A5GEN_PALLAS=expand convention); auto falls
            # back silently.
            if not getattr(self, "_pair_warned", False):
                self._pair_warned = True
                import sys

                print(
                    "a5gen: warning: pair requested (--pair on) but "
                    "this plan/config is not pair-eligible (schema "
                    "gate, windowed decode, or hash-block count); "
                    "running K=1",
                    file=sys.stderr,
                )
        return k

    def _superstep_static(self, plan, n_devices: int, mesh, step_ctx,
                          force_solo: bool = False):
        """The cursor-independent half of the superstep build: the
        compiled step (shared via the step cache — the trace no longer
        bakes the sweep's block count, so equal-structure streaming
        chunks reuse one program), the device-resident index arrays,
        and the dispatch closure.  None when the executor cannot take
        this plan: config/env opt-out, packed block layout, or an
        int32-unsafe block index (huge words).

        Streaming calls this ON THE WORKER THREAD (the ss-array
        transfers and the XLA compile overlap the previous chunk's
        device sweep); the whole path calls it lazily from
        :meth:`_make_superstep`."""
        steps = self._superstep_steps()
        if steps is None:
            return None
        cfg = self.config
        stride = cfg.resolve_block_stride()
        if stride is None:
            return None
        # Pair-lane tier (PERF.md §24): blocks cover ``pair_k`` × the
        # lane stride in CANDIDATE ranks, so the whole cursor fabric
        # below (index, boundaries, checkpoints, replay ranges) walks
        # in rank_stride units while the launch geometry stays
        # ``cfg.lanes`` lanes.  An int32-overflowing pair index falls
        # back to the solo tier rather than the per-launch path.
        pair_k = (
            None if force_solo
            else self._pair_k(plan, step_ctx["pieces"], stride)
        )
        rank_stride = stride * (pair_k or 1)
        idx = superstep_index(plan, rank_stride)
        if idx is None and pair_k is not None:
            pair_k, rank_stride = None, stride
            idx = superstep_index(plan, stride)
        if idx is None:
            return None
        cum, _totals, total_blocks = idx
        # Pod-sharded giant-job striping (PERF.md §29): with P pod
        # processes of D local devices, global device stripe
        # ``p*D + d`` starts at ``b0 + (p*D + d) * num_blocks`` and
        # EVERY stripe advances ``steps * num_blocks * P * D`` per
        # superstep — the sharded executor's per-device striping with
        # the pod as the outer axis, so the union of the shards'
        # streams is exactly the single-device stream and boundary
        # cursors stay global.  All shards must compute the identical
        # ``steps`` cap, hence total_stripes (not n_devices) below.
        pod_index, pod_procs = cfg.pod or (0, 1)
        total_stripes = n_devices * pod_procs
        stripe_off = pod_index * n_devices
        # The superstep's device accumulator is int32: cap steps so a
        # worst case of every lane emitting cannot reach 2^31 per fetch.
        steps = max(1, min(
            steps,
            ((1 << 31) - 1)
            // max(1, cfg.lanes * total_stripes * (pair_k or 1)),
        ))
        # The tail superstep's device cursor overshoots the sweep end by
        # up to one full superstep (those blocks cut zero-count); the
        # overshot indices must themselves stay int32, or `b < total`
        # comparisons wrap negative and resurrect word-0 blocks.
        if (
            total_blocks + (steps + 1) * cfg.num_blocks * total_stripes
            >= (1 << 31)
        ):
            return None
        hit_cap = int(cfg.superstep_hit_cap)
        common = dict(
            out_width=plan.out_width, block_stride=stride, steps=steps,
            hit_cap=hit_cap, total_blocks=total_blocks,
            windowed=bool(getattr(plan, "windowed", False)),
            fused_expand_opts=step_ctx["fused_opts"],
            fused_scalar_units=step_ctx["scalar_units"],
            radix2=step_ctx["radix2"],
            pieces=step_ctx["pieces"],
            pair_k=pair_k,
        )
        # ``total_blocks`` rides the ss tree as data, so it is NOT key
        # material — chunks of different length share the program.
        skey = ("superstep", self.spec, n_devices, cfg.lanes,
                cfg.num_blocks, plan.out_width, stride, steps, hit_cap,
                common["windowed"], step_ctx["fused_opts"],
                step_ctx["scalar_units"], step_ctx["radix2"],
                _pieces_static(step_ctx["pieces"]), pair_k)
        if cfg.pod is not None:
            # The per-step advance is baked into the traced body, so
            # pod-striped programs must never share a cache entry with
            # solo ones (and solo keys stay byte-identical to pre-pod).
            skey = skey + (("pod", stripe_off, total_stripes),)
            common = dict(
                common, step_advance=cfg.num_blocks * total_stripes
            )
        if mesh is not None:
            skey = skey + (tuple(int(d.id) for d in mesh.devices.flat),)
        p, t, darrs = step_ctx["arrays"]
        if n_devices == 1:
            from ..models.attack import superstep_buffers

            step = self._get_step(skey, lambda: make_superstep_step(
                self.spec, num_lanes=cfg.lanes, num_blocks=cfg.num_blocks,
                **common,
            ))
            ss = superstep_arrays(plan, rank_stride, idx=idx)
            make_bufs = lambda: superstep_buffers(hit_cap)  # noqa: E731
            solo_off = stripe_off * cfg.num_blocks

            def call(b: int, bufs):
                return step(p, t, darrs, ss, np.int32(b + solo_off), bufs)
        else:
            from ..parallel.mesh import (
                make_sharded_superstep_step,
                replicate,
                shard_leading,
            )

            step = self._get_step(
                skey, lambda: make_sharded_superstep_step(
                    self.spec, mesh, lanes_per_device=cfg.lanes,
                    num_blocks=cfg.num_blocks, **common,
                )
            )
            ss = replicate(mesh, superstep_arrays(plan, rank_stride,
                                                  idx=idx))
            nb = cfg.num_blocks

            def make_bufs():
                per_dev = hit_cap + 1
                return shard_leading(mesh, {
                    "hit_word": np.full(
                        (n_devices * per_dev,), -1, np.int32
                    ),
                    "hit_rank": np.zeros(
                        (n_devices * per_dev,), np.int32
                    ),
                })

            def call(b: int, bufs):
                b0_dev = shard_leading(mesh, np.asarray(
                    [b + (stripe_off + d) * nb for d in range(n_devices)],
                    np.int32,
                ))
                return step(p, t, darrs, ss, b0_dev, bufs)

        return {
            "call": call,
            "make_bufs": make_bufs,
            "ss": ss,
            "key": skey,
            "steps": steps,
            # Every cursor below (resume alignment, boundary decode,
            # replay ranges) walks in RANK stride units — pair_k × the
            # lane stride (PERF.md §24).
            "stride": rank_stride,
            "pair": pair_k or 0,
            "cum": cum,
            "total_blocks": total_blocks,
            "hit_cap": hit_cap,
            "advance": steps * cfg.num_blocks * total_stripes,
            # Pod giant-job stripe layout (None = no pod striping):
            # overflow replay must re-run only THIS shard's stripes of
            # the superstep's global [b_lo, b_hi) range.
            "stripe": (
                None if cfg.pod is None
                else (stripe_off, n_devices, total_stripes, cfg.num_blocks)
            ),
        }

    def _make_superstep(self, plan, cursor: SweepCursor, n_devices: int,
                        mesh, step_ctx):
        """Build this plan's superstep executor, or None when the
        per-launch pipeline should carry it: static ineligibility
        (:meth:`_superstep_static`) or a stride-misaligned resume cursor
        (cross-geometry checkpoints).

        Returns a descriptor dict whose ``call(b0, bufs)`` dispatches one
        superstep starting at plan-local block index ``b0`` into the
        device hit-buffer set ``bufs`` — ONE device program running
        ``steps`` fused launches with on-device block cutting
        (``models.attack.make_superstep_body``); ``make_bufs()``
        allocates one buffer set (the pipelined driver cycles ``depth``
        of them).
        Must run after :meth:`_make_launch` (which resolves the geometry
        and returns the step-build context the executor shares; the
        streaming worker pre-builds the static half into
        ``step_ctx['ss_static']``)."""
        if "ss_static" not in step_ctx:
            step_ctx["ss_static"] = self._superstep_static(
                plan, n_devices, mesh, step_ctx
            )
        st = step_ctx["ss_static"]
        if st is None:
            return None
        cum, stride = st["cum"], st["stride"]
        total_blocks = st["total_blocks"]
        # Normalize the cursor exactly as make_blocks does (skip fallback
        # and finished words), then require stride alignment — misaligned
        # resumes keep the scalar per-launch path, as they always have.
        w, rank = cursor.word, cursor.rank
        while w < plan.batch and (
            plan.fallback[w] or rank >= plan.n_variants[w]
        ):
            w, rank = w + 1, 0
        if w < plan.batch and rank % stride:
            # Pair-misaligned but K=1-aligned (a checkpoint taken at an
            # odd superstep boundary of a solo run): degrade to the K=1
            # SUPERSTEP tier instead of the per-launch path — the same
            # way pack_candidate degrades a misaligned tenant.  The
            # region keeps the §15 dispatch amortization; only the pair
            # multiplier is lost, and only for this resumed region.
            lane_stride = stride // (st.get("pair") or 1)
            if st.get("pair") and rank % lane_stride == 0:
                st = step_ctx["ss_static"] = self._superstep_static(
                    plan, n_devices, mesh, step_ctx, force_solo=True
                )
                if st is None:
                    return None
                cum, stride = st["cum"], st["stride"]
                total_blocks = st["total_blocks"]
            else:
                return None
        b0 = total_blocks if w >= plan.batch else int(cum[w]) + rank // stride
        if w < plan.batch and block_cursor(plan, stride, cum, b0) != (w, rank):
            # Resume integrity: the executor's start block must round-trip
            # to the (normalized) checkpoint cursor exactly — a cum/cursor
            # mismatch here would silently re-sweep or skip blocks, and a
            # drained pipelined run must land where the checkpoint says it
            # did (cross-path resumes pin this in tests).
            raise RuntimeError(
                f"superstep resume cursor mismatch: block {b0} decodes to "
                f"{block_cursor(plan, stride, cum, b0)}, checkpoint says "
                f"({w}, {rank}); the checkpoint does not match this "
                "plan/geometry"
            )
        return {**st, "depth": self._pipeline_depth(), "b0": b0}

    def _drive_superstep(
        self, ss, state: CheckpointState, launch: Callable, n_devices: int,
        mesh, device_hit: Callable, fallback_candidate: Callable,
        prefetch, last_ckpt: List[float], process_launch_hits: Callable,
        plan=None, row_base: int = 0,
    ) -> "Iterator[None]":
        """The superstep launch loop: one dispatch and ONE device→host
        fetch per ``steps`` fused launches.  A GENERATOR — the explicitly
        resumable state machine of the service mode (PERF.md §20): it
        yields once per FETCHED superstep, with ``state`` consistent at
        that lagged boundary, so a resident engine can interleave many
        sweeps by round-robining ``next()`` across their machines (and
        abandon one mid-sweep: the machine's state IS the checkpoint).
        The generator's return value (``StopIteration.value`` /
        ``yield from``) is the region's superstep stats dict.
        The drive is double-buffered
        over ``depth`` alternating device hit-buffer sets
        (``max_in_flight``, default 2 — PERF.md §18): superstep N+1 is
        dispatched into set B before set A's counters are fetched, so
        the fetch overlaps the next
        superstep's compute — the honest-sync rule moves to the lagged
        barrier: fetching set A completes superstep N ONLY, never the
        in-flight one, and nothing calls ``block_until_ready``.  A set is
        recycled only after its counters (and any hit slice) were
        consumed, which with donation makes the cycle a true double
        buffer.  A device whose capped hit buffer overflowed triggers an
        exact per-launch replay of that superstep's block range;
        checkpoint/progress/replay all land at the FETCHED (lagged)
        superstep boundary, and the loop exits only once the in-flight
        superstep is drained.  ``plan``/``row_base`` scope the drive to
        one compiled plan region (a streaming chunk: plan rows are
        dictionary rows ``row_base + local``); the whole-dictionary path
        passes neither."""
        cfg = self.config
        plan = self.plan if plan is None else plan
        cum, stride = ss["cum"], ss["stride"]
        total_blocks, hit_cap = ss["total_blocks"], ss["hit_cap"]
        advance, depth = ss["advance"], ss["depth"]
        stats = {"supersteps": 0, "launches": 0, "replays": 0,
                 "retries": 0, "launches_per_fetch": ss["steps"],
                 "pipelined": int(depth > 1),
                 "pair": int(ss.get("pair", 0))}
        free_bufs = [ss["make_bufs"]() for _ in range(depth)]
        inflight: deque = deque()
        b0 = ss["b0"]
        consumed_b0 = ss["b0"]
        attempts = 0
        while b0 < total_blocks or inflight:
            try:
                while b0 < total_blocks and len(inflight) < depth:
                    # The dispatch wall-clock rides the deque as plain
                    # data; the telemetry record itself happens only at
                    # the fetch boundary below (audit_telemetry pins that
                    # the in-flight window stays instrumentation-free).
                    if faults.ACTIVE is not None:
                        faults.ACTIVE.fire("superstep.dispatch")
                    inflight.append(
                        (b0, time.monotonic(),
                         ss["call"](b0, free_bufs.pop()))
                    )
                    b0 += advance
                sb0, disp_t, out = inflight.popleft()
                if faults.ACTIVE is not None:
                    faults.ACTIVE.fire("superstep.fetch")
                self._await_fetch(out["counters"])
                # The ONE per-superstep fetch — the completion barrier
                # for superstep N only (N+1 keeps running on device).
                counters = np.asarray(out["counters"])
            except Exception as exc:  # noqa: BLE001 — typed check inside
                # Transient-device-error supervision (PERF.md §23):
                # _retry_backoff re-raises unless exc is transient and
                # attempts remain; recovery drops every in-flight
                # dispatch (results unfetched — their blocks re-run),
                # rebuilds the buffer sets (a dispatch may have consumed
                # one before dying), and re-dispatches from the last
                # FETCHED boundary, which the lagged-checkpoint
                # discipline keeps exact.
                self._retry_backoff(exc, attempts)
                attempts += 1
                stats["retries"] += 1
                inflight.clear()
                free_bufs[:] = [ss["make_bufs"]() for _ in range(depth)]
                b0 = consumed_b0
                continue
            attempts = 0
            consumed_b0 = sb0 + advance
            ne, nh = int(counters[0]), int(counters[1])
            if self._ttfc[0] is None:
                self._ttfc[0] = time.monotonic()
            end_b = min(sb0 + advance, total_blocks)
            end_w, end_r = block_cursor(plan, stride, cum, end_b)
            replayed = False
            hit_occupancy = 0.0
            if nh:
                dev_hits = np.asarray(out["dev_hits"])
                hit_occupancy = int(dev_hits.max()) / max(hit_cap, 1)
                if int(dev_hits.max()) > hit_cap:
                    # Graceful degradation: the capped device buffer
                    # dropped entries — replay this superstep exactly
                    # through the per-launch path (its hit processing is
                    # the accounting; the scan's counts stand).  Under
                    # pod striping only THIS shard's stripe sub-ranges
                    # replay — re-running a peer's blocks would emit
                    # duplicate hits.
                    stats["replays"] += 1
                    replayed = True
                    for r_lo, r_hi in self._pod_replay_ranges(
                        sb0, end_b, ss
                    ):
                        self._replay_superstep(
                            r_lo, r_hi, ss, launch, n_devices, mesh,
                            process_launch_hits, plan=plan,
                        )
                else:
                    hw = np.asarray(out["hit_word"])
                    hr = np.asarray(out["hit_rank"])
                    per_dev = hit_cap + 1  # trailing trash slot
                    entries: List[Tuple[int, int]] = []
                    for d in range(n_devices):
                        k = int(dev_hits[d])
                        lo = d * per_dev
                        entries.extend(zip(hw[lo:lo + k].tolist(),
                                           hr[lo:lo + k].tolist()))
                    # (word, rank) sort = cursor order: device stripes
                    # interleave by scan step, so the raw buffer order is
                    # per-device, not global.
                    entries.sort()
                    for w_row, rank in entries:
                        device_hit(int(w_row), int(rank))
            # Superstep N's buffers are fully consumed — recycle the set
            # for superstep N+2 (donation aliases the next dispatch's
            # outputs onto it).
            free_bufs.append({"hit_word": out["hit_word"],
                              "hit_rank": out["hit_rank"]})
            # Fallback words wholly before the cursor are due now
            # (cursors/flush are GLOBAL dictionary rows: plan-local
            # words translate by the region's row base).
            self._flush_fallback_until(
                row_base + end_w, state, fallback_candidate, prefetch
            )
            state.n_emitted += ne
            state.cursor = SweepCursor(row_base + end_w, end_r)
            stats["supersteps"] += 1
            stats["launches"] += ss["steps"]
            # Span record at the consumed (lagged) fetch boundary —
            # already host-side, so the overlap invariant is untouched
            # (PERF.md §21); in-flight depth 0 here means the fetch gap
            # was dead device time (the barriered arm's signature).
            with telemetry.profiler_span("a5.superstep.consume"):
                self.timeline.record_fetch(
                    kind="superstep", index=stats["supersteps"],
                    dispatched_at=disp_t, inflight=len(inflight),
                    launches=ss["steps"], emitted=ne, hits=nh,
                    hit_occupancy=hit_occupancy, replayed=replayed,
                    chunk=(
                        row_base // self._stream["chunk_words"]
                        if self._stream is not None else None
                    ),
                )
            self._maybe_checkpoint(state, last_ckpt)
            if cfg.progress:
                cfg.progress.update(
                    words_done=row_base + end_w,
                    emitted=state.n_emitted,
                    hits=state.n_hits,
                )
            yield
        return stats

    def _pod_replay_ranges(
        self, b_lo: int, b_hi: int, ss
    ) -> "Iterator[Tuple[int, int]]":
        """The block sub-ranges THIS process owns inside one superstep's
        global ``[b_lo, b_hi)`` range.  Without pod striping that is the
        whole range; under ``SweepConfig.pod`` each scan step ``s``
        grants this shard the contiguous slice
        ``[b_lo + s*span + off*nb, + n_local*nb)`` where ``span =
        total_stripes * nb`` — its local devices' stripes — clipped to
        the sweep end (overshot stripes cut zero-count blocks on
        device, and must replay nothing on the host)."""
        stripe = ss.get("stripe")
        if stripe is None:
            yield (b_lo, b_hi)
            return
        off, n_local, total_stripes, nb = stripe
        span = total_stripes * nb
        total_blocks = ss["total_blocks"]
        base = b_lo
        while base < b_hi:
            lo = base + off * nb
            hi = min(lo + n_local * nb, total_blocks)
            if lo < hi:
                yield (lo, hi)
            base += span

    def _replay_superstep(
        self, b_lo: int, b_hi: int, ss, launch: Callable, n_devices: int,
        mesh, process_launch_hits: Callable, plan=None,
    ) -> None:
        """Exact per-launch replay of one superstep's block range — the
        hit-buffer overflow fallback.  The host fast cutter shares the
        device cutter's index arrays, so the replay cuts the SAME blocks
        and its per-launch hit bitmasks recover every dropped hit."""
        plan = self.plan if plan is None else plan
        stride, cum = ss["stride"], ss["cum"]
        w, rank = block_cursor(plan, stride, cum, b_lo)
        end = block_cursor(plan, stride, cum, b_hi)
        for segments, out, cur in self._launches(
            SweepCursor(w, rank), launch, n_devices=n_devices, mesh=mesh,
            plan=plan,
        ):
            if int(out["n_hits"]):
                process_launch_hits(segments, out)
            if (cur.word, cur.rank) >= end:
                # In-flight launches past the range are dropped unfetched
                # (their hits belong to later supersteps' own buffers).
                break

    def _drive_packed(
        self, src, plan, state: CheckpointState, launch: Callable,
        n_devices: int, mesh, device_hit: Callable,
        fallback_candidate: Callable, prefetch, last_ckpt: List[float],
        process_launch_hits: Callable,
    ) -> "Iterator[None]":
        """The consume half of the cross-job packed drive (PERF.md
        §22).  ``src`` is the engine's ``runtime.fuse.FusedGroup``: it
        owns dispatch and the single per-round counters fetch across
        ALL fused tenants; this generator pulls this job's own split
        result per tick — per-job emitted/hit counts from the packed
        program's segmented counter rows, (word, rank) hit entries
        already mapped back to job-local plan rows — and runs the SAME
        consume sequence as :meth:`_drive_superstep`'s post-fetch half
        (fallback interleave at the cursor, host hit re-derivation +
        re-verification, lagged-boundary checkpoint/progress, the
        span-timeline record — so per-job telemetry attribution under
        fused dispatches is the solo instrument, untouched).  The two
        bodies must stay statement-for-statement mirrors: a consume fix
        in either drive belongs in both.  Overflowed supersteps
        replay this job's own block range through its per-launch path,
        exactly like the solo drive.  Detaches from the group in the
        finally, so completion, pause, cancel and failure all park the
        job's segment without disturbing cohabitants."""
        cfg = self.config
        stride, cum = src.stride, src.member_cum(self)
        stats = {"supersteps": 0, "launches": 0, "replays": 0,
                 "launches_per_fetch": src.steps,
                 "pipelined": int(src.depth > 1),
                 "packed": src.n_seg,
                 "pair": int(getattr(src, "pair_k", 0))}
        try:
            while True:
                res = src.next_result(self)
                if res is None:
                    break
                ne, nh = res["ne"], res["nh"]
                if self._ttfc[0] is None:
                    self._ttfc[0] = time.monotonic()
                end_w, end_r = block_cursor(plan, stride, cum,
                                            res["b_hi"])
                replayed = False
                if res["overflow"]:
                    stats["replays"] += 1
                    replayed = True
                    self._replay_superstep(
                        res["b_lo"], res["b_hi"],
                        {"stride": stride, "cum": cum}, launch,
                        n_devices, mesh, process_launch_hits, plan=plan,
                    )
                else:
                    for w_row, rank in res["entries"]:
                        device_hit(int(w_row), int(rank))
                self._flush_fallback_until(
                    end_w, state, fallback_candidate, prefetch
                )
                state.n_emitted += ne
                state.cursor = SweepCursor(end_w, end_r)
                stats["supersteps"] += 1
                stats["launches"] += src.steps
                with telemetry.profiler_span("a5.superstep.consume"):
                    self.timeline.record_fetch(
                        kind="superstep", index=stats["supersteps"],
                        dispatched_at=res["disp_t"],
                        inflight=res["inflight"], launches=src.steps,
                        emitted=ne, hits=nh,
                        hit_occupancy=res["hit_occupancy"],
                        replayed=replayed,
                    )
                self._maybe_checkpoint(state, last_ckpt)
                if cfg.progress:
                    cfg.progress.update(
                        words_done=end_w,
                        emitted=state.n_emitted,
                        hits=state.n_hits,
                    )
                yield
        finally:
            src.leave(self)
        return stats

    # ------------------------------------------------------------------
    # Fault supervision (PERF.md §23)
    # ------------------------------------------------------------------

    def _retry_backoff(self, exc: BaseException, attempts: int) -> None:
        """The retry supervisor's gate over this sweep's config knobs —
        re-raise vs count+backoff lives in ONE place,
        :func:`faults.supervise_retry` (the packed pump shares it)."""
        cfg = self.config
        faults.supervise_retry(
            exc, attempts, attempts_budget=cfg.retry_attempts,
            backoff_s=cfg.retry_backoff_s, label="the sweep drive",
        )

    def _await_fetch(self, value) -> None:
        """Watchdog on a consumed fetch: ``SweepConfig.fetch_timeout_s``
        through the shared :func:`faults.await_ready` (the packed pump
        rides the same helper).  Off by default: giant cold compiles
        and CPU sweeps legitimately outlast any sane timeout."""
        faults.await_ready(value, self.config.fetch_timeout_s)

    def _dispatch_launch(self, launch: Callable, blocks):
        """One per-launch-path dispatch under the same supervision as
        the superstep drive: the ``superstep.dispatch`` injection point
        covers both drive shapes, and a transient dispatch error is
        retried with backoff (the launch is pure — re-dispatching the
        same blocks is exact replay)."""
        attempts = 0
        while True:
            try:
                if faults.ACTIVE is not None:
                    faults.ACTIVE.fire("superstep.dispatch")
                return launch(blocks)
            except Exception as exc:  # noqa: BLE001 — typed check inside
                self._retry_backoff(exc, attempts)
                attempts += 1

    def _launches(
        self, cursor: SweepCursor, launch: Callable, *, n_devices: int = 1,
        mesh=None, plan=None,
    ) -> Iterator[Tuple[list, object, SweepCursor]]:
        """Double-buffered launch stream: yields (segments, device out,
        cursor AFTER this launch); ``segments`` is a cursor-ordered list of
        ``(batch, lane_lo, lane_hi)`` — one entry per device, slicing the
        launch's flat lane axis. Dispatch runs ``max_in_flight`` ahead of
        fetch, so host block-cutting overlaps device execution.
        ``plan`` scopes the stream to one compiled plan region (a
        streaming chunk); cursors here are plan-LOCAL."""
        cfg = self.config
        plan = self.plan if plan is None else plan
        stride = cfg.resolve_block_stride()
        pending: deque = deque()
        w, rank = cursor.word, cursor.rank
        lanes = cfg.lanes
        while True:
            # Annotated so a --profile trace shows how much wall-clock the
            # host-side scheduler costs vs the overlapped device launches
            # (guarded: a no-op wherever the profiler is unavailable).
            with telemetry.profiler_span("a5.host_cut_blocks"):
                if n_devices == 1:
                    batch, w2, rank2 = make_blocks(
                        plan,
                        start_word=w,
                        start_rank=rank,
                        max_variants=lanes,
                        max_blocks=cfg.num_blocks,
                        fixed_stride=stride,
                    )
                    if batch.total == 0:
                        break
                    blocks = block_arrays(batch, num_blocks=cfg.num_blocks)
                    segments = [(batch, 0, lanes)]
                else:
                    from ..parallel.mesh import (
                        make_device_blocks,
                        shard_leading,
                        stack_blocks,
                    )

                    batches, w2, rank2 = make_device_blocks(
                        plan,
                        n_devices=n_devices,
                        lanes_per_device=lanes,
                        start_word=w,
                        start_rank=rank,
                        max_blocks=cfg.num_blocks,
                        fixed_stride=stride,
                    )
                    if sum(b.total for b in batches) == 0:
                        break
                    blocks = shard_leading(
                        mesh, stack_blocks(batches, num_blocks=cfg.num_blocks)
                    )
                    segments = [
                        (batches[d], d * lanes, (d + 1) * lanes)
                        for d in range(n_devices)
                    ]
            out = self._dispatch_launch(launch, blocks)
            pending.append((segments, out, SweepCursor(w2, rank2)))
            w, rank = w2, rank2
            if len(pending) >= cfg.max_in_flight:
                yield pending.popleft()
        while pending:
            yield pending.popleft()

    def _maybe_checkpoint(self, state: CheckpointState, last: List[float],
                          *, force: bool = False,
                          before_save: Optional[Callable[[], None]] = None
                          ) -> None:
        cfg = self.config
        if cfg.checkpoint_path is None:
            return
        now = time.monotonic()
        if force or now - last[0] >= cfg.checkpoint_every_s:
            if before_save is not None:
                # Durably land everything the cursor claims was emitted
                # BEFORE the checkpoint asserts it (else a crash between
                # the save and the flush loses output resume cannot replay).
                before_save()
            try:
                save_checkpoint(cfg.checkpoint_path, state)
            except Exception as exc:  # noqa: BLE001 — periodic-save fate
                # A PERIODIC save failure (disk full, injected
                # checkpoint.write fault) must not kill a healthy sweep
                # — the atomic write left the previous checkpoint
                # intact, the state stays in memory, and the next
                # interval retries.  The FINAL forced save is the
                # durability the caller asked for: it propagates.
                if force:
                    raise
                telemetry.counter("faults.checkpoint_errors").add(1)
                import sys

                print(
                    f"a5gen: warning: checkpoint write failed "
                    f"({type(exc).__name__}: {exc}); previous checkpoint "
                    "intact, retrying at the next interval",
                    file=sys.stderr,
                )
            last[0] = now

    def _flush_fallback_until(
        self,
        word_row: int,
        state: CheckpointState,
        on_candidate: Callable[[int, int, bytes], None],
        prefetch: "Optional[_FallbackPrefetcher]" = None,
    ) -> None:
        """Emit every unprocessed fallback word < ``word_row`` (pass
        ``len(words)`` to flush all). Candidate callback gets (word_row,
        dfs_index, candidate). With ``prefetch``, rows come from the
        worker thread's queue (expanded concurrently with device
        launches); without, the oracle runs inline."""
        while (
            state.fallback_done < len(self.fallback_rows)
            and self.fallback_rows[state.fallback_done] < word_row
        ):
            row = self.fallback_rows[state.fallback_done]
            source = (
                prefetch.iter_row()
                if prefetch is not None
                else enumerate(self._oracle_candidates(row))
            )
            for i, cand in source:
                on_candidate(row, i, cand)
                state.n_emitted += 1
            state.fallback_done += 1

    def _make_prefetcher(
        self, state: CheckpointState
    ) -> "Optional[_FallbackPrefetcher]":
        if state.fallback_done >= len(self.fallback_rows):
            return None
        return _FallbackPrefetcher(self, state.fallback_done)

    # ------------------------------------------------------------------
    # Crack mode
    # ------------------------------------------------------------------

    def _word_plan(self, w_row: int):
        """A cached single-word plan for streaming hit re-derivation:
        per-word plan fields are batch-independent, and the enumeration
        scheme/out_width are forced to the prescan's global decisions,
        so decoding (word 0, rank) here is byte-exact with the chunk
        plan that flagged the hit — without recompiling its chunk."""
        from ..ops.packing import slice_packed

        cache = getattr(self, "_word_plan_cache", None)
        if cache is None:
            cache = self._word_plan_cache = {}
        plan1 = cache.get(w_row)
        if plan1 is None:
            plan1 = build_plan(
                self.spec, self.ct,
                slice_packed(self.packed, w_row, w_row + 1),
                out_width=self._stream["out_width"],
                force_windowed=self._stream["windowed"],
            )
            cache[w_row] = plan1
        return plan1

    def _rederive_hit(self, w_row: int, rank: int) -> bytes:
        """Candidate bytes of a checkpointed hit (resume replay).
        Fallback-word hits carry a DFS index, not a variant rank —
        re-derive via the oracle.  Streaming sweeps have no whole-
        dictionary plan; a single-word mini-plan decodes the hit
        without recompiling its (already-swept) chunk."""
        if self._stream is None:
            plan, row = self.plan, w_row
        else:
            plan, row = self._word_plan(w_row), 0
        if plan.fallback[row]:
            return next(
                c
                for i, c in enumerate(self._oracle_candidates(w_row))
                if i == rank
            )
        return decode_variant(plan, self.ct, self.spec, row, rank)

    def run_crack(
        self,
        recorder: Optional[HitRecorder] = None,
        *,
        resume: bool = True,
        state: "Optional[CheckpointState]" = None,
    ) -> SweepResult:
        """Fused expand→hash→membership; only hits return to the host.

        The implementation IS :meth:`crack_machine`, exhausted — the
        resident engine (PERF.md §20) runs the identical generator with
        interleaving, so a solo job through the engine is byte-identical
        to this path by construction."""
        return _exhaust(self.crack_machine(recorder, resume=resume,
                                           state=state))

    def crack_machine(
        self,
        recorder: Optional[HitRecorder] = None,
        *,
        resume: bool = True,
        state: "Optional[CheckpointState]" = None,
    ) -> "Generator[None, None, SweepResult]":
        """The crack sweep as an explicitly resumable state machine
        (PERF.md §20): a generator yielding at every consumed fetch
        boundary (superstep or chunk drain), with its
        :class:`CheckpointState` — exposed as ``self.active_state`` —
        consistent at each yield.  ``next()`` advances one boundary;
        closing the generator abandons the sweep cleanly (worker
        threads stopped, wall accounted, state at the last boundary —
        the engine's pause/cancel); exhausting it returns the
        :class:`SweepResult` via ``StopIteration.value``.  An injected
        ``state`` (a paused machine's) resumes exactly like an on-disk
        checkpoint."""
        from ..ops.packing import schema_cache_stats

        cfg = self.config
        recorder = recorder if recorder is not None else HitRecorder()
        state, resumed = self._load_state(resume, state)
        self.active_state = state
        sc0 = schema_cache_stats()
        if cfg.progress is not None:
            cfg.progress.seed_emitted(state.n_emitted)
            # Checkpointed hits are re-reported below; they belong to an
            # earlier process's windows, not this one's first rate.
            seed_hits = getattr(cfg.progress, "seed_hits", None)
            if seed_hits is not None:
                seed_hits(state.n_hits)
        self._report_stream_position(state)

        # Replay checkpointed hits into the recorder (resume produces the
        # same final hit list a never-interrupted run would).
        for w_row, rank in state.hits:
            cand = self._rederive_hit(w_row, rank)
            recorder.emit(
                HitRecord(
                    word_index=int(self.packed.index[w_row]),
                    variant_rank=rank,
                    candidate=cand,
                    digest_hex=self._host_digest(cand).hex(),
                )
            )

        def fallback_candidate(row: int, i: int, cand: bytes) -> None:
            dig = self._host_digest(cand)
            if self._digest_contains(dig):
                state.n_hits += 1
                state.hits.append((row, i))
                recorder.emit(
                    HitRecord(
                        word_index=int(self.packed.index[row]),
                        variant_rank=i,
                        candidate=cand,
                        digest_hex=dig.hex(),
                    )
                )

        t0 = time.monotonic()
        self._run_t0 = t0
        self._ttfc = [None]
        last_ckpt = [t0]
        prefetch = self._make_prefetcher(state)
        superstep_stats: Dict[str, int] = {}
        stream_stats: Dict[str, float] = {}
        try:
            if self._stream is not None:
                superstep_stats, stream_stats = yield from self._run_stream(
                    "crack", state,
                    lambda chunk, local: self._crack_plan_region(
                        chunk.plan, chunk.lo, chunk.payload, state, local,
                        recorder, fallback_candidate, prefetch, last_ckpt,
                    ),
                    fallback_candidate, prefetch,
                )
            else:
                launch, n_devices, mesh, step_ctx = self._make_launch(
                    "crack", self.plan
                )
                payload = dict(launch=launch, n_devices=n_devices,
                               mesh=mesh, step_ctx=step_ctx)
                # A resumed streaming checkpoint's chunk marker is stale
                # under whole-dictionary materialization.
                state.stream = None
                superstep_stats = yield from self._crack_plan_region(
                    self.plan, 0, payload, state, state.cursor,
                    recorder, fallback_candidate, prefetch, last_ckpt,
                )
            # Tail: any fallback words at/after the last device word.
            self._flush_fallback_until(
                self.n_words, state, fallback_candidate, prefetch
            )
        finally:
            if prefetch is not None:
                prefetch.close()
            # In the finally so an ABANDONED machine (the engine's
            # pause/cancel closes the generator mid-sweep) still accrues
            # its run time into the checkpointable state.
            state.wall_s += time.monotonic() - t0
        state.cursor = SweepCursor(word=self.n_words, rank=0)
        self._maybe_checkpoint(state, last_ckpt, force=True)
        if cfg.progress:
            cfg.progress.final(
                words_done=self.n_words,
                emitted=state.n_emitted,
                hits=state.n_hits,
            )
        return SweepResult(
            n_emitted=state.n_emitted,
            n_hits=state.n_hits,
            hits=recorder.hits,
            words_done=self.n_words,
            resumed=resumed,
            wall_s=state.wall_s,
            routing=dict(self.routing),
            superstep=superstep_stats,
            stream=stream_stats,
            geometry=self._geometry_provenance(),
            geometry_source=self.config.geometry_source,
            schema_cache=_stats_delta(sc0, schema_cache_stats()),
        )

    def _crack_plan_region(
        self, plan, row_base: int, payload: dict, state: CheckpointState,
        local_cursor: SweepCursor, recorder, fallback_candidate: Callable,
        prefetch, last_ckpt: List[float],
    ) -> "Iterator[None]":
        """Drive the crack loop over ONE compiled plan region — the
        whole dictionary (``row_base`` 0) or one streaming chunk (plan
        rows are dictionary rows ``[row_base, row_base + plan.batch)``).
        ``local_cursor`` is plan-local; everything written to ``state``
        (cursor, hits, fallback flushes) is global.  A generator in the
        machine protocol (PERF.md §20): yields at every consumed fetch
        boundary (superstep or per-launch chunk drain) with ``state``
        consistent; returns the region's superstep stats ({} when the
        per-launch pipeline ran)."""
        spec, cfg = self.spec, self.config
        launch, n_devices = payload["launch"], payload["n_devices"]
        mesh, step_ctx = payload["mesh"], payload["step_ctx"]

        import jax
        import jax.numpy as jnp

        def device_hit(w_local: int, rank: int) -> None:
            """One device-flagged hit, shared by the per-launch and
            superstep paths: flush oracle words that sit before this
            hit's word (the hit list stays word-ordered), re-derive the
            candidate, re-verify its digest on the host, record."""
            w_row = row_base + w_local
            self._flush_fallback_until(
                w_row, state, fallback_candidate, prefetch
            )
            cand = decode_variant(plan, self.ct, spec, w_local, rank)
            dig = self._host_digest(cand)
            # Host re-verification: the device flagged this lane;
            # its digest must really be in the target set.
            if not self._digest_contains(dig):
                raise RuntimeError(
                    f"device hit failed host re-verification: "
                    f"word {w_row} rank {rank} candidate {cand!r}"
                )
            state.n_hits += 1
            state.hits.append((w_row, rank))
            recorder.emit(
                HitRecord(
                    word_index=int(self.packed.index[w_row]),
                    variant_rank=rank,
                    candidate=cand,
                    digest_hex=dig.hex(),
                )
            )

        def process_launch_hits(segments, out) -> None:
            hit = unpack_bits(out["hit_bits"], cfg.lanes * n_devices)
            # Segments are cursor-ordered (device d's lane slice precedes
            # device d+1's), so walking them in order keeps hits
            # word-ordered.
            for batch, lo, hi in segments:
                lanes = np.nonzero(hit[lo:hi])[0]
                for w_local, rank in lane_cursor(plan, batch, lanes):
                    device_hit(w_local, rank)

        if self._packed_source is not None and row_base == 0 \
                and self._stream is None:
            if cfg.pod is not None:
                raise RuntimeError(
                    "pod giant-job mode cannot ride a cross-job packed "
                    "dispatch (the FusedGroup owns the block cursors); "
                    "run giant jobs solo"
                )
            # Cross-job packed dispatch (PERF.md §22): the engine's
            # FusedGroup owns dispatch and the one-per-round fetch; this
            # machine consumes its own split share through the SAME
            # state/hit/fallback bookkeeping the solo drive runs.
            return (yield from self._drive_packed(
                self._packed_source, plan, state, launch, n_devices,
                mesh, device_hit, fallback_candidate, prefetch,
                last_ckpt, process_launch_hits,
            ))

        sstep = self._make_superstep(
            plan, local_cursor, n_devices, mesh, step_ctx
        )
        if sstep is None and cfg.pod is not None:
            # The striping seam IS the superstep executor's block
            # lattice; the per-launch fallback would sweep every shard
            # over the whole keyspace (P× duplicate work and duplicate
            # hit streams).  Fail loudly instead.
            raise RuntimeError(
                "pod giant-job mode requires the superstep executor "
                "(fixed-stride layout, int32-safe block index, "
                "stride-aligned resume cursor); this plan/geometry/"
                "cursor is ineligible — adjust the geometry or drop "
                "--giant-job"
            )
        if sstep is not None:
            return (yield from self._drive_superstep(
                sstep, state, launch, n_devices, mesh,
                device_hit, fallback_candidate, prefetch, last_ckpt,
                process_launch_hits, plan=plan, row_base=row_base,
            ))

        # Per-launch counts chain into a device-side accumulator; the host
        # fetches it once per chunk (see SweepConfig.fetch_chunk). The fetch
        # is the completion barrier for the chunk's whole launch chain.
        accum = self._get_step(
            ("accum",),
            lambda: jax.jit(lambda acc, ne, nh: acc + jnp.stack([ne, nh])),
        )
        acc_zero = jnp.zeros((2,), jnp.int32)
        chunk: List[tuple] = []
        # The device accumulator is int32: cap the chunk so a worst case of
        # every lane emitting cannot reach 2^31 counts per chunk.
        chunk_cap = max(1, min(
            int(cfg.fetch_chunk),
            ((1 << 31) - 1) // max(1, cfg.lanes * n_devices),
        ))
        chunk_len = 1  # grows adaptively toward chunk_cap
        acc = acc_zero
        last_drain = [time.monotonic()]

        def drain_chunk() -> None:
            nonlocal chunk, acc, chunk_len
            if not chunk:
                return
            ne_delta, nh_delta = (int(x) for x in np.asarray(acc))
            if self._ttfc[0] is None:
                self._ttfc[0] = time.monotonic()
            if nh_delta:
                # Rare path: find the hit-bearing launches (scalar probe
                # each) and fetch only their masks.
                for segments_i, out_i, _cur in chunk:
                    if int(out_i["n_hits"]):
                        process_launch_hits(segments_i, out_i)
            end_cursor = chunk[-1][2]
            end_word = row_base + end_cursor.word
            # Fallback words wholly before the cursor are due now.
            self._flush_fallback_until(
                end_word, state, fallback_candidate, prefetch
            )
            state.n_emitted += ne_delta
            state.cursor = SweepCursor(end_word, end_cursor.rank)
            n_launches = len(chunk)
            chunk = []
            acc = acc_zero
            # Span record at the consumed chunk-drain boundary (the
            # per-launch path's fetch barrier, PERF.md §21).
            self.timeline.record_fetch(
                kind="drain", launches=n_launches, emitted=ne_delta,
                hits=nh_delta,
            )
            self._maybe_checkpoint(state, last_ckpt)
            if cfg.progress:
                cfg.progress.update(
                    words_done=end_word,
                    emitted=state.n_emitted,
                    hits=state.n_hits,
                )
            # Adapt: grow while full chunk cycles run fast (amortize the
            # fetch round trip), shrink when they crawl (keep checkpoint
            # and progress granularity).
            cycle = time.monotonic() - last_drain[0]
            if cycle < 1.0:
                chunk_len = min(chunk_len * 2, chunk_cap)
            elif cycle > 4.0:
                chunk_len = max(1, chunk_len // 2)
            last_drain[0] = time.monotonic()

        for item in self._launches(
            local_cursor, launch, n_devices=n_devices, mesh=mesh, plan=plan
        ):
            out = item[1]
            acc = accum(acc, out["n_emitted"], out["n_hits"])
            chunk.append(item)
            if len(chunk) >= chunk_len:
                drain_chunk()
                yield
        drain_chunk()
        return {}

    # ------------------------------------------------------------------
    # Streaming chunk ring (PERF.md §19)
    # ------------------------------------------------------------------

    def _compile_chunk(self, kind: str, ci: int, lo: int, hi: int):
        """ONE chunk's full compile, run on the ring's worker thread
        (PERF.md §19): the chunk plan (enumeration scheme and out_width
        forced to the prescan's global decisions), its PieceSchema
        (through the on-disk cache when configured), the device plan /
        superstep arrays (async ``device_put`` — the transfer overlaps
        the previous chunk's device sweep), and a warmup dispatch that
        forces any new XLA compile HERE instead of in the drive loop.
        Returns the ring's :class:`ops.packing.PlanChunk`."""
        import jax

        from ..ops.packing import PlanChunk, slice_packed

        plan = build_plan(
            self.spec, self.ct, slice_packed(self.packed, lo, hi),
            out_width=self._stream["out_width"],
            force_windowed=self._stream["windowed"],
        )
        launch, n_devices, mesh, step_ctx = self._make_launch(kind, plan)
        payload = dict(launch=launch, n_devices=n_devices, mesh=mesh,
                       step_ctx=step_ctx)
        st = None
        if kind == "crack":
            st = self._superstep_static(plan, n_devices, mesh, step_ctx)
            step_ctx["ss_static"] = st
        # The warmup exists to force XLA compiles onto this worker; when
        # the (step, argument shapes) pair already executed — equal-size
        # chunks with equal schema structure, the step cache's whole
        # point — the executable exists and the warmup would just burn a
        # launch of masked device compute against the live sweep.
        if st is not None:
            wkey = (st["key"], _step_env_key(), _tree_shape_sig(
                (step_ctx["arrays"][0], st["ss"])
            ))
            if wkey not in _WARMED_STEPS:
                # Superstep warmup: one dispatch starting past the
                # chunk's last block — every cut block is invalid
                # (zero-count), the throwaway buffer set absorbs the
                # donation, and the fetch below blocks THIS thread until
                # compile + run finish.
                warm = st["call"](
                    int(st["total_blocks"]), st["make_bufs"]()
                )
                np.asarray(warm["counters"])
                _WARMED_STEPS.add(wkey)
        else:
            # num_blocks is warm-key material the step key deliberately
            # omits (the traced program doesn't depend on it, but the
            # executable specializes on the [num_blocks, ...] blocks
            # argument this warmup dispatches).
            wkey = (step_ctx["step_key"], self.config.num_blocks,
                    _step_env_key(),
                    _tree_shape_sig(step_ctx["arrays"][0]))
            if wkey not in _WARMED_STEPS:
                if self._warm_launch(kind, launch, plan, n_devices, mesh):
                    # Only a dispatch that actually ran proves the
                    # executable exists (an all-fallback chunk cuts no
                    # blocks and warms nothing).
                    _WARMED_STEPS.add(wkey)
        leaves = jax.tree_util.tree_leaves(step_ctx["arrays"][0])
        if st is not None:
            leaves += jax.tree_util.tree_leaves(st["ss"])
        chunk_bytes = int(sum(int(getattr(x, "nbytes", 0)) for x in leaves))
        with self._stream_lock:
            self._stream_resident += chunk_bytes
            self._stream_peak = max(
                self._stream_peak, self._stream_resident
            )
            self._stream_chunk_max = max(self._stream_chunk_max,
                                         chunk_bytes)
        return PlanChunk(
            index=ci, lo=lo, hi=hi, plan=plan,
            pieces=step_ctx["pieces"], payload=payload,
            host_bytes=chunk_bytes, releaser=self._release_chunk,
        )

    def _warm_launch(self, kind: str, launch: Callable, plan,
                     n_devices: int, mesh) -> bool:
        """Force a per-launch step's XLA compile on the worker thread:
        cut and dispatch the region's first block batch, discard the
        outputs (launches are pure — the drive re-cuts and re-runs it).
        Returns whether a dispatch actually ran — a chunk that cuts no
        blocks (every word oracle-routed) warms nothing."""
        cfg = self.config
        stride = cfg.resolve_block_stride()
        if n_devices == 1:
            batch, _w, _r = make_blocks(
                plan, start_word=0, start_rank=0, max_variants=cfg.lanes,
                max_blocks=cfg.num_blocks, fixed_stride=stride,
            )
            if batch.total == 0:
                return False
            blocks = block_arrays(batch, num_blocks=cfg.num_blocks)
        else:
            from ..parallel.mesh import (
                make_device_blocks,
                shard_leading,
                stack_blocks,
            )

            batches, _w, _r = make_device_blocks(
                plan, n_devices=n_devices, lanes_per_device=cfg.lanes,
                start_word=0, start_rank=0, max_blocks=cfg.num_blocks,
                fixed_stride=stride,
            )
            if sum(b.total for b in batches) == 0:
                return False
            blocks = shard_leading(
                mesh, stack_blocks(batches, num_blocks=cfg.num_blocks)
            )
        out = launch(blocks)
        # Block this worker until the compile (and the one discarded
        # launch) completed — the drive loop must never pay it.
        np.asarray(out["n_emitted"] if kind == "crack" else out[3])
        return True

    def _release_chunk(self, chunk) -> None:
        """Free a consumed chunk before the ring advances: the chunk's
        device plan + superstep arrays are deleted explicitly (the
        shared table/digest residents and the drive's hit buffers are
        NOT the chunk's to free); host references are dropped by
        ``PlanChunk.release``."""
        from ..parallel.mesh import delete_tree

        ctx = chunk.payload["step_ctx"]
        delete_tree(ctx["arrays"][0])
        st = ctx.get("ss_static")
        if st is not None:
            delete_tree(st["ss"])
        with self._stream_lock:
            self._stream_resident -= chunk.host_bytes

    def _sweep_chunks(self, compiler, drive_chunk: Callable
                      ) -> "Iterator[None]":
        """The chunk ring's consume loop (PERF.md §19), kept to the
        auditable shape graftaudit's chunk-ring check pins
        (``tools.graftaudit.transfers.audit_chunk_ring``): iterate the
        compiler ring DIRECTLY (materializing it would resurrect the
        O(dictionary) memory this pipeline removes), no host→device
        transfers in the loop body (the worker thread owns every
        transfer), and release each consumed chunk unconditionally
        before the ring advances — resident plan memory stays
        O(ring × chunk).  ``drive_chunk`` is a machine-protocol
        generator (PERF.md §20); its boundary yields pass through."""
        for chunk in compiler:
            yield from drive_chunk(chunk)
            chunk.release()

    def _run_stream(
        self, kind: str, state: CheckpointState, drive_region: Callable,
        fallback_candidate: Callable, prefetch,
    ) -> "Iterator[None]":
        """The streaming drive (PERF.md §19): resume lands on the chunk
        containing the checkpoint cursor (already-swept chunks are never
        recompiled — the prescan plus a mini-plan per checkpointed hit
        cover everything resume needs), then the ring sweeps chunk N
        while the worker compiles N+1.  A machine-protocol generator
        (PERF.md §20; ``drive_region`` must be one too): returns
        (superstep stats merged across chunks, stream stats)."""
        from ..ops.packing import ChunkCompiler

        bounds = self._stream["bounds"]
        cw = self._stream["chunk_words"]
        start_ci = next(
            (ci for ci, (_lo, hi) in enumerate(bounds)
             if state.cursor.word < hi),
            len(bounds),
        )
        superstep_stats: Dict[str, int] = {}
        stream: Dict[str, float] = {
            "chunks": len(bounds),
            "chunks_swept": 0,
            "chunk_words": cw,
            "prefetch": self._stream["prefetch"],
            # Resident bound: the chunk being swept + the prefetch
            # window + the one the worker may have started before the
            # consumer released its predecessor.
            "ring": self._stream["prefetch"] + 2,
            "resumed_chunk": start_ci,
        }
        # Under the lock even though no ring worker exists yet: a
        # bare reset here would race a straggling release if runs ever
        # overlap, and the guard is what graftrace pins (PERF.md §26).
        with self._stream_lock:
            self._stream_resident = 0
            self._stream_peak = 0
            self._stream_chunk_max = 0
        if start_ci >= len(bounds):
            return superstep_stats, stream
        compiler = ChunkCompiler(
            lambda ci, lo, hi: self._compile_chunk(kind, ci, lo, hi),
            bounds, start=start_ci, prefetch=self._stream["prefetch"],
        )
        t_drive0: List[Optional[float]] = [None]

        def drive_chunk(chunk) -> "Iterator[None]":
            if t_drive0[0] is None:
                t_drive0[0] = time.monotonic()
            w = state.cursor.word
            local = (
                SweepCursor(w - chunk.lo, state.cursor.rank)
                if chunk.lo <= w < chunk.hi
                else SweepCursor(0, 0)
            )
            sstats = (yield from drive_region(chunk, local)) or {}
            # Per-chunk accumulation rides the SAME key semantics the
            # bucketed and multihost mergers use (PERF.md §21a) — a new
            # max-semantics key added to the spec cannot silently sum
            # here while maxing there.
            superstep_stats.update(
                telemetry.SUPERSTEP_MERGE.merge([superstep_stats, sstats])
            )
            # Fallback words at the chunk's tail are due before the ring
            # advances; the cursor lands exactly on the next chunk's lo,
            # and the checkpoint remembers which chunk was active.
            self._flush_fallback_until(
                chunk.hi, state, fallback_candidate, prefetch
            )
            state.cursor = SweepCursor(chunk.hi, 0)
            state.stream = {"chunk": chunk.index, "chunk_words": cw}
            self._report_stream_position(state)
            stream["chunks_swept"] += 1

        try:
            yield from self._sweep_chunks(compiler, drive_chunk)
        finally:
            compiler.close()
        t_end = time.monotonic()
        overlap = 0.0
        if t_drive0[0] is not None:
            for a, b in compiler.windows:
                overlap += max(0.0, min(b, t_end) - max(a, t_drive0[0]))
        wall = compiler.compile_wall_s
        first = (
            compiler.windows[0][1] - compiler.windows[0][0]
            if compiler.windows else 0.0
        )
        stream.update({
            "compile_wall_s": wall,
            "first_chunk_compile_s": first,
            "compile_overlap_s": overlap,
            # Chunk 0 compiles before anything can overlap it (that IS
            # time-to-first-candidate); the steady ratio excludes it.
            "overlap_ratio": (overlap / wall) if wall > 0 else 0.0,
            "steady_overlap_ratio": (
                overlap / (wall - first) if wall - first > 0 else 0.0
            ),
            "ttfc_s": (
                self._ttfc[0] - self._run_t0
                if self._ttfc[0] is not None else 0.0
            ),
            "peak_resident_plan_bytes": self._stream_peak,
            "chunk_bytes_max": self._stream_chunk_max,
        })
        return superstep_stats, stream

    def _report_stream_position(self, state: CheckpointState) -> None:
        """Surface ``CheckpointState.stream`` (the active chunk marker)
        in the progress JSON: resumed streaming sweeps — and live ones —
        report their chunk position, not just the global cursor.  A
        sweep running the WHOLE-dictionary path reports nothing: a
        streaming checkpoint's marker is stale there (the run nulls
        it), and chunk numbering under a different chunk size would be
        somebody else's anyway."""
        if self._stream is None:
            return
        set_stream = getattr(self.config.progress, "set_stream", None)
        if set_stream is not None and state.stream is not None:
            set_stream(state.stream)

    # ------------------------------------------------------------------
    # Candidates mode (reference-compatible stdout surface)
    # ------------------------------------------------------------------

    def run_candidates(
        self,
        writer: CandidateWriter,
        *,
        resume: bool = True,
        state: "Optional[CheckpointState]" = None,
    ) -> SweepResult:
        """Stream every candidate to ``writer`` in word order (in-word order
        is variant-rank order; per-word multiset parity with the oracle).

        Resume is at-least-once: candidates written between the last
        checkpoint and a crash are re-emitted on resume (tune the window
        with ``checkpoint_every_s``); crack mode has no such duplication —
        hits are keyed by (word, rank) in the checkpoint itself.  The
        implementation is :meth:`candidates_machine`, exhausted."""
        return _exhaust(self.candidates_machine(writer, resume=resume,
                                                state=state))

    def candidates_machine(
        self,
        writer: CandidateWriter,
        *,
        resume: bool = True,
        state: "Optional[CheckpointState]" = None,
    ) -> "Generator[None, None, SweepResult]":
        """Candidates mode in the machine protocol (PERF.md §20): the
        crack machine's twin — yields at every consumed launch batch,
        returns the :class:`SweepResult`; see :meth:`crack_machine`."""
        from ..ops.packing import schema_cache_stats

        cfg = self.config
        if cfg.pod is not None:
            # Candidates mode streams EVERY candidate to one writer; a
            # pod stripe would emit an interleaved subset with no merge
            # discipline.  Giant-job striping is a crack-mode contract.
            raise RuntimeError(
                "pod giant-job mode is crack-only; candidates mode "
                "streams the full keyspace from one process"
            )
        state, resumed = self._load_state(resume, state)
        self.active_state = state
        sc0 = schema_cache_stats()
        if cfg.progress is not None:
            cfg.progress.seed_emitted(state.n_emitted)
        self._report_stream_position(state)

        def fallback_candidate(row: int, i: int, cand: bytes) -> None:
            writer.emit(cand)

        t0 = time.monotonic()
        self._run_t0 = t0
        self._ttfc = [None]
        last_ckpt = [t0]
        prefetch = self._make_prefetcher(state)
        stream_stats: Dict[str, float] = {}
        try:
            if self._stream is not None:
                _sstats, stream_stats = yield from self._run_stream(
                    "candidates", state,
                    lambda chunk, local: self._candidates_plan_region(
                        chunk.plan, chunk.lo, chunk.payload, state, local,
                        writer, fallback_candidate, prefetch, last_ckpt,
                    ),
                    fallback_candidate, prefetch,
                )
            else:
                launch, n_devices, mesh, step_ctx = self._make_launch(
                    "candidates", self.plan
                )
                payload = dict(launch=launch, n_devices=n_devices,
                               mesh=mesh, step_ctx=step_ctx)
                state.stream = None  # see run_crack
                yield from self._candidates_plan_region(
                    self.plan, 0, payload, state, state.cursor,
                    writer, fallback_candidate, prefetch, last_ckpt,
                )
            self._flush_fallback_until(
                self.n_words, state, fallback_candidate, prefetch
            )
        finally:
            if prefetch is not None:
                prefetch.close()
            state.wall_s += time.monotonic() - t0  # see crack_machine
        state.cursor = SweepCursor(word=self.n_words, rank=0)
        self._maybe_checkpoint(state, last_ckpt, force=True,
                               before_save=writer.flush)
        if cfg.progress:
            cfg.progress.final(
                words_done=self.n_words, emitted=state.n_emitted, hits=0
            )
        return SweepResult(
            n_emitted=state.n_emitted,
            n_hits=0,
            hits=[],
            words_done=self.n_words,
            resumed=resumed,
            wall_s=state.wall_s,
            routing=dict(self.routing),
            stream=stream_stats,
            geometry=self._geometry_provenance(),
            geometry_source=self.config.geometry_source,
            schema_cache=_stats_delta(sc0, schema_cache_stats()),
        )

    def _candidates_plan_region(
        self, plan, row_base: int, payload: dict, state: CheckpointState,
        local_cursor: SweepCursor, writer: CandidateWriter,
        fallback_candidate: Callable, prefetch, last_ckpt: List[float],
    ) -> "Iterator[None]":
        """Stream one compiled plan region's candidates to ``writer`` —
        the whole dictionary (``row_base`` 0) or one streaming chunk.
        The region twin of :meth:`_crack_plan_region`: local cursors in,
        global state out, one machine-protocol yield per consumed
        launch (PERF.md §20)."""
        cfg = self.config
        launch, n_devices = payload["launch"], payload["n_devices"]
        mesh = payload["mesh"]
        for segments, out, cursor in self._launches(
            local_cursor, launch, n_devices=n_devices, mesh=mesh, plan=plan
        ):
            cand, clen, _, emit = out
            cand = np.asarray(cand)
            clen = np.asarray(clen).astype(np.int32)
            emit = np.asarray(emit)
            if self._ttfc[0] is None:
                self._ttfc[0] = time.monotonic()
            # Segments in cursor order; within each device's lane slice,
            # walk blocks in order — fallback words interleave at their
            # word position. Within a fallback-free run of blocks, the
            # write is one vectorized ragged flatten (newline planted at
            # clen).
            for batch, seg_lo, _seg_hi in segments:
                nb = len(batch.count)
                b0 = 0
                while b0 < nb:
                    w0 = row_base + int(batch.word[b0])
                    self._flush_fallback_until(
                        w0, state, fallback_candidate, prefetch
                    )
                    b1 = b0
                    next_fb = (
                        self.fallback_rows[state.fallback_done]
                        if state.fallback_done < len(self.fallback_rows)
                        else self.n_words
                    )
                    while (
                        b1 < nb
                        and row_base + int(batch.word[b1]) <= next_fb
                    ):
                        b1 += 1
                    lo = seg_lo + int(batch.offset[b0])
                    hi = seg_lo + int(
                        batch.offset[b1 - 1] + batch.count[b1 - 1]
                    )
                    n = self._write_lane_range(
                        writer, cand, clen, emit, lo, hi
                    )
                    state.n_emitted += n
                    b0 = b1
            state.cursor = SweepCursor(row_base + cursor.word, cursor.rank)
            # Span record at the consumed launch boundary (candidates
            # mode's fetch barrier, PERF.md §21).
            self.timeline.record_fetch(kind="launch", launches=1)
            self._maybe_checkpoint(
                state, last_ckpt, before_save=writer.flush
            )
            if cfg.progress:
                cfg.progress.update(
                    words_done=row_base + cursor.word,
                    emitted=state.n_emitted,
                    hits=0,
                )
            yield

    @staticmethod
    def _write_lane_range(
        writer: CandidateWriter,
        cand: np.ndarray,
        clen: np.ndarray,
        emit: np.ndarray,
        lo: int,
        hi: int,
    ) -> int:
        """Write emitted lanes in [lo, hi) as candidate+\\n lines with one
        vectorized ragged flatten; returns the number of lines written."""
        sel = emit[lo:hi]
        if not sel.any():
            return 0
        rows = cand[lo:hi][sel]
        lens = clen[lo:hi][sel]
        n, w = rows.shape
        if writer.hex_unsafe:
            # Rare path: per-candidate inspection needed; emit row by row.
            for i in range(n):
                writer.emit(bytes(rows[i, : lens[i]]))
            return n
        buf = np.empty((n, w + 1), dtype=np.uint8)
        buf[:, :w] = rows
        buf[np.arange(n), lens] = 0x0A  # newline at each row's length
        mask = np.arange(w + 1)[None, :] <= lens[:, None]
        writer.write_block(buf[mask].tobytes(), n)
        return n
